"""tensor_transform — elementwise op engine.

Reference parity: gst/nnstreamer/elements/gsttensor_transform.c (2141 LoC;
modes dimchg/typecast/arithmetic/transpose/stand/clamp :181-199, op-chain
parser :117-122, Orc SIMD kernels). TPU-first redesign: a transform
compiles its option string **once** into a chain of array ops that runs
either

- host-side via numpy (standalone use), or
- traced into an adjacent ``tensor_filter``'s XLA computation (fusion —
  the SIMD-kernel analog is simply XLA fusing these into the model's
  HLO; see elements/filter.py which collects neighbouring transforms).

Option syntax (reference-compatible):
  mode=typecast    option=float32
  mode=arithmetic  option=typecast:float32,add:-127.5,div:127.5
                   (per-channel values ':'-separated: add:1:2:3)
  mode=transpose   option=1:0:2:3   (reference innermost-first indices)
  mode=dimchg      option=0:2       (move reference dim 0 to position 2)
  mode=clamp       option=min:max
  mode=stand       option=default|dc-average[:per-channel]
"""

from __future__ import annotations

from dataclasses import replace
from typing import Callable, List, Sequence, Tuple

import numpy as np

from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import Element, Emission, PropDef, StreamSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

#: one compiled step: (fn(xp, array) -> array, out_info_fn(TensorInfo) -> TensorInfo)
Step = Tuple[Callable, Callable]

MODES = ("typecast", "arithmetic", "transpose", "dimchg", "clamp", "stand")
_ARITH_OPS = ("add", "sub", "mul", "div", "typecast")


def _ref_perm_to_row_major(perm_ref: Sequence[int], rank: int) -> Tuple[int, ...]:
    """Reference transpose indices (innermost-first) → row-major axes perm."""
    return tuple(rank - 1 - perm_ref[rank - 1 - k] for k in range(rank))


class TransformProgram:
    """A compiled option string: a pure function over one array, plus the
    static shape/dtype transfer used at negotiation time."""

    def __init__(self, mode: str, option: str):
        if mode not in MODES:
            raise PipelineError(
                f"unknown tensor_transform mode {mode!r}; valid: {MODES}"
            )
        self.mode = mode
        self.option = option or ""
        self._steps: List[Step] = self._compile()

    # -- public ------------------------------------------------------------
    def apply(self, xp, arr):
        """Run on one array with module `xp` (numpy or jax.numpy)."""
        for fn, _ in self._steps:
            arr = fn(xp, arr)
        return arr

    def out_info(self, info: TensorInfo) -> TensorInfo:
        for _, transfer in self._steps:
            info = transfer(info)
        return info

    # -- compilation -------------------------------------------------------
    def _compile(self) -> List[Step]:
        mode, option = self.mode, self.option
        if mode == "typecast":
            return [self._step_typecast(option)]
        if mode == "arithmetic":
            return self._compile_arith_chain(option)
        if mode == "transpose":
            return [self._step_transpose(option)]
        if mode == "dimchg":
            return [self._step_dimchg(option)]
        if mode == "clamp":
            return [self._step_clamp(option)]
        if mode == "stand":
            return [self._step_stand(option)]
        raise AssertionError(mode)

    def _parse_dtype(self, s: str) -> DType:
        try:
            return DType.from_name(s)
        except ValueError as e:
            raise PipelineError(f"tensor_transform: {e}") from None

    def _step_typecast(self, option: str) -> Step:
        dt = self._parse_dtype(option)
        np_dt = dt.np_dtype

        def fn(xp, a):
            return a.astype(np_dt)

        return fn, lambda info: replace(info, dtype=dt)

    def _compile_arith_chain(self, option: str) -> List[Step]:
        if not option:
            raise PipelineError(
                "tensor_transform mode=arithmetic requires option="
                "<op:value[,op:value...]>, e.g. "
                "option=typecast:float32,add:-127.5,div:127.5"
            )
        steps: List[Step] = []
        for chunk in option.split(","):
            op, _, valstr = chunk.strip().partition(":")
            if op not in _ARITH_OPS:
                raise PipelineError(
                    f"unknown arithmetic op {op!r} in option {option!r}; "
                    f"valid ops: {_ARITH_OPS}"
                )
            if op == "typecast":
                steps.append(self._step_typecast(valstr))
                continue
            try:
                vals = [float(v) for v in valstr.split(":")]
            except ValueError:
                raise PipelineError(
                    f"bad operand {valstr!r} for arithmetic op {op!r}"
                ) from None
            operand = vals[0] if len(vals) == 1 else np.asarray(vals, np.float32)
            # Whether this op promotes integer inputs to float32. The
            # declared spec and the runtime result are forced to agree
            # (numpy NEP-50 / jnp weak-typing differences are cast away):
            # div or a non-integral operand promotes; otherwise the input
            # dtype is preserved (reference arithmetic semantics: ops run
            # in the tensor's own type unless a typecast is chained).
            if isinstance(operand, float):
                promotes = (op == "div") or not operand.is_integer()
            else:
                promotes = (op == "div") or bool(
                    np.any(operand != np.round(operand))
                )

            def fn(xp, a, op=op, operand=operand, promotes=promotes):
                in_dt = a.dtype
                is_float = np.issubdtype(np.dtype(str(in_dt)), np.floating) or (
                    str(in_dt) == "bfloat16"
                )
                if promotes and not is_float:
                    a = a.astype(np.float32)
                operand_c = operand
                if not isinstance(operand, float):
                    operand_c = operand.astype(a.dtype)
                # per-channel vectors broadcast along the last axis
                if op == "add":
                    r = a + operand_c
                elif op == "sub":
                    r = a - operand_c
                elif op == "mul":
                    r = a * operand_c
                else:
                    r = a / operand_c
                # pin the result to the declared dtype on every path
                return r.astype(a.dtype)

            def transfer(info, promotes=promotes):
                is_float = info.dtype in (DType.FLOAT64, DType.FLOAT32,
                                          DType.FLOAT16, DType.BFLOAT16)
                if promotes and not is_float:
                    return replace(info, dtype=DType.FLOAT32)
                return info

            steps.append((fn, transfer))
        return steps

    def _step_transpose(self, option: str) -> Step:
        try:
            perm_ref = [int(v) for v in option.split(":")]
        except ValueError:
            raise PipelineError(
                f"tensor_transform mode=transpose needs option=i:j:k:… "
                f"(reference innermost-first indices), got {option!r}"
            ) from None

        def fn(xp, a):
            return xp.transpose(a, _ref_perm_to_row_major(perm_ref, a.ndim))

        def transfer(info: TensorInfo) -> TensorInfo:
            rank = len(info.shape)
            if sorted(perm_ref) != list(range(rank)):
                raise PipelineError(
                    f"transpose option {option!r} is not a permutation of "
                    f"0..{rank - 1} for input shape {info.shape}"
                )
            perm = _ref_perm_to_row_major(perm_ref, rank)
            return replace(info, shape=tuple(info.shape[p] for p in perm))

        return fn, transfer

    def _step_dimchg(self, option: str) -> Step:
        try:
            frm, to = (int(v) for v in option.split(":"))
        except ValueError:
            raise PipelineError(
                f"tensor_transform mode=dimchg needs option=from:to "
                f"(reference dim indices), got {option!r}"
            ) from None

        def fn(xp, a):
            rank = a.ndim
            return xp.moveaxis(a, rank - 1 - frm, rank - 1 - to)

        def transfer(info: TensorInfo) -> TensorInfo:
            rank = len(info.shape)
            if not (0 <= frm < rank and 0 <= to < rank):
                raise PipelineError(
                    f"dimchg option {option!r} out of range for shape "
                    f"{info.shape}"
                )
            shape = list(info.shape)
            v = shape.pop(rank - 1 - frm)
            shape.insert(rank - 1 - to, v)
            return replace(info, shape=tuple(shape))

        return fn, transfer

    def _step_clamp(self, option: str) -> Step:
        try:
            lo, hi = (float(v) for v in option.split(":"))
        except ValueError:
            raise PipelineError(
                f"tensor_transform mode=clamp needs option=min:max, got "
                f"{option!r}"
            ) from None
        if lo > hi:
            raise PipelineError(f"clamp min {lo} > max {hi}")

        def fn(xp, a):
            return xp.clip(a, lo, hi)

        return fn, lambda info: info

    def _step_stand(self, option: str) -> Step:
        parts = (option or "default").split(":")
        kind = parts[0] or "default"
        per_channel = len(parts) > 1 and parts[1] == "per-channel"
        if kind not in ("default", "dc-average"):
            raise PipelineError(
                f"tensor_transform mode=stand supports "
                f"default|dc-average[:per-channel], got {option!r}"
            )

        def fn(xp, a):
            f = a.astype(np.float32)
            axes = tuple(range(f.ndim - 1)) if per_channel else None
            mean = f.mean(axis=axes, keepdims=per_channel)
            if kind == "dc-average":
                return f - mean
            std = f.std(axis=axes, keepdims=per_channel)
            return (f - mean) / (std + 1e-10)

        return fn, lambda info: replace(info, dtype=DType.FLOAT32)


@register_element("tensor_transform")
class TensorTransform(Element):
    ELEMENT_NAME = "tensor_transform"
    PROPS = {
        "mode": PropDef(str, None, "transform mode: " + "|".join(MODES)),
        "option": PropDef(str, "", "mode-specific option string"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if not self.props["mode"]:
            raise PipelineError(
                f"tensor_transform ({self.name}) requires mode=<"
                + "|".join(MODES) + ">"
            )
        self.program = TransformProgram(self.props["mode"], self.props["option"])

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0])
        try:
            infos = tuple(self.program.out_info(t) for t in spec.tensors)
        except PipelineError as e:
            self.fail_negotiation(str(e))
        return [replace(spec, tensors=infos)]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        xp = _array_module(buf)
        out = tuple(self.program.apply(xp, t) for t in buf.tensors)
        return [(0, buf.with_tensors(out))]

    # fusion hook: elements/filter.py calls this to absorb the program
    def as_elementwise(self):
        program = self.program

        def apply_all(tensors):
            import jax.numpy as jnp

            return tuple(program.apply(jnp, t) for t in tensors)

        return apply_all


def _array_module(buf: TensorBuffer):
    if buf.on_device:
        import jax.numpy as jnp

        return jnp
    return np


@register_element("tensor_resize")
class TensorResize(Element):
    """Spatial resize — the flexible→static bridge (SURVEY.md §7 hard
    part d).

    A FLEXIBLE stream (e.g. tensor_crop regions, per-buffer shapes) maps
    onto XLA's static-shape world by resizing every region to one fixed
    (H, W): `tensor_crop ! tensor_resize size=224:224 channels=3 !
    tensor_filter ...` runs data-driven ROI inference with exactly one
    compiled program — the reference can only do this by bouncing back
    to media and using GStreamer videoscale.

    STATIC input: per-tensor resize, same tensor count. FLEXIBLE input
    (requires channels=): each region becomes its own STATIC (H, W, C)
    buffer downstream (meta["region_index"]/["num_regions"] record the
    grouping).
    """

    ELEMENT_NAME = "tensor_resize"
    PROPS = {
        "size": PropDef(str, None, "output H:W"),
        "method": PropDef(str, "nearest", "nearest|bilinear"),
        "channels": PropDef(int, 0, "required for FLEXIBLE input"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if not self.props["size"]:
            raise PipelineError(
                f"tensor_resize ({self.name}) requires size=H:W")
        try:
            self._h, self._w = (int(v) for v in self.props["size"].split(":"))
        except ValueError:
            raise PipelineError(
                f"tensor_resize size must be H:W, got "
                f"{self.props['size']!r}") from None
        if self.props["method"] not in ("nearest", "bilinear"):
            raise PipelineError(
                f"tensor_resize method must be nearest|bilinear, got "
                f"{self.props['method']!r}")
        self._flexible_in = False

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        from nnstreamer_tpu.tensor.info import TensorFormat

        spec = self.expect_tensors(in_specs[0])
        if spec.format == TensorFormat.FLEXIBLE:
            c = self.props["channels"]
            if not c:
                self.fail_negotiation(
                    "FLEXIBLE input needs channels=<C> to declare the "
                    "static output type (each region becomes one "
                    "(H, W, C) buffer)")
            self._flexible_in = True
            return [TensorsSpec.of(
                TensorInfo((self._h, self._w, c), DType.UINT8
                           if not spec.tensors else spec.tensors[0].dtype),
                rate=spec.rate)]
        infos = []
        for t in spec.tensors:
            if len(t.shape) < 2:
                self.fail_negotiation(
                    f"cannot resize rank-{len(t.shape)} tensor {t}; need "
                    f"spatial (…, H, W, C) or (H, W) layout")
            shape = list(t.shape)
            h_ax = len(shape) - 3 if len(shape) >= 3 else 0
            shape[h_ax], shape[h_ax + 1] = self._h, self._w
            infos.append(replace(t, shape=tuple(shape)))
        return [replace(spec, tensors=tuple(infos))]

    def _resize(self, arr):
        h_ax = arr.ndim - 3 if arr.ndim >= 3 else 0
        in_h, in_w = arr.shape[h_ax], arr.shape[h_ax + 1]
        if (in_h, in_w) == (self._h, self._w):
            return np.asarray(arr)
        if self.props["method"] == "bilinear":
            import jax.image
            import jax.numpy as jnp

            shape = list(arr.shape)
            shape[h_ax], shape[h_ax + 1] = self._h, self._w
            out = jax.image.resize(jnp.asarray(arr).astype(jnp.float32),
                                   shape, method="bilinear")
            return np.asarray(out).astype(np.asarray(arr).dtype)
        a = np.asarray(arr)
        ys = np.clip(((np.arange(self._h) + 0.5) * in_h / self._h)
                     .astype(int), 0, in_h - 1)
        xs = np.clip(((np.arange(self._w) + 0.5) * in_w / self._w)
                     .astype(int), 0, in_w - 1)
        return np.take(np.take(a, ys, axis=h_ax), xs, axis=h_ax + 1)

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        from nnstreamer_tpu.tensor.info import TensorFormat

        if not self._flexible_in:
            return [(0, buf.with_tensors(
                tuple(self._resize(t) for t in buf.tensors)))]
        out: List[Emission] = []
        c = self.props["channels"]
        n = buf.num_tensors
        for i, t in enumerate(buf.tensors):
            a = np.asarray(t)
            if a.ndim == 2:
                a = a[..., None]
            if a.ndim != 3 or a.shape[-1] != c:
                raise PipelineError(
                    f"tensor_resize {self.name}: region {i} has shape "
                    f"{np.asarray(t).shape}, expected (h, w, {c})")
            region = self._resize(a)
            out.append((0, TensorBuffer(
                tensors=(region,), pts=buf.pts,
                format=TensorFormat.STATIC,
                meta={**buf.meta, "region_index": i, "num_regions": n})))
        return out
