"""tensor_repo — out-of-band buffer repository for feedback loops.

Reference parity: gsttensor_reposink.c / gsttensor_reposrc.c /
gsttensor_repo.c — a global slot-indexed repository passing buffers
outside the link graph, the sanctioned way to build cycles (RNN/LSTM
state, tests/nnstreamer_repo_{rnn,lstm}). The pipeline DAG stays acyclic;
the repo closes the loop.

Semantics: reposink writes its input buffer into slot N; reposrc reads
slot N, emitting one buffer per read. reposrc must produce the *first*
buffer itself (the loop has no data yet): zeros shaped by dims/types —
the recurrent-state initializer.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from fractions import Fraction
from typing import Dict, Iterator

import numpy as np

from nnstreamer_tpu.core.errors import PipelineError, StreamError
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import (
    Element,
    PropDef,
    SinkElement,
    SourceElement,
    StreamSpec)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec


class _Repo:
    """Global slot table (gsttensor_repo.c analog)."""

    def __init__(self):
        self._slots: Dict[int, _queue.Queue] = {}
        self._lock = threading.Lock()

    def slot(self, idx: int) -> _queue.Queue:
        with self._lock:
            if idx not in self._slots:
                self._slots[idx] = _queue.Queue(maxsize=16)
            return self._slots[idx]

    def reset(self) -> None:
        with self._lock:
            self._slots.clear()


REPO = _Repo()


@register_element("tensor_repo_sink")
class TensorRepoSink(SinkElement):
    ELEMENT_NAME = "tensor_repo_sink"
    PROPS = {
        "slot": PropDef(int, 0, "repository slot index"),
        "put_timeout": PropDef(float, 10.0,
                               "seconds to wait for a free slot entry"),
    }

    def render(self, buf: TensorBuffer) -> None:
        slot = self.props["slot"]
        q = REPO.slot(slot)
        # bounded, stop-aware wait: a pipeline tearing down (e.g. another
        # element failed) must not leave this worker parked the full
        # timeout on a slot nobody will ever drain
        deadline = time.monotonic() + self.props["put_timeout"]
        while True:
            try:
                q.put(buf, timeout=0.2)
                return
            except _queue.Full:
                if self._stop_evt is not None and self._stop_evt.is_set():
                    raise StreamError(
                        f"tensor_repo_sink {self.name}: pipeline stopping "
                        f"while waiting on full repo slot {slot}"
                    ) from None
                if time.monotonic() >= deadline:
                    raise StreamError(
                        f"tensor_repo_sink {self.name}: repo slot {slot} "
                        f"still full after {self.props['put_timeout']:.1f}s "
                        f"— is the matching tensor_repo_src consuming, and "
                        f"is the feedback loop making progress?"
                    ) from None

    def stop(self) -> None:
        # wake a blocked reposrc at teardown
        try:
            REPO.slot(self.props["slot"]).put_nowait(None)
        except _queue.Full:
            pass


@register_element("tensor_repo_src")
class TensorRepoSrc(SourceElement):
    """Reads slot N. Emits `initial` zero-buffers first to prime the loop,
    then one buffer per reposink write, until `count` total buffers."""

    ELEMENT_NAME = "tensor_repo_src"
    PROPS = {
        "slot": PropDef(int, 0),
        "dims": PropDef(str, None, "state tensor dims (zeros initializer)"),
        "types": PropDef(str, "float32"),
        "initial": PropDef(int, 1, "number of priming zero-buffers"),
        "count": PropDef(int, 0, "total buffers to emit; 0 = until stopped"),
        "rate": PropDef(str, "0/1"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._stop = threading.Event()

    def start(self) -> None:
        # purge stale buffers / teardown sentinels a previous run left in
        # this slot, so every pipeline run starts from a clean loop state
        q = REPO.slot(self.props["slot"])
        while True:
            try:
                q.get_nowait()
            except _queue.Empty:
                break

    def output_spec(self) -> StreamSpec:
        if not self.props["dims"]:
            raise PipelineError(
                f"tensor_repo_src {self.name}: dims= is required (shapes "
                f"the priming zero-state)"
            )
        return TensorsSpec.from_strings(
            self.props["dims"], self.props["types"],
            rate=Fraction(self.props["rate"]))

    def interrupt(self) -> None:
        self._stop.set()
        try:
            REPO.slot(self.props["slot"]).put_nowait(None)
        except _queue.Full:
            pass

    def generate(self) -> Iterator[TensorBuffer]:
        spec: TensorsSpec = self.out_specs[0]
        emitted = 0
        count = self.props["count"]
        for _ in range(self.props["initial"]):
            zeros = tuple(np.zeros(t.shape, t.dtype.np_dtype)
                          for t in spec.tensors)
            yield TensorBuffer(tensors=zeros, pts=0)
            emitted += 1
            if count and emitted >= count:
                return
        q = REPO.slot(self.props["slot"])
        while not self._stop.is_set():
            item = q.get()
            if item is None:
                return
            yield item
            emitted += 1
            if count and emitted >= count:
                return
