"""tensor_aggregator — frame aggregation / sliding windows.

Reference parity: gsttensor_aggregator.c (properties frames-in/out/flush
and the concat dim, :171-200; GstAdapter ring). This is the reference's
"sequence length" mechanism (SURVEY.md §5.7): the temporal-window
primitive that feeds windowed models. Output framerate scales by
frames_out/frames_in... actually by the flush cadence: one output per
`frames_flush` inputs (default frames_out).

TPU-first: windows are assembled with np/jnp stacking on whichever device
the frames live; the window dim is the concat axis so a downstream filter
sees one static shape (no dynamic shapes under jit).
"""

from __future__ import annotations

from collections import deque
from fractions import Fraction
from typing import Deque, List, Sequence

from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.elements.routing import _xp
from nnstreamer_tpu.graph.pipeline import Element, Emission, PropDef, StreamSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec


@register_element("tensor_aggregator")
class TensorAggregator(Element):
    ELEMENT_NAME = "tensor_aggregator"
    PROPS = {
        "frames_in": PropDef(int, 1, "frames per incoming buffer along dim"),
        "frames_out": PropDef(int, 1, "frames per outgoing buffer (window)"),
        "frames_flush": PropDef(int, 0, "advance per output; 0 = frames_out "
                                        "(tumbling); < frames_out = sliding"),
        "frames_dim": PropDef(int, 0, "row-major axis that counts frames"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._ring: Deque = deque()
        self._axis = 0
        self._pending_flush = 0

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0])
        if spec.num_tensors != 1:
            self.fail_negotiation(
                f"tensor_aggregator windows a single-tensor stream; got "
                f"{spec.num_tensors} tensors (demux first)"
            )
        t = spec.tensors[0]
        fin, fout = self.props["frames_in"], self.props["frames_out"]
        self._axis = self.props["frames_dim"] % len(t.shape)
        if t.shape[self._axis] % max(1, fin) != 0:
            self.fail_negotiation(
                f"frames_in={fin} does not divide axis {self._axis} size "
                f"{t.shape[self._axis]}"
            )
        flush = self.props["frames_flush"] or fout
        if flush <= 0 or fout <= 0 or fin <= 0:
            self.fail_negotiation("frames_in/out/flush must be positive")
        out_shape = tuple(
            (v // fin) * fout if d == self._axis else v
            for d, v in enumerate(t.shape)
        )
        rate = spec.rate * Fraction(fin, flush) if spec.rate else spec.rate
        return [TensorsSpec.of(TensorInfo(out_shape, t.dtype), rate=rate)]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        fin = self.props["frames_in"]
        fout = self.props["frames_out"]
        flush = self.props["frames_flush"] or fout
        t = buf.tensors[0]
        # slice incoming buffer into single frames along the axis
        per = t.shape[self._axis] // fin
        for i in range(fin):
            sl = [slice(None)] * t.ndim
            sl[self._axis] = slice(i * per, (i + 1) * per)
            self._ring.append((t[tuple(sl)], buf.pts))
        out: List[Emission] = []
        while len(self._ring) >= fout + self._pending_flush:
            if self._pending_flush:
                for _ in range(self._pending_flush):
                    self._ring.popleft()
                self._pending_flush = 0
            if len(self._ring) < fout:
                break
            window = list(self._ring)[:fout]
            arrays = [w[0] for w in window]
            xp = _xp(arrays)
            merged = xp.concatenate(arrays, axis=self._axis)
            out.append((0, TensorBuffer(tensors=(merged,), pts=window[-1][1])))
            self._pending_flush = flush
        return out
