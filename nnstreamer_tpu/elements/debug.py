"""tensor_debug — passthrough stream introspection.

Reference parity: gsttensor_debug.c (:29) — prints caps/meta of passing
buffers. Here it logs spec + per-buffer summary (shape/dtype/pts/device
residency) through the framework logger, with `output=console|log` and a
`capture` deque for tests (bounded by `capture-limit` so a long-running
pipeline can't grow it without bound).
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Sequence

import numpy as np

from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import Element, Emission, PropDef, StreamSpec, prop_bool
from nnstreamer_tpu.tensor.buffer import TensorBuffer

log = get_logger("elements.debug")


@register_element("tensor_debug")
class TensorDebug(Element):
    ELEMENT_NAME = "tensor_debug"
    PROPS = {
        "output": PropDef(str, "log", "log|console"),
        "verbose": PropDef(prop_bool, False, "include value stats"),
        "capture": PropDef(prop_bool, False, "record lines in .lines"),
        "capture_limit": PropDef(int, 1000,
                                 "max captured lines kept (oldest dropped)"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        limit = max(1, int(self.props["capture_limit"]))
        self.lines: Deque[str] = deque(maxlen=limit)
        self.buffers_seen = 0
        self._captured_total = 0

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        self._say(f"{self.name}: negotiated {in_specs[0]}")
        return [in_specs[0]]

    def _say(self, line: str) -> None:
        if self.props["capture"]:
            self.lines.append(line)
            self._captured_total += 1
        if self.props["output"] == "console":
            print(line)
        else:
            log.info("%s", line)

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        self.buffers_seen += 1
        desc = repr(buf)
        if self.props["verbose"]:
            stats = []
            for t in buf.tensors:
                a = np.asarray(t)
                if a.dtype.kind in "fiu" and a.size:
                    stats.append(f"min={a.min():.4g} max={a.max():.4g} "
                                 f"mean={a.mean():.4g}")
                else:
                    stats.append("-")
            desc += " [" + "; ".join(stats) + "]"
        self._say(f"{self.name}: {desc}")
        return [(0, buf)]

    def extra_stats(self) -> dict:
        return {
            "buffers_seen": self.buffers_seen,
            "captured_lines": len(self.lines),
            "capture_dropped": self._captured_total - len(self.lines),
        }
