"""Source elements (reference: videotestsrc/appsrc/filesrc from GStreamer
core, plus tensor_src_* — the framework needs its own since there is no
GStreamer underneath).
"""

from __future__ import annotations

import queue as _queue
import time
from fractions import Fraction
from typing import Iterator, List

import numpy as np

from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.media import VideoSpec
from nnstreamer_tpu.graph.pipeline import PropDef, SourceElement, StreamSpec, prop_bool
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec

NS = 1_000_000_000


@register_element("videotestsrc")
class VideoTestSrc(SourceElement):
    """Deterministic video pattern generator (videotestsrc analog).

    Patterns: `gradient` (default; per-frame-varying diagonal ramp),
    `random` (seeded uniform noise), `solid` (option: solid-color).
    Deterministic given (pattern, seed) so golden tests are exact.
    """

    ELEMENT_NAME = "videotestsrc"
    PROPS = {
        "width": PropDef(int, 224),
        "height": PropDef(int, 224),
        "format": PropDef(str, "RGB"),
        "num_buffers": PropDef(int, 10, "frames to emit before EOS"),
        "framerate": PropDef(str, "30/1"),
        "pattern": PropDef(str, "gradient", "gradient|random|solid"),
        "solid_color": PropDef(int, 127),
        "seed": PropDef(int, 0),
        "is_live": PropDef(prop_bool, False, "pace emission to framerate"),
    }

    def output_spec(self) -> StreamSpec:
        rate = Fraction(self.props["framerate"].replace("/", "/"))
        return VideoSpec(
            width=self.props["width"],
            height=self.props["height"],
            format=self.props["format"],
            rate=rate,
        )

    def generate(self) -> Iterator[TensorBuffer]:
        spec: VideoSpec = self.out_specs[0]
        h, w, c = spec.frame_shape
        rate = spec.rate or Fraction(30, 1)
        frame_ns = int(NS / rate) if rate else 0
        pattern = self.props["pattern"]
        rng = np.random.default_rng(self.props["seed"])
        next_qos_pts = 0
        for i in range(self.props["num_buffers"]):
            pts = i * frame_ns
            # a live source models a camera: the frame interval elapses
            # whether or not the frame is kept, so pace BEFORE the QoS
            # skip or throttled live capture runs ahead of real time
            if self.props["is_live"] and frame_ns:
                time.sleep(frame_ns / NS)
            # downstream throttle QoS (tensor_rate): skip BEFORE computing
            # the frame — the whole point of the upstream event
            qos = self.qos_min_interval_ns
            if qos and pts < next_qos_pts:
                self.qos_skipped += 1
                continue
            if qos:
                next_qos_pts = pts + qos
            if pattern == "random":
                frame = rng.integers(0, 256, size=(h, w, c), dtype=np.uint8)
            elif pattern == "solid":
                frame = np.full((h, w, c), self.props["solid_color"], np.uint8)
            elif pattern == "gradient":
                yy, xx = np.mgrid[0:h, 0:w]
                base = (xx + yy + 7 * i) % 256
                frame = np.stack(
                    [(base + 85 * ch) % 256 for ch in range(c)], axis=-1
                ).astype(np.uint8)
            else:
                raise PipelineError(
                    f"videotestsrc pattern {pattern!r} unknown "
                    f"(gradient|random|solid)"
                )
            yield TensorBuffer.of(frame, pts=pts,
                                  duration=frame_ns or None)


@register_element("appsrc")
class AppSrc(SourceElement):
    """Programmatic ingress: the application pushes buffers (appsrc analog).

    Usage:
        src = AppSrc(spec=TensorsSpec...)   # or any MediaSpec
        src.push(buf); ...; src.end()
    In the DSL, give dims/types: `appsrc dims=3:4 types=float32`.
    """

    ELEMENT_NAME = "appsrc"
    PROPS = {
        "spec": PropDef(lambda s: s, None, "StreamSpec object (programmatic)"),
        "dims": PropDef(str, "", "tensor dims string, e.g. 3:224:224:1"),
        "types": PropDef(str, "float32"),
        "rate": PropDef(str, "0/1"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._q: "_queue.Queue" = _queue.Queue()
        self._closed = False

    def output_spec(self) -> StreamSpec:
        if self.props["spec"] is not None:
            return self.props["spec"]
        if self.props["dims"]:
            return TensorsSpec.from_strings(
                self.props["dims"], self.props["types"],
                rate=Fraction(self.props["rate"]),
            )
        raise PipelineError(
            f"appsrc ({self.name}) needs spec=<StreamSpec> (programmatic) "
            f"or dims=/types= properties"
        )

    def push(self, buf) -> None:
        if self._closed:
            raise PipelineError(f"appsrc {self.name}: push after end()")
        if isinstance(buf, np.ndarray):
            buf = TensorBuffer.of(buf)
        self._q.put(buf)

    def end(self) -> None:
        self._closed = True
        self._q.put(None)

    def interrupt(self) -> None:
        self._q.put(None)

    def generate(self) -> Iterator[TensorBuffer]:
        while True:
            item = self._q.get()
            if item is None:
                return
            yield item


@register_element("tensor_src")
class TensorSrc(SourceElement):
    """Emit a fixed iterable of arrays/buffers (test + replay source)."""

    ELEMENT_NAME = "tensor_src"
    PROPS = {
        "data": PropDef(lambda s: s, None, "iterable of arrays or buffers"),
        "spec": PropDef(lambda s: s, None, "TensorsSpec (else inferred)"),
        "rate": PropDef(str, "0/1"),
    }

    def output_spec(self) -> StreamSpec:
        if self.props["spec"] is not None:
            return self.props["spec"]
        data = self.props["data"]
        if not data:
            raise PipelineError(f"tensor_src ({self.name}) needs data= items")
        first = data[0]
        arrs = first.tensors if isinstance(first, TensorBuffer) else (first,)
        return TensorBuffer.of(*arrs).spec().with_rate(Fraction(self.props["rate"]))

    def generate(self) -> Iterator[TensorBuffer]:
        for i, item in enumerate(self.props["data"] or []):
            if isinstance(item, TensorBuffer):
                yield item
            else:
                yield TensorBuffer.of(item, pts=i)


@register_element("filesrc")
class FileSrc(SourceElement):
    """Replay frames from a file (filesrc + decodebin-lite analog).

    Formats by extension:
    - .npy  — one array; axis 0 indexes frames (shape[1:] per frame),
              unless frames-per-file=1, then the whole array is one frame
    - .npz  — arrays sorted by key, one frame each
    - .raw/.bin — raw bytes reshaped to dims/types per frame, repeated
              until the file is exhausted (the reference's raw filesrc +
              tensor_converter octet path)
    """

    ELEMENT_NAME = "filesrc"
    PROPS = {
        "location": PropDef(str, None, "input file path"),
        "dims": PropDef(str, "", "frame dims for raw files"),
        "types": PropDef(str, "float32"),
        "rate": PropDef(str, "0/1", "emission framerate, 0/1 = as fast"),
        "frames_per_file": PropDef(int, 0, "npy: 0 = axis-0-indexed"),
        "loop": PropDef(prop_bool, False, "repeat forever"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if not self.props["location"]:
            raise PipelineError(f"filesrc {self.name}: location= is required")
        self._frames = self._load()
        if not self._frames:
            raise PipelineError(
                f"filesrc {self.name}: {self.props['location']!r} contains "
                f"no frames (empty file or zero-length leading axis)")

    def _load(self) -> List[np.ndarray]:
        import os

        path = self.props["location"]
        if not os.path.isfile(path):
            raise PipelineError(
                f"filesrc {self.name}: file not found: {path!r}")
        ext = path.rsplit(".", 1)[-1].lower()
        if ext == "npy":
            arr = np.load(path)
            if self.props["frames_per_file"] == 1 or arr.ndim == 0:
                return [np.atleast_1d(arr)]
            return [arr[i] for i in range(arr.shape[0])]
        if ext == "npz":
            z = np.load(path)
            return [z[k] for k in sorted(z.files)]
        # raw bytes
        if not self.props["dims"]:
            raise PipelineError(
                f"filesrc {self.name}: raw files need dims=/types= to "
                f"frame the byte stream")
        spec = TensorsSpec.from_strings(self.props["dims"], self.props["types"])
        info = spec.tensors[0]
        data = open(path, "rb").read()
        fsize = info.nbytes
        if fsize == 0 or len(data) % fsize != 0:
            raise PipelineError(
                f"filesrc {self.name}: file size {len(data)} is not a "
                f"multiple of the {fsize}-byte frame ({info})")
        frames = [
            np.frombuffer(data[i:i + fsize], info.dtype.np_dtype)
            .reshape(info.shape)
            for i in range(0, len(data), fsize)
        ]
        return frames

    def output_spec(self) -> StreamSpec:
        first = self._frames[0]
        spec = TensorBuffer.of(first).spec()
        return spec.with_rate(Fraction(self.props["rate"]))

    def generate(self) -> Iterator[TensorBuffer]:
        rate = Fraction(self.props["rate"])
        frame_ns = int(1e9 / rate) if rate > 0 else 0
        i = 0
        while True:
            for f in self._frames:
                if frame_ns:
                    time.sleep(frame_ns / 1e9)
                yield TensorBuffer.of(f, pts=i * (frame_ns or 1))
                i += 1
            if not self.props["loop"]:
                return


@register_element("audiotestsrc")
class AudioTestSrc(SourceElement):
    """Deterministic audio generator (audiotestsrc analog): sine or
    seeded noise chunks of `samples_per_buffer` frames."""

    ELEMENT_NAME = "audiotestsrc"
    PROPS = {
        "sample_rate": PropDef(int, 16000),
        "channels": PropDef(int, 1),
        "format": PropDef(str, "S16LE"),
        "wave": PropDef(str, "sine", "sine|noise"),
        "freq": PropDef(float, 440.0),
        "num_buffers": PropDef(int, 10),
        "samples_per_buffer": PropDef(int, 1024),
        "seed": PropDef(int, 0),
    }

    def output_spec(self) -> StreamSpec:
        from nnstreamer_tpu.graph.media import AudioSpec

        return AudioSpec(sample_rate=self.props["sample_rate"],
                         channels=self.props["channels"],
                         sample_format=self.props["format"])

    def generate(self) -> Iterator[TensorBuffer]:
        spec = self.out_specs[0]
        n = self.props["samples_per_buffer"]
        ch = self.props["channels"]
        sr = self.props["sample_rate"]
        rng = np.random.default_rng(self.props["seed"])
        dtype = np.dtype(spec.dtype_name)
        for i in range(self.props["num_buffers"]):
            t = (np.arange(n) + i * n) / sr
            if self.props["wave"] == "noise":
                wave = rng.uniform(-1.0, 1.0, size=(n, ch))
            else:
                wave = np.sin(2 * np.pi * self.props["freq"] * t)[:, None]
                wave = np.repeat(wave, ch, axis=1)
            if dtype.kind == "i":
                scale = np.iinfo(dtype).max
                chunk = (wave * 0.8 * scale).astype(dtype)
            else:
                chunk = wave.astype(dtype)
            yield TensorBuffer.of(chunk, pts=int(i * n * 1e9 / sr))
