"""tensor_decoder — tensor→media egress via decoder subplugins.

Reference parity: gst/nnstreamer/elements/gsttensor_decoder.c dispatching
to `GstTensorDecoderDef` subplugins (include/nnstreamer_plugin_api_decoder.h:39).
Decoder subplugins live in nnstreamer_tpu/decoders/ (image_labeling,
bounding_boxes, image_segment, pose_estimation, direct_video, …).
"""

from __future__ import annotations

from typing import List, Sequence

from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.core.registry import PluginKind, register_element, registry
from nnstreamer_tpu.graph.pipeline import (
    Element, Emission, PropDef, StreamSpec, prop_bool)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec


class DecoderSubplugin:
    """tensor→media decoder API (GstTensorDecoderDef analog)."""

    MODE = ""

    def init(self, props: dict) -> None:
        """Receive the decoder element's option properties."""

    def negotiate(self, in_spec: TensorsSpec) -> StreamSpec:
        """Validate the tensor input and declare the output media spec
        (getOutCaps analog)."""
        raise NotImplementedError

    def decode(self, buf: TensorBuffer) -> TensorBuffer:
        raise NotImplementedError

    # -- optional device path (tensor_decoder device=true) -----------------
    # TPU-first extension: postprocess as XLA on device, emitting a
    # compact result tensor instead of host-rendered media, so raw model
    # outputs never cross D2H (decoders/device.py rationale).

    def device_negotiate(self, in_spec: TensorsSpec) -> TensorsSpec:
        raise PipelineError(
            f"decoder mode={self.MODE} has no device decode path; drop "
            f"device=true to use the host decoder")

    def device_decode(self, tensors, aux=None):
        """jit-traceable: tuple of arrays → tuple of arrays. `aux` is
        device_aux()'s pytree, passed as a jit ARGUMENT (large decode
        constants must never embed as literals — see backends/xla.py
        fuse())."""
        raise NotImplementedError

    def device_aux(self):
        """Optional pytree of decode-time constants (e.g. SSD anchors)."""
        return None


def register_decoder(mode: str):
    def deco(cls):
        cls.MODE = mode
        registry.register(PluginKind.DECODER, mode, cls)
        return cls
    return deco


@register_element("tensor_decoder")
class TensorDecoder(Element):
    ELEMENT_NAME = "tensor_decoder"
    WANTS_HOST = True
    PROPS = {
        "mode": PropDef(str, None, "decoder subplugin name"),
        # device=true: run the decode as XLA on device and emit the
        # compact result tensor (boxes/keypoints/label index) instead of
        # host-rendered media — raw model outputs never cross D2H
        "device": PropDef(prop_bool, False, "device-side decode"),
        # reference passes up to 9 positional option strings; we accept
        # those plus named passthrough props via option_fields
        **{f"option{i}": PropDef(str, "") for i in range(1, 10)},
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if not self.props["mode"]:
            raise PipelineError(
                f"tensor_decoder ({self.name}) requires mode=<subplugin>; "
                f"available: {registry.names(PluginKind.DECODER)}"
            )
        import nnstreamer_tpu.decoders  # noqa: F401 (registers built-ins)
        cls = registry.get(PluginKind.DECODER, self.props["mode"])
        self.sub: DecoderSubplugin = cls()
        self.sub.init(dict(self.props))
        self._device_fn = None
        if self.props["device"]:
            self.WANTS_HOST = False   # keep payloads on device

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0])
        try:
            if self.props["device"]:
                out = self.sub.device_negotiate(spec)
                import jax

                self._device_aux = self.sub.device_aux()
                if self._device_aux is not None:
                    self._device_aux = jax.device_put(self._device_aux)
                self._device_fn = jax.jit(self.sub.device_decode)
            else:
                out = self.sub.negotiate(spec)
        except (ValueError, PipelineError) as e:
            self.fail_negotiation(
                f"decoder mode={self.props['mode']} rejected input "
                f"{spec}: {e}"
            )
        return [out]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        if self._device_fn is not None:
            out = self._device_fn(buf.tensors, self._device_aux)
            if not isinstance(out, (tuple, list)):
                out = (out,)
            return [(0, buf.with_tensors(tuple(out)))]
        return [(0, self.sub.decode(buf.to_host()))]
