"""tensor_decoder — tensor→media egress via decoder subplugins.

Reference parity: gst/nnstreamer/elements/gsttensor_decoder.c dispatching
to `GstTensorDecoderDef` subplugins (include/nnstreamer_plugin_api_decoder.h:39).
Decoder subplugins live in nnstreamer_tpu/decoders/ (image_labeling,
bounding_boxes, image_segment, pose_estimation, direct_video, …).
"""

from __future__ import annotations

from typing import List, Sequence

from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.core.registry import PluginKind, register_element, registry
from nnstreamer_tpu.graph.pipeline import (
    Element, Emission, PropDef, StreamSpec, prop_bool)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec


class DecoderSubplugin:
    """tensor→media decoder API (GstTensorDecoderDef analog)."""

    MODE = ""

    def init(self, props: dict) -> None:
        """Receive the decoder element's option properties."""

    def negotiate(self, in_spec: TensorsSpec) -> StreamSpec:
        """Validate the tensor input and declare the output media spec
        (getOutCaps analog)."""
        raise NotImplementedError

    def decode(self, buf: TensorBuffer) -> TensorBuffer:
        raise NotImplementedError

    # -- optional device path (tensor_decoder device=true) -----------------
    # TPU-first extension: postprocess as XLA on device, emitting a
    # compact result tensor instead of host-rendered media, so raw model
    # outputs never cross D2H (decoders/device.py rationale).

    def device_negotiate(self, in_spec: TensorsSpec) -> TensorsSpec:
        raise PipelineError(
            f"decoder mode={self.MODE} has no device decode path; drop "
            f"device=true to use the host decoder")

    def device_decode(self, tensors, aux=None):
        """jit-traceable: tuple of arrays → tuple of arrays. `aux` is
        device_aux()'s pytree, passed as a jit ARGUMENT (large decode
        constants must never embed as literals — see backends/xla.py
        fuse())."""
        raise NotImplementedError

    def device_aux(self):
        """Optional pytree of decode-time constants (e.g. SSD anchors)."""
        return None

    # -- optional compaction path (tensor_decoder device=compact) ----------
    # Middle ground: the heavy raw model outputs are reduced on device to
    # a small candidate tensor (e.g. top-K boxes), but the decoder's host
    # semantics — thresholding, NMS, media overlay — still run on host
    # exactly as in the plain mode. D2H shrinks from the raw grids to the
    # compact tensor; results are identical whenever the compact tensor
    # covers everything above threshold.

    def device_compact(self, tensors, aux=None):
        """jit-traceable reduction: raw output arrays → compact arrays
        that decode() can consume (flagged via `consume_compact`)."""
        raise PipelineError(
            f"decoder mode={self.MODE} has no device compaction; use "
            f"device=true (full device decode) or the host decoder")

    def device_compact_check(self) -> None:
        """Raise PipelineError at negotiation time when this subplugin
        (or its configured scheme) cannot compact — fail-fast parity
        with device_negotiate's validation."""
        raise PipelineError(
            f"decoder mode={self.MODE} has no device compaction; use "
            f"device=true (full device decode) or the host decoder")


def _prop_device(v) -> object:
    """false | true | compact (bool-compatible parse)."""
    if isinstance(v, str) and v.strip().lower() == "compact":
        return "compact"
    from nnstreamer_tpu.graph.pipeline import prop_bool

    return prop_bool(v)


def register_decoder(mode: str):
    def deco(cls):
        cls.MODE = mode
        registry.register(PluginKind.DECODER, mode, cls)
        return cls
    return deco


@register_element("tensor_decoder")
class TensorDecoder(Element):
    ELEMENT_NAME = "tensor_decoder"
    WANTS_HOST = True
    PROPS = {
        "mode": PropDef(str, None, "decoder subplugin name"),
        # device=true: run the decode as XLA on device and emit the
        # compact result tensor (boxes/keypoints/label index) instead of
        # host-rendered media — raw model outputs never cross D2H.
        # device=compact: reduce on device (e.g. top-K candidates) but
        # keep the host decode semantics (threshold/NMS/overlay) — only
        # the compact candidate tensor crosses D2H.
        "device": PropDef(_prop_device, False,
                          "device-side decode (false|true|compact)"),
        # frames whose D2H readback may be in flight at once (compact
        # AND plain host decode). >1 pipelines the host copies
        # (copy_to_host_async) so the transfer latency overlaps across
        # frames — decode emission lags by up to max_in_flight-1 frames
        # mid-stream (flush drains at EOS). 1 (default) = strict
        # per-frame synchronous behavior.
        "max_in_flight": PropDef(int, 1, "decode D2H pipelining depth"),
        # reference passes up to 9 positional option strings; we accept
        # those plus named passthrough props via option_fields
        **{f"option{i}": PropDef(str, "") for i in range(1, 10)},
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if not self.props["mode"]:
            raise PipelineError(
                f"tensor_decoder ({self.name}) requires mode=<subplugin>; "
                f"available: {registry.names(PluginKind.DECODER)}"
            )
        import nnstreamer_tpu.decoders  # noqa: F401 (registers built-ins)
        cls = registry.get(PluginKind.DECODER, self.props["mode"])
        self.sub: DecoderSubplugin = cls()
        self.sub.init(dict(self.props))
        self._device_fn = None
        self._compact_fn = None
        self._inflight: List = []     # frames awaiting D2H completion
        if self.props["device"]:
            self.WANTS_HOST = False   # keep payloads on device
            # device decode emits unresolved jax arrays — eligible for
            # the scheduler's async-dispatch window (no per-result sync)
            self.DEVICE_RESIDENT = True  # nnlint: disable=NNL001 residency is the device= property's choice, set before the scheduler ever reads it
        # pipelined host decode (max_in_flight>1) keeps WANTS_HOST=True:
        # the scheduler's enqueue-side prefetch_host starts the copy as
        # early as possible; this element merely defers the blocking
        # to_host() behind the window

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0])
        dev = self.props["device"]
        try:
            if dev == "compact":
                import jax

                # host media semantics on the compacted candidates:
                # negotiate() validates the RAW input + declares the
                # media output; the device step only shrinks the D2H
                self.sub.device_compact_check()   # fail fast pre-stream
                out = self.sub.negotiate(spec)
                self._device_aux = self.sub.device_aux()
                if self._device_aux is not None:
                    self._device_aux = jax.device_put(self._device_aux)
                self._compact_fn = jax.jit(self.sub.device_compact)
                self.sub.consume_compact = True
            elif dev:
                import jax

                out = self.sub.device_negotiate(spec)
                self._device_aux = self.sub.device_aux()
                if self._device_aux is not None:
                    self._device_aux = jax.device_put(self._device_aux)
                self._device_fn = jax.jit(self.sub.device_decode)
            else:
                out = self.sub.negotiate(spec)
        except (ValueError, PipelineError) as e:
            self.fail_negotiation(
                f"decoder mode={self.props['mode']} rejected input "
                f"{spec}: {e}"
            )
        return [out]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        depth = max(1, int(self.props["max_in_flight"]))
        if self._compact_fn is not None:
            out = self._compact_fn(buf.tensors, self._device_aux)
            if not isinstance(out, (tuple, list)):
                out = (out,)
            # best-effort async D2H start: overlaps the copy across
            # in-flight frames (buffer.prefetch_host guards backends
            # whose copy_to_host_async raises)
            return self._window(buf.with_tensors(tuple(out)), depth)
        if self._device_fn is not None:
            out = self._device_fn(buf.tensors, self._device_aux)
            if not isinstance(out, (tuple, list)):
                out = (out,)
            return [(0, buf.with_tensors(tuple(out)))]
        if depth > 1:
            # pipelined host decode: same windowing as compact mode,
            # minus the device reduction step
            return self._window(buf, depth)
        return [(0, self.sub.decode(buf.to_host()))]

    def _window(self, buf: TensorBuffer, depth: int) -> List[Emission]:
        """Enqueue with async readback; emit decodes of frames whose
        window slot expired (flush() drains the rest at EOS)."""
        self._inflight.append(buf.prefetch_host())
        ems: List[Emission] = []
        while len(self._inflight) >= depth:
            ems.append((0, self._emit_pending()))
        return ems

    def _emit_pending(self) -> TensorBuffer:
        return self.sub.decode(self._inflight.pop(0).to_host())

    def flush(self) -> List[Emission]:
        ems: List[Emission] = []
        while self._inflight:
            ems.append((0, self._emit_pending()))
        return ems
