"""mqttsink / mqttsrc — brokered pub/sub with cross-host time alignment.

Reference parity: gst/mqtt/ (3437 LoC, paho-based) — publish any tensor
stream to a topic on a broker, subscribe from any number of pipelines on
any host, and keep timestamps comparable across machines via NTP
(mqttsrc.c:26, GstMQTTMessageHdr mqttcommon.h:43-63, ntputil.c:140).

TPU-first redesign: the broker is our own EdgeBroker (edge/broker.py) —
no external MQTT daemon dependency — and the NTP daemon collapses into
the broker's TIME exchange: mqttsink stamps every frame with *broker
time* (local clock + measured offset), and mqttsrc exposes that stamp
plus its own offset so receivers on a different host can rebase PTS into
the shared broker timeline (`sync=broker` rewrites PTS; `sync=none`
leaves sender PTS). Payloads are standard wire frames, so caps, meta and
PTS all travel.
"""

from __future__ import annotations

import queue as _queue
import threading
from typing import Iterator, Optional

from nnstreamer_tpu.core.errors import PipelineError, StreamError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.edge.broker import BrokerClient
from nnstreamer_tpu.edge.wire import decode_buffer, encode_buffer
from nnstreamer_tpu.graph.pipeline import (
    PropDef, SinkElement, SourceElement, StreamSpec)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec

log = get_logger("elements.mqtt")


def _broker_props():
    return {
        "host": PropDef(str, "127.0.0.1", "broker host"),
        "port": PropDef(int, None, "broker port (required)"),
        "topic": PropDef(str, None, "topic (required)"),
        # protocol=mqtt speaks real MQTT 3.1.1 (edge/mqtt_wire.py) so a
        # STOCK broker (mosquitto, EMQX, EdgeBroker's MQTT listener)
        # carries the stream — full wire parity with the reference's
        # paho-based gst/mqtt. protocol=edge uses the EdgeBroker native
        # protocol, which adds the broker-time PTS rebase (sync=broker).
        "protocol": PropDef(str, "edge", "edge|mqtt wire protocol"),
        "qos": PropDef(int, 0, "MQTT QoS for publishes (0|1)"),
    }


def _check_protocol(name, props):
    if props["protocol"] not in ("edge", "mqtt"):
        raise PipelineError(
            f"{name}: protocol= must be edge|mqtt, got "
            f"{props['protocol']!r}")
    if props["qos"] not in (0, 1):
        raise PipelineError(
            f"{name}: qos= must be 0|1, got {props['qos']!r}")


@register_element("mqttsink")
class MqttSink(SinkElement):
    """Publish the stream to a broker topic, stamped in broker time."""

    ELEMENT_NAME = "mqttsink"
    WANTS_HOST = True
    PROPS = {**_broker_props()}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if self.props["port"] is None or not self.props["topic"]:
            raise PipelineError(
                f"{self.name}: port= (broker) and topic= are required")
        _check_protocol(self.name, self.props)
        self._bc: Optional[BrokerClient] = None
        self._mc = None                      # MqttClient (protocol=mqtt)

    def start(self) -> None:
        if self.props["protocol"] == "mqtt":
            from nnstreamer_tpu.edge.mqtt_wire import MqttClient

            self._mc = MqttClient(self.props["host"], self.props["port"],
                                  client_id=f"nns-{self.name}")
            return
        self._bc = BrokerClient(self.props["host"], self.props["port"])
        # one clock sync up front; frames stamp broker_now from it
        off = self._bc.clock_offset_ns()
        log.info("%s: broker clock offset %+d us", self.name, off // 1000)

    def render(self, buf: TensorBuffer) -> None:
        if self._mc is not None:
            if not self._mc.alive:
                raise StreamError(
                    f"{self.name}: MQTT connection lost (topic "
                    f"{self.props['topic']!r})")
            self._mc.publish(self.props["topic"], encode_buffer(buf),
                             qos=self.props["qos"])
            return
        if not self._bc.alive:
            raise StreamError(
                f"{self.name}: broker connection lost (topic "
                f"{self.props['topic']!r})")
        self._bc.publish(self.props["topic"], encode_buffer(buf))

    def stop(self) -> None:
        if self._bc is not None:
            self._bc.close()
            self._bc = None
        if self._mc is not None:
            self._mc.close()
            self._mc = None


@register_element("mqttsrc")
class MqttSrc(SourceElement):
    """Subscribe to a broker topic and emit its frames.

    sync=none: keep the publisher's PTS. sync=broker: rewrite PTS to the
    publish timestamp on the shared broker timeline, rebased so the first
    frame is 0 — streams from different hosts become directly
    mux/merge-able (the reference's NTP-sync use case).
    dims/types declare the spec, or it is sniffed from frame 1.
    """

    ELEMENT_NAME = "mqttsrc"
    PROPS = {
        **_broker_props(),
        "dims": PropDef(str, "", "expected dims (else sniffed)"),
        "types": PropDef(str, "float32"),
        "sync": PropDef(str, "none", "none|broker PTS handling"),
        "sniff_timeout": PropDef(float, 10.0),
        "queue_size": PropDef(int, 64, "pending frames before dropping old"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if self.props["port"] is None or not self.props["topic"]:
            raise PipelineError(
                f"{self.name}: port= (broker) and topic= are required")
        if self.props["sync"] not in ("none", "broker"):
            raise PipelineError(
                f"{self.name}: sync= must be none|broker, got "
                f"{self.props['sync']!r}")
        _check_protocol(self.name, self.props)
        if self.props["protocol"] == "mqtt" and \
                self.props["sync"] == "broker":
            raise PipelineError(
                f"{self.name}: sync=broker needs the broker-time stamps "
                f"of protocol=edge (stock MQTT has no shared clock; the "
                f"reference runs an external NTP daemon for this)")
        self._mc = None                      # MqttClient (protocol=mqtt)
        self._bc: Optional[BrokerClient] = None
        self._q: _queue.Queue = _queue.Queue(maxsize=self.props["queue_size"])
        self._stop = threading.Event()
        self._sniffed = None
        self._base_pub_ns: Optional[int] = None

    def _on_frame(self, pub_broker_ns: int, frame: bytes) -> None:
        try:
            buf, _ = decode_buffer(frame)
        except (ValueError, StreamError) as e:
            log.error("%s: dropping corrupt frame on %r: %s",
                      self.name, self.props["topic"], e)
            return
        buf.meta["pub_broker_ns"] = pub_broker_ns
        if self.props["sync"] == "broker":
            if self._base_pub_ns is None:
                self._base_pub_ns = pub_broker_ns
            buf = buf.with_tensors(buf.tensors,
                                   pts=pub_broker_ns - self._base_pub_ns)
        try:
            self._q.put_nowait(buf)
        except _queue.Full:
            try:   # drop the OLDEST so a stalled pipeline sees fresh data
                self._q.get_nowait()
                self._q.put_nowait(buf)
            except (_queue.Empty, _queue.Full):
                pass

    def _on_mqtt_frame(self, _topic: str, payload: bytes) -> None:
        # stock-MQTT path: no publish-time stamp on the wire; frames
        # keep the sender's PTS from the wire frame itself
        self._on_frame(0, payload)

    def _ensure_connected(self) -> None:
        if self.props["protocol"] == "mqtt":
            if self._mc is None:
                from nnstreamer_tpu.edge.mqtt_wire import MqttClient

                self._mc = MqttClient(
                    self.props["host"], self.props["port"],
                    client_id=f"nns-{self.name}")
                self._mc.subscribe(self.props["topic"],
                                   self._on_mqtt_frame,
                                   qos=self.props["qos"])
            return
        if self._bc is None:
            self._bc = BrokerClient(self.props["host"], self.props["port"])
            # no clock exchange here: PTS rebasing reads the *publish*
            # stamps (already broker time, stamped by mqttsink), so the
            # subscriber needs no own offset
            self._bc.subscribe(self.props["topic"], self._on_frame)

    def output_spec(self) -> StreamSpec:
        if self.props["dims"]:
            return TensorsSpec.from_strings(self.props["dims"],
                                            self.props["types"])
        self._ensure_connected()
        try:
            self._sniffed = self._q.get(timeout=self.props["sniff_timeout"])
        except _queue.Empty:
            raise PipelineError(
                f"{self.name}: nothing published on "
                f"{self.props['topic']!r} within "
                f"{self.props['sniff_timeout']}s; declare dims=/types= to "
                f"negotiate without sniffing") from None
        return self._sniffed.spec()

    def generate(self) -> Iterator[TensorBuffer]:
        self._ensure_connected()
        if self._sniffed is not None:
            yield self._sniffed
            self._sniffed = None
        while not self._stop.is_set():
            try:
                buf = self._q.get(timeout=0.1)
            except _queue.Empty:
                if self._bc is not None and not self._bc.alive:
                    raise StreamError(
                        f"{self.name}: broker connection lost (topic "
                        f"{self.props['topic']!r})")
                if self._mc is not None and not self._mc.alive:
                    raise StreamError(
                        f"{self.name}: MQTT connection lost (topic "
                        f"{self.props['topic']!r})")
                continue
            yield buf

    def interrupt(self) -> None:
        self._stop.set()

    def stop(self) -> None:
        self._stop.set()
        if self._bc is not None:
            self._bc.close()
            self._bc = None
        if self._mc is not None:
            self._mc.close()
            self._mc = None
