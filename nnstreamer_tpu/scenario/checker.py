"""One property checker for the four standing invariants.

Every drill in this repo has asserted some subset of the same four
properties ad-hoc; this module is the single evaluation site, fed by
ONE scrape taken at quiesce (after the open-loop drain, before
teardown):

1. ``offered_admitted``  — ``offered == admitted + Σrejected`` at the
   front door, per tenant class and summed across classes.
2. ``admitted_settled``  — ``admitted == replied + Σshed + depth +
   inflight``, per class and summed; when the scrape carries a
   per-host replied sum (mesh), it must equal the router's replied —
   the cross-host form of the same books.
3. ``zero_orphans``      — no worker pid outlives its pool's close.
4. ``trace_complete``    — every replied frame's trace context carries
   the full serving hop chain (tracing.REQUIRED_REPLY_HOPS), and every
   completed request produced a trace at all.

Scenario SLO assertions (`ScenarioSLO`) layer on top: zero lost,
recovery, optional shed-rate and p99 gates. A violation is data —
``{"invariant", "detail"}`` — so the executor can hand the whole
verdict plus the failing spec to a `FlightRecorder` bundle
(``scenario_violation``) and the shrinker can re-evaluate candidates
mechanically.

The scrape is a plain dict (see `check_scrape`) precisely so tests can
hand-build violating scrapes for each invariant without spinning up a
single worker.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from nnstreamer_tpu.runtime.tracing import (
    missing_hops, trace_chain_complete)

#: the four standing invariants, in evaluation order
INVARIANTS = ("offered_admitted", "admitted_settled", "zero_orphans",
              "trace_complete")


def _viol(out: List[dict], invariant: str, detail: str) -> None:
    out.append({"invariant": invariant, "detail": detail})


def _check_offered_admitted(c: dict, out: List[dict]) -> None:
    rej = sum(c.get("rejected", {}).values())
    if c["offered"] != c["admitted"] + rej:
        _viol(out, "offered_admitted",
              f"offered {c['offered']} != admitted {c['admitted']} + "
              f"rejected {rej}")
    for name, st in (c.get("classes") or {}).items():
        crej = sum(st.get("rejected", {}).values())
        if st["offered"] != st["admitted"] + crej:
            _viol(out, "offered_admitted",
                  f"class {name}: offered {st['offered']} != admitted "
                  f"{st['admitted']} + rejected {crej}")


def _check_admitted_settled(c: dict, out: List[dict],
                            perhost_replied_sum: Optional[int]) -> None:
    shed = sum(c.get("shed", {}).values())
    if c["admitted"] != (c["replied"] + shed + c["depth"]
                         + c["inflight"]):
        _viol(out, "admitted_settled",
              f"admitted {c['admitted']} != replied {c['replied']} + "
              f"shed {shed} + depth {c['depth']} + inflight "
              f"{c['inflight']}")
    classes = c.get("classes") or {}
    sums = {k: 0 for k in ("offered", "admitted", "replied",
                           "rejected", "shed", "depth", "inflight")}
    for name, st in classes.items():
        cshed = sum(st.get("shed", {}).values())
        if st["admitted"] != (st["replied"] + cshed + st["depth"]
                              + st["inflight"]):
            _viol(out, "admitted_settled",
                  f"class {name}: admitted {st['admitted']} != replied "
                  f"{st['replied']} + shed {cshed} + depth "
                  f"{st['depth']} + inflight {st['inflight']}")
        sums["offered"] += st["offered"]
        sums["admitted"] += st["admitted"]
        sums["replied"] += st["replied"]
        sums["rejected"] += sum(st.get("rejected", {}).values())
        sums["shed"] += cshed
        sums["depth"] += st["depth"]
        sums["inflight"] += st["inflight"]
    if classes:
        want = {"offered": c["offered"], "admitted": c["admitted"],
                "replied": c["replied"],
                "rejected": sum(c.get("rejected", {}).values()),
                "shed": sum(c.get("shed", {}).values()),
                "depth": c["depth"], "inflight": c["inflight"]}
        for k, v in want.items():
            if sums[k] != v:
                _viol(out, "admitted_settled",
                      f"class sums: Σ{k} {sums[k]} != global {v}")
    if perhost_replied_sum is not None \
            and perhost_replied_sum != c["replied"]:
        _viol(out, "admitted_settled",
              f"Σ per-host replied {perhost_replied_sum} != router "
              f"replied {c['replied']}")


def _check_traces(scrape: dict, out: List[dict]) -> None:
    traces = scrape.get("traces")
    if traces is None:
        return                        # untraced run: nothing to prove
    completed = scrape.get("completed")
    if completed is not None and len(traces) != completed:
        _viol(out, "trace_complete",
              f"{completed} replies but only {len(traces)} carried a "
              f"trace context home")
    for pts, ctx in traces.items():
        hops = (ctx or {}).get("hops") or []
        if not trace_chain_complete(hops):
            _viol(out, "trace_complete",
                  f"pts {pts} (trace {ctx.get('id')}): missing hops "
                  f"{list(missing_hops(hops))}")
            return                    # one example is enough evidence


def check_scrape(scrape: dict, *, slo=None) -> dict:
    """Evaluate the four invariants (and optional `ScenarioSLO`
    assertions) over one scrape::

        {"admission": AdmissionQueue.counters() dict,   # required
         "orphans": [pid, ...],                          # required
         "completed": int,             # replies the client matched
         "traces": {pts: trace_ctx},   # per-reply contexts (optional)
         "perhost_replied_sum": int,   # mesh cross-host sum (optional)
         "report": {...}}              # loadgen report for SLO gates

    Returns ``{"ok", "invariants": {name: bool}, "violations":
    [{"invariant", "detail"}, ...]}``. SLO violations use invariant
    name ``"slo"`` and do not affect the four standing flags."""
    c = scrape.get("admission")
    if not isinstance(c, dict):
        raise ValueError("scrape needs an 'admission' counters dict")
    violations: List[dict] = []
    _check_offered_admitted(c, violations)
    _check_admitted_settled(c, violations,
                            scrape.get("perhost_replied_sum"))
    orphans = scrape.get("orphans") or []
    if orphans:
        _viol(violations, "zero_orphans",
              f"{len(orphans)} worker pid(s) outlived close(): "
              f"{list(orphans)[:8]}")
    _check_traces(scrape, violations)

    report = scrape.get("report") or {}
    if slo is not None:
        if getattr(slo, "require_zero_lost", False) \
                and report.get("lost", 0) != 0:
            _viol(violations, "slo",
                  f"lost must be 0, got {report.get('lost')}")
        if getattr(slo, "require_recovered", False) \
                and not report.get("recovered", False):
            _viol(violations, "slo", "world did not recover (fence/"
                  "restart budget missed)")
        max_shed = getattr(slo, "max_shed_rate", None)
        if max_shed is not None \
                and report.get("shed_rate", 0.0) > max_shed:
            _viol(violations, "slo",
                  f"shed_rate {report.get('shed_rate')} > "
                  f"{max_shed}")
        if getattr(slo, "enforce_p99", False):
            p99 = (report.get("latency_ms") or {}).get("p99")
            budget = getattr(slo, "p99_budget_ms", None)
            if p99 is not None and budget is not None and p99 > budget:
                _viol(violations, "slo",
                      f"p99 {p99}ms > budget {budget}ms")

    flags: Dict[str, bool] = {
        name: not any(v["invariant"] == name for v in violations)
        for name in INVARIANTS}
    return {"ok": not violations, "invariants": flags,
            "violations": violations}


def check_result(result: dict, spec=None, *, recorder=None) -> dict:
    """Check an executor result (scenario/executor.py shape) and, on
    any violation, dump a flight-recorder bundle with the failing spec
    embedded in the cause (``flight --inspect`` renders it). Returns
    the `check_scrape` verdict, plus ``flight_bundle`` when a bundle
    was published."""
    scrape = {
        "admission": result["admission"],
        "orphans": result.get("orphans") or [],
        "completed": (result.get("report") or {}).get("completed"),
        "traces": (result.get("report") or {}).get("traces"),
        "perhost_replied_sum": result.get("perhost_replied_sum"),
        "report": result.get("report") or {},
    }
    verdict = check_scrape(scrape, slo=spec.slo if spec else None)
    if not verdict["ok"] and recorder is not None:
        cause = {
            "scenario": result.get("scenario"),
            "seed": result.get("seed"),
            "violations": verdict["violations"],
            "scenario_spec": (spec.to_dict() if spec is not None
                              else result.get("spec")),
        }
        try:
            path = recorder.trigger("scenario_violation", cause)
            if path:
                verdict["flight_bundle"] = path
        except Exception:              # forensics must not mask the
            pass                       # violation verdict itself
    return verdict
