"""Adversarial scenario engine: composable seeded world drills.

Every robustness harness in this repo — worker-kill chaos, mesh
partition floods, tenant storms, swap drills — is a hand-written
composition of the same primitives. This package makes the
composition declarative and replayable:

- spec.py     — `ScenarioSpec`: arrival programs × fault programs ×
                topology × SLO, JSON round-trippable, every random
                choice derived from ONE root seed (`sub_seed`).
- executor.py — compiles a spec into a live world (real subprocess
                pool or multi-host mesh) and runs it open-loop;
                `replay_scenario` re-runs from the embedded spec and
                compares the quiesce ledgers.
- checker.py  — ONE property checker for the four standing invariants
                (front-door conservation, settlement, zero orphans,
                trace completeness) from one scrape; violations dump a
                `scenario_violation` flight-recorder bundle with the
                failing spec embedded.
- shrink.py   — deterministic ddmin: bisect fault and arrival
                programs (sub-seeds pinned to surviving labels) down
                to a minimal still-failing repro.

CLI: ``python -m nnstreamer_tpu scenario run|replay|shrink|list``.
Bench: ``bench.py --family scenario`` (composed mesh drill gated by
``BENCH_SCENARIO_GATE=1``). See docs/scenarios.md.
"""

from nnstreamer_tpu.scenario.checker import (
    INVARIANTS, check_result, check_scrape)
from nnstreamer_tpu.scenario.executor import (
    compile_arrivals, replay_scenario, run_scenario)
from nnstreamer_tpu.scenario.shrink import (
    ShrinkBudgetExceeded, shrink)
from nnstreamer_tpu.scenario.spec import (
    ARRIVAL_KINDS, FAULT_KINDS, TOPOLOGY_KINDS, ArrivalProgram,
    FaultProgram, ScenarioSLO, ScenarioSpec, Topology, derive_seed)


def builtin_specs() -> "dict[str, ScenarioSpec]":
    """The shipped drill catalog (``scenario list`` / ``scenario run
    NAME``). Rates are sized UNDER capacity — with zero rejects and
    zero sheds the quiesce ledger is seed-determined, so replay can
    demand bit-equal totals even through faults."""
    smoke = ScenarioSpec(
        name="smoke_pool", seed=7,
        topology=Topology(kind="pool", workers=2, service_ms=2.0),
        arrivals=(ArrivalProgram(kind="constant", n=40, rate_x=0.5),))
    kill = ScenarioSpec(
        name="kill_pool", seed=11,
        topology=Topology(kind="pool", workers=3, service_ms=4.0),
        arrivals=(ArrivalProgram(kind="poisson", n=150, rate_x=0.4),),
        faults=(FaultProgram(kind="worker_kill", at_s=0.1, kills=1),),
        slo=ScenarioSLO(require_recovered=True))
    flash = ScenarioSpec(
        name="flash_mesh", seed=23,
        topology=Topology(kind="mesh", hosts=2, workers=1,
                          service_ms=5.0, max_pending=128,
                          lease_s=0.5, max_redeliver=3),
        arrivals=(ArrivalProgram(kind="flash_crowd", n=200,
                                 rate_x=0.4, ramp_at_s=0.4,
                                 ramp_s=0.3),),
        faults=(FaultProgram(kind="blackhole", at_s=0.3, host=0,
                             heal_after_s=0.8),),
        slo=ScenarioSLO(require_recovered=True))
    composed = ScenarioSpec(
        name="composed_storm", seed=1337,
        topology=Topology(
            kind="mesh", hosts=2, workers=1, service_ms=5.0,
            max_pending=256, lease_s=0.5, max_redeliver=3,
            tenants={"paid": {"weight": 3.0},
                     "free": {"weight": 1.0}}),
        arrivals=(
            ArrivalProgram(kind="flash_crowd", n=240, rate_x=0.35,
                           tenant="paid", ramp_at_s=0.6, ramp_s=0.4),
            ArrivalProgram(kind="poisson", n=80, rate_x=0.1,
                           tenant="free"),
        ),
        faults=(
            FaultProgram(kind="blackhole", at_s=0.5, host=0,
                         heal_after_s=0.8),
            FaultProgram(kind="swap_storm", at_s=0.3, swaps=4,
                         interval_s=0.15),
            FaultProgram(kind="tenant_flood", at_s=0.8,
                         tenant="free", rate_x=0.1, n=60),
        ),
        slo=ScenarioSLO(require_recovered=True))
    return {s.name: s for s in (smoke, kill, flash, composed)}


__all__ = [
    "ARRIVAL_KINDS",
    "ArrivalProgram",
    "FAULT_KINDS",
    "FaultProgram",
    "INVARIANTS",
    "ScenarioSLO",
    "ScenarioSpec",
    "ShrinkBudgetExceeded",
    "TOPOLOGY_KINDS",
    "Topology",
    "builtin_specs",
    "check_result",
    "check_scrape",
    "compile_arrivals",
    "derive_seed",
    "replay_scenario",
    "run_scenario",
    "shrink",
]
