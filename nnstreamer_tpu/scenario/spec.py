"""Declarative seeded scenario specs — the adversarial world as data.

A `ScenarioSpec` is the whole drill written down: a topology (one
bounded pool or a multi-host mesh, optionally with a weighted-fair
tenant table), a set of arrival programs (what load arrives when, per
tenant), and a set of fault programs (what breaks when). Everything
random — arrival traces, kill victim choice, ChaosProxy fault
placement — derives from the spec's ONE root seed through
`derive_seed`, a stable hash over (root, label path), so:

- the whole world replays bit-exact from the spec alone;
- shrinking (scenario/shrink.py) can delete programs without moving
  any surviving program's randomness, because sub-seeds key off each
  program's stable ``label``, not its list position.

Validation is eager and typed, the `SLOSpec`/`TenantTable` discipline:
`from_dict` refuses unknown program kinds, unknown keys, negative
rates/counts, and malformed tenant names at load — a spec that
constructs is a spec the executor can run. JSON round-trips exactly
(``from_json(spec.to_json())`` reproduces the spec, labels included).

Program catalog: docs/scenarios.md.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from nnstreamer_tpu.traffic.admission import SHED_POLICIES

#: arrival program kinds (traffic/loadgen.py arrival processes)
ARRIVAL_KINDS = ("constant", "poisson", "bursty", "diurnal",
                 "flash_crowd")
#: fault program kinds the executor can compile
FAULT_KINDS = ("worker_kill", "blackhole", "slow_close", "swap_storm",
               "tenant_flood")
TOPOLOGY_KINDS = ("pool", "mesh")

#: net faults need a ChaosProxy in front of a host — mesh-only
_NET_FAULTS = ("blackhole", "slow_close")

_NAME_RE = re.compile(r"^[A-Za-z][A-Za-z0-9_.-]{0,63}$")


def derive_seed(root: int, *labels) -> int:
    """Stable 63-bit sub-seed from one root seed and a label path.
    hashlib, not `hash()` — PYTHONHASHSEED must not be able to change
    where a scenario's faults land between processes."""
    key = ":".join([str(int(root))] + [str(x) for x in labels])
    digest = hashlib.sha256(key.encode()).digest()
    return int.from_bytes(digest[:8], "big") >> 1


def _check_name(what: str, name) -> str:
    if not isinstance(name, str) or not _NAME_RE.match(name):
        raise ValueError(
            f"{what} must match {_NAME_RE.pattern!r}, got {name!r}")
    return name


def _check_pos(what: str, v, *, zero_ok: bool = False) -> float:
    if not isinstance(v, (int, float)) or isinstance(v, bool) \
            or (v < 0 if zero_ok else v <= 0):
        bound = ">= 0" if zero_ok else "> 0"
        raise ValueError(f"{what} must be a number {bound}, got {v!r}")
    return float(v)


def _from_dict(cls, d: dict, what: str):
    """Typed, closed-world dataclass construction: unknown keys refuse
    (a typo'd knob must not silently become a default)."""
    if not isinstance(d, dict):
        raise ValueError(f"{what} must be an object, got {type(d).__name__}")
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(d) - names
    if unknown:
        raise ValueError(f"{what}: unknown key(s) {sorted(unknown)}; "
                         f"expected a subset of {sorted(names)}")
    return cls(**d)


@dataclass(frozen=True)
class ArrivalProgram:
    """One load segment: `n` requests whose peak rate is ``rate_x`` ×
    the topology's aggregate capacity, starting at ``start_s`` on the
    scenario clock, attributed to ``tenant`` (None = untagged). Shape
    knobs by kind:

    - ``constant``     — evenly spaced at the peak rate.
    - ``poisson``      — memoryless at the peak rate.
    - ``bursty``       — Markov on/off between rate_x and
                         rate_x*low_x, exponential ``mean_dwell_s``.
    - ``diurnal``      — sinusoid between rate_x*low_x and rate_x,
                         period ``period_s``.
    - ``flash_crowd``  — rate_x*low_x until ``ramp_at_s``, then a
                         linear ramp to rate_x over ``ramp_s``.

    ``label`` is the stable sub-seed key (auto-assigned ``a<i>`` by
    `ScenarioSpec` when empty) — it, not list position, decides where
    this program's randomness comes from."""

    kind: str
    n: int
    rate_x: float
    start_s: float = 0.0
    tenant: Optional[str] = None
    label: str = ""
    low_x: float = 0.25
    mean_dwell_s: float = 0.25
    period_s: float = 2.0
    ramp_at_s: float = 0.5
    ramp_s: float = 0.5

    def __post_init__(self):
        if self.kind not in ARRIVAL_KINDS:
            raise ValueError(f"unknown arrival kind {self.kind!r}; "
                             f"expected one of {ARRIVAL_KINDS}")
        if not isinstance(self.n, int) or isinstance(self.n, bool) \
                or self.n < 1:
            raise ValueError(f"arrival n must be an int >= 1, "
                             f"got {self.n!r}")
        _check_pos("arrival rate_x", self.rate_x)
        _check_pos("arrival start_s", self.start_s, zero_ok=True)
        if not (isinstance(self.low_x, (int, float))
                and 0 < self.low_x <= 1):
            raise ValueError(f"arrival low_x must be in (0, 1], "
                             f"got {self.low_x!r}")
        _check_pos("arrival mean_dwell_s", self.mean_dwell_s)
        _check_pos("arrival period_s", self.period_s)
        _check_pos("arrival ramp_at_s", self.ramp_at_s, zero_ok=True)
        _check_pos("arrival ramp_s", self.ramp_s)
        if self.tenant is not None:
            _check_name("arrival tenant", self.tenant)
        if self.label:
            _check_name("arrival label", self.label)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ArrivalProgram":
        return _from_dict(cls, d, "arrival program")


@dataclass(frozen=True)
class FaultProgram:
    """One scheduled fault at ``at_s`` on the scenario clock:

    - ``worker_kill``  — SIGKILL ``kills`` rng-chosen workers (on mesh,
                         in ``host``'s pool), staggered 0.25s apart.
    - ``blackhole``    — silently partition ``host`` (mesh only; a
                         seeded ChaosProxy program), healing after
                         ``heal_after_s`` when set.
    - ``slow_close``   — freeze ``host``'s link without closing for
                         ``linger_s`` (mesh only).
    - ``swap_storm``   — ``swaps`` back-to-back two-phase model-swap
                         broadcasts, ``interval_s`` apart.
    - ``tenant_flood`` — ``n`` extra Poisson requests at ``rate_x`` ×
                         capacity from ``tenant``, starting at at_s
                         (compiled into the arrival timeline).

    ``label`` is the stable sub-seed key (auto-assigned ``f<i>``)."""

    kind: str
    at_s: float
    label: str = ""
    host: int = 0
    kills: int = 1
    heal_after_s: Optional[float] = None
    linger_s: float = 0.5
    swaps: int = 4
    interval_s: float = 0.1
    tenant: Optional[str] = None
    rate_x: float = 3.0
    n: int = 0

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"expected one of {FAULT_KINDS}")
        _check_pos("fault at_s", self.at_s, zero_ok=True)
        if not isinstance(self.host, int) or isinstance(self.host, bool) \
                or self.host < 0:
            raise ValueError(f"fault host must be an int >= 0, "
                             f"got {self.host!r}")
        if self.kind == "worker_kill" and self.kills < 1:
            raise ValueError(f"worker_kill kills must be >= 1, "
                             f"got {self.kills!r}")
        if self.heal_after_s is not None:
            _check_pos("fault heal_after_s", self.heal_after_s)
        _check_pos("fault linger_s", self.linger_s)
        if self.kind == "swap_storm":
            if self.swaps < 1:
                raise ValueError(f"swap_storm swaps must be >= 1, "
                                 f"got {self.swaps!r}")
            _check_pos("swap_storm interval_s", self.interval_s)
        if self.kind == "tenant_flood":
            if self.tenant is None:
                raise ValueError("tenant_flood requires a tenant name")
            if not isinstance(self.n, int) or self.n < 1:
                raise ValueError(f"tenant_flood n must be an int >= 1, "
                                 f"got {self.n!r}")
            _check_pos("tenant_flood rate_x", self.rate_x)
        if self.tenant is not None:
            _check_name("fault tenant", self.tenant)
        if self.label:
            _check_name("fault label", self.label)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultProgram":
        return _from_dict(cls, d, "fault program")


@dataclass(frozen=True)
class Topology:
    """The world the drill runs against: ``pool`` is one bounded
    subprocess worker pool (serving/pool.py); ``mesh`` is ``hosts``
    pool hosts behind a MeshRouter (serving/mesh.py), ``workers`` per
    host. ``tenants`` (name → TenantClass kwargs) installs the
    weighted-fair admission front on whichever door the load enters."""

    kind: str = "pool"
    workers: int = 2
    hosts: int = 1
    service_ms: float = 5.0
    max_pending: int = 32
    shed_policy: str = "reject-oldest"
    lease_s: float = 1.0
    max_redeliver: int = 2
    tenants: Dict[str, dict] = field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in TOPOLOGY_KINDS:
            raise ValueError(f"unknown topology kind {self.kind!r}; "
                             f"expected one of {TOPOLOGY_KINDS}")
        if not isinstance(self.workers, int) or self.workers < 1:
            raise ValueError(f"workers must be an int >= 1, "
                             f"got {self.workers!r}")
        if not isinstance(self.hosts, int) or self.hosts < 1:
            raise ValueError(f"hosts must be an int >= 1, "
                             f"got {self.hosts!r}")
        if self.kind == "pool" and self.hosts != 1:
            raise ValueError("pool topology has exactly 1 host; use "
                             "kind='mesh' for multi-host worlds")
        _check_pos("service_ms", self.service_ms)
        if not isinstance(self.max_pending, int) or self.max_pending < 1:
            raise ValueError(f"max_pending must be an int >= 1, "
                             f"got {self.max_pending!r}")
        if self.shed_policy not in SHED_POLICIES:
            raise ValueError(f"unknown shed_policy "
                             f"{self.shed_policy!r}; expected one of "
                             f"{tuple(SHED_POLICIES)}")
        _check_pos("lease_s", self.lease_s)
        if not isinstance(self.max_redeliver, int) \
                or self.max_redeliver < 0:
            raise ValueError(f"max_redeliver must be an int >= 0, "
                             f"got {self.max_redeliver!r}")
        if not isinstance(self.tenants, dict):
            raise ValueError("tenants must map name -> class kwargs")
        for name, kw in self.tenants.items():
            _check_name("tenant name", name)
            if not isinstance(kw, dict):
                raise ValueError(f"tenant {name!r} config must be an "
                                 f"object, got {type(kw).__name__}")

    @property
    def capacity_rps(self) -> float:
        return self.hosts * self.workers * 1e3 / self.service_ms

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Topology":
        return _from_dict(cls, d, "topology")


@dataclass(frozen=True)
class ScenarioSLO:
    """Per-scenario assertions layered on the four standing invariants:
    zero lost is on by default (the repo-wide contract); the p99 gate
    is opt-in (``enforce_p99``) because wall-clock latency on a loaded
    CI host is not deterministic the way the books are."""

    p99_budget_ms: float = 250.0
    require_zero_lost: bool = True
    require_recovered: bool = False
    enforce_p99: bool = False
    max_shed_rate: Optional[float] = None

    def __post_init__(self):
        _check_pos("slo p99_budget_ms", self.p99_budget_ms)
        if self.max_shed_rate is not None and not (
                isinstance(self.max_shed_rate, (int, float))
                and 0 <= self.max_shed_rate <= 1):
            raise ValueError(f"max_shed_rate must be in [0, 1], "
                             f"got {self.max_shed_rate!r}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioSLO":
        return _from_dict(cls, d, "slo")


@dataclass(frozen=True)
class ScenarioSpec:
    """One replayable adversarial world (module docstring)."""

    name: str
    seed: int
    topology: Topology
    arrivals: Tuple[ArrivalProgram, ...]
    faults: Tuple[FaultProgram, ...] = ()
    slo: ScenarioSLO = field(default_factory=ScenarioSLO)

    def __post_init__(self):
        _check_name("scenario name", self.name)
        if not isinstance(self.seed, int) or isinstance(self.seed, bool):
            raise ValueError(f"seed must be an int, got {self.seed!r}")
        if not isinstance(self.topology, Topology):
            raise ValueError("topology must be a Topology")
        arrivals = tuple(self.arrivals)
        faults = tuple(self.faults)
        if not arrivals or not all(isinstance(a, ArrivalProgram)
                                   for a in arrivals):
            raise ValueError("arrivals must be a non-empty list of "
                             "arrival programs")
        if not all(isinstance(f, FaultProgram) for f in faults):
            raise ValueError("faults must be fault programs")
        if not isinstance(self.slo, ScenarioSLO):
            raise ValueError("slo must be a ScenarioSLO")
        # auto-assign stable labels by ORIGINAL position; a shrink that
        # deletes programs keeps every survivor's label (and therefore
        # its derived randomness) unchanged
        arrivals = tuple(
            dataclasses.replace(a, label=a.label or f"a{i}")
            for i, a in enumerate(arrivals))
        faults = tuple(
            dataclasses.replace(f, label=f.label or f"f{i}")
            for i, f in enumerate(faults))
        for what, progs in (("arrival", arrivals), ("fault", faults)):
            labels = [p.label for p in progs]
            if len(set(labels)) != len(labels):
                raise ValueError(f"duplicate {what} labels: {labels}")
        object.__setattr__(self, "arrivals", arrivals)
        object.__setattr__(self, "faults", faults)
        # cross-checks the executor relies on
        for f in faults:
            if f.kind in _NET_FAULTS and self.topology.kind != "mesh":
                raise ValueError(
                    f"{f.kind} fault ({f.label}) needs a mesh topology "
                    f"(a ChaosProxy in front of a host)")
            if f.kind in _NET_FAULTS + ("worker_kill",) \
                    and f.host >= self.topology.hosts:
                raise ValueError(
                    f"fault {f.label} targets host {f.host} but the "
                    f"topology has {self.topology.hosts} host(s)")
        if self.topology.tenants:
            known = set(self.topology.tenants)
            for a in arrivals:
                if a.tenant is not None and a.tenant not in known:
                    raise ValueError(
                        f"arrival {a.label} names unknown tenant "
                        f"{a.tenant!r}; declared: {sorted(known)}")
            for f in faults:
                if f.kind == "tenant_flood" and f.tenant not in known:
                    raise ValueError(
                        f"tenant_flood {f.label} names unknown tenant "
                        f"{f.tenant!r}; declared: {sorted(known)}")

    # -- seeds -------------------------------------------------------------
    def sub_seed(self, *labels) -> int:
        """The sub-seed for one labelled consumer of this scenario's
        randomness (an arrival program, a fault, a proxy)."""
        return derive_seed(self.seed, *labels)

    # -- size (the shrinker's strictly-smaller metric) ---------------------
    def size(self) -> int:
        return (len(self.faults) + len(self.arrivals)
                + sum(a.n for a in self.arrivals)
                + sum(f.n for f in self.faults
                      if f.kind == "tenant_flood"))

    # -- serialization -----------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "seed": self.seed,
            "topology": self.topology.to_dict(),
            "arrivals": [a.to_dict() for a in self.arrivals],
            "faults": [f.to_dict() for f in self.faults],
            "slo": self.slo.to_dict(),
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioSpec":
        if not isinstance(d, dict):
            raise ValueError(f"scenario spec must be an object, "
                             f"got {type(d).__name__}")
        unknown = set(d) - {"name", "seed", "topology", "arrivals",
                            "faults", "slo"}
        if unknown:
            raise ValueError(
                f"scenario spec: unknown key(s) {sorted(unknown)}")
        if "name" not in d or "seed" not in d:
            raise ValueError("scenario spec needs 'name' and 'seed'")
        arrivals = d.get("arrivals")
        if not isinstance(arrivals, list):
            raise ValueError("scenario spec needs an 'arrivals' list")
        return cls(
            name=d["name"],
            seed=d["seed"],
            topology=Topology.from_dict(d.get("topology") or {}),
            arrivals=tuple(ArrivalProgram.from_dict(a)
                           for a in arrivals),
            faults=tuple(FaultProgram.from_dict(f)
                         for f in (d.get("faults") or [])),
            slo=ScenarioSLO.from_dict(d.get("slo") or {}),
        )

    @classmethod
    def from_json(cls, text: str) -> "ScenarioSpec":
        try:
            d = json.loads(text)
        except ValueError as e:
            raise ValueError(f"scenario spec is not valid JSON: {e}")
        return cls.from_dict(d)
