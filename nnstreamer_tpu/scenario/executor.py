"""Scenario executor — compile a spec into a live world and run it.

The executor is deliberately thin: every primitive it drives already
exists and is already tested in isolation. `compile_arrivals` turns
the spec's arrival (and tenant-flood) programs into ONE merged
open-loop timeline; the topology builds into a real `PooledQueryServer`
(pool) or `MeshWorld` (mesh); fault programs become the refactored
fault-injector primitives — `schedule_worker_kills` timers, seeded
`ChaosProxy.program` schedules, swap-broadcast timers — all started
against one clock instant. The run itself is the standard
`run_open_loop` flood with tracing on, so the result carries the same
exhaustive accounting every drill in this repo reports.

Randomness discipline: every consumer draws from
``spec.sub_seed(kind, label)`` — arrival program ``a2`` gets the same
arrival trace whether the spec has one fault or five, which is what
makes delta-debugging shrinks (scenario/shrink.py) meaningful.

At quiesce the executor takes ONE scrape (front-door admission
counters, mesh per-host replied sum, post-close orphan audit, the
per-reply trace contexts) and hands it to the property checker
(scenario/checker.py); violations dump a `FlightRecorder` bundle with
the failing spec embedded.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.scenario.checker import check_result
from nnstreamer_tpu.scenario.spec import ScenarioSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.traffic.admission import DEADLINE_META
from nnstreamer_tpu.traffic.loadgen import (
    MeshWorld, bursty_arrivals, diurnal_arrivals, flash_crowd_arrivals,
    poisson_arrivals, run_open_loop, schedule_worker_kills)

log = get_logger("scenario.executor")


def compile_arrivals(spec: ScenarioSpec
                     ) -> "Tuple[np.ndarray, List[Optional[str]], List[dict]]":
    """Compile every arrival program — plus every ``tenant_flood``
    fault, which is load in a fault costume — into one merged global
    timeline. Returns ``(arrivals, owner, segments)``: cumulative
    times, the per-request tenant attribution, and a per-program
    summary (label/kind/tenant/n/window) for the report."""
    cap = spec.topology.capacity_rps
    pairs: List[tuple] = []
    segments: List[dict] = []

    def add(times: np.ndarray, tenant: Optional[str], label: str,
            kind: str) -> None:
        segments.append({
            "label": label, "kind": kind, "tenant": tenant,
            "n": int(len(times)), "t0_s": round(float(times[0]), 3),
            "t1_s": round(float(times[-1]), 3)})
        pairs.extend((float(t), tenant) for t in times)

    for a in spec.arrivals:
        rng = np.random.default_rng(spec.sub_seed("arrival", a.label))
        peak = a.rate_x * cap
        if a.kind == "constant":
            times = np.arange(1, a.n + 1) / peak
        elif a.kind == "poisson":
            times = poisson_arrivals(peak, a.n, rng)
        elif a.kind == "bursty":
            times = bursty_arrivals(
                a.n, rate_high_hz=peak, rate_low_hz=peak * a.low_x,
                mean_dwell_s=a.mean_dwell_s, rng=rng)
        elif a.kind == "diurnal":
            times = diurnal_arrivals(
                a.n, peak_hz=peak, trough_hz=peak * a.low_x,
                period_s=a.period_s, rng=rng)
        else:                          # flash_crowd (validated at load)
            times = flash_crowd_arrivals(
                a.n, base_hz=peak * a.low_x, peak_hz=peak,
                ramp_at_s=a.ramp_at_s, ramp_s=a.ramp_s, rng=rng)
        add(times + a.start_s, a.tenant, a.label, a.kind)
    for f in spec.faults:
        if f.kind != "tenant_flood":
            continue
        rng = np.random.default_rng(spec.sub_seed("fault", f.label))
        times = poisson_arrivals(f.rate_x * cap, f.n, rng) + f.at_s
        add(times, f.tenant, f.label, "tenant_flood")
    # sort by (t, tenant) so exact-tie order is spec-determined, not
    # list-order-determined (constant programs can collide exactly)
    pairs.sort(key=lambda p: (p[0], p[1] or ""))
    arrivals = np.asarray([t for t, _ in pairs])
    owner = [tenant for _, tenant in pairs]
    return arrivals, owner, segments


def _build_world(spec: ScenarioSpec):
    """Returns (front, world, table): the front door serving object
    (PooledQueryServer or MeshRouter), the MeshWorld (None on pool
    topologies), and the installed TenantTable (or None)."""
    from nnstreamer_tpu.runtime.tracing import Tracer
    from nnstreamer_tpu.serving.pool import PooledQueryServer
    from nnstreamer_tpu.serving.tenancy import TenantTable

    topo = spec.topology
    table = TenantTable.from_dict({"tenants": dict(topo.tenants)}) \
        if topo.tenants else None
    if topo.kind == "pool":
        # an active tracer makes the workers stamp their hops, which
        # the trace_complete invariant audits on every reply
        front = PooledQueryServer.echo(
            workers=topo.workers, service_ms=topo.service_ms,
            max_pending=topo.max_pending,
            shed_policy=topo.shed_policy, tenants=table,
            tracer=Tracer())
        return front, None, table
    proxy_hosts = sorted({f.host for f in spec.faults
                          if f.kind in ("blackhole", "slow_close")})
    world = MeshWorld(
        hosts=topo.hosts, workers_per_host=topo.workers,
        service_ms=topo.service_ms, max_pending=topo.max_pending,
        lease_s=topo.lease_s, max_redeliver=topo.max_redeliver,
        seed=spec.sub_seed("netchaos"), proxy_hosts=proxy_hosts,
        trace_hosts=True, shed_policy=topo.shed_policy)
    if table is not None:
        world.router.set_tenants(table)
    return world.router, world, table


def run_scenario(spec: ScenarioSpec, *,
                 flight_dir: Optional[str] = None,
                 drain_timeout_s: float = 20.0,
                 recovery_timeout_s: float = 15.0,
                 check: bool = True, recorder=None) -> dict:
    """Run one scenario against a real world; return the result dict:
    ``{scenario, seed, spec, report, admission, totals, orphans,
    fault_log, check}``. ``totals`` is the quiesce ledger the replay
    acceptance compares; ``check`` is the property-checker verdict
    (with a ``flight_bundle`` path when a violation dumped one)."""
    from nnstreamer_tpu.serving.pool import proc_alive

    topo = spec.topology
    arrivals, owner, segments = compile_arrivals(spec)
    front, world, _table = _build_world(spec)
    pools = [front] if world is None else world.pools
    closed = False
    timers: List[threading.Timer] = []
    kill_schedules: List[dict] = []
    swap_log: List[dict] = []
    swap_lock = threading.Lock()
    proxy_events: Dict[int, list] = {}
    try:
        for f in spec.faults:
            if f.kind == "worker_kill":
                pool = pools[f.host].pool if world is not None \
                    else front.pool
                rng = np.random.default_rng(
                    spec.sub_seed("fault", f.label))
                sched, ts = schedule_worker_kills(
                    pool, workers=topo.workers, rng=rng,
                    kill_at_s=f.at_s, kills=f.kills)
                kill_schedules.append({"label": f.label,
                                       "host": f.host,
                                       "schedule": sched})
                timers.extend(ts)
            elif f.kind == "blackhole":
                evs = proxy_events.setdefault(f.host, [])
                evs.append((f.at_s, "blackhole"))
                if f.heal_after_s is not None:
                    evs.append((f.at_s + f.heal_after_s, "heal"))
            elif f.kind == "slow_close":
                proxy_events.setdefault(f.host, []).append(
                    (f.at_s, "slow_close", f.linger_s))
            elif f.kind == "swap_storm":
                def do_swap(j, f=f):
                    # bounded: a swap raced against a blackhole must
                    # not outlive the scenario waiting on a fenced host
                    try:
                        out = front.swap(f"scenario_{f.label}", j + 1,
                                         timeout_s=5.0)
                        ok = bool((out or {}).get("ok", True))
                    except Exception as e:
                        out, ok = {"error": str(e)}, False
                    with swap_lock:
                        swap_log.append({"label": f.label,
                                         "version": j + 1, "ok": ok})

                for j in range(f.swaps):
                    t = threading.Timer(f.at_s + j * f.interval_s,
                                        do_swap, args=(j,))
                    t.daemon = True
                    timers.append(t)
            # tenant_flood already compiled into the arrival timeline

        x = np.ones((8, 1), np.float32)
        tagged = any(o is not None for o in owner)

        def make_frame(i):
            from nnstreamer_tpu.serving.tenancy import TENANT_META

            buf = TensorBuffer.of(x, pts=i)
            meta = {}
            if owner[i] is not None:
                meta[TENANT_META] = owner[i]
            if topo.shed_policy == "deadline-drop":
                meta[DEADLINE_META] = spec.slo.p99_budget_ms
            return buf.with_meta(**meta) if meta else buf

        t0 = time.monotonic()
        for host, evs in proxy_events.items():
            world.proxies[host].program(sorted(evs), t0=t0)
        for t in timers:
            t.start()
        try:
            report = run_open_loop(
                "127.0.0.1", front.port, dims="8:1", types="float32",
                arrivals=arrivals, make_frame=make_frame,
                p99_budget_ms=spec.slo.p99_budget_ms,
                drain_timeout_s=drain_timeout_s,
                depth_probe=front.depth_probe,
                group_of=(lambda i: owner[i] or "_untagged")
                if tagged else None,
                trace=True, collect_traces=True)
        finally:
            for t in timers:
                t.cancel()

        # fault settlement: programs run to their promised offsets
        # (the scenario clock, not the flood's early drain, owns them)
        fault_log: Dict[str, object] = {"kills": kill_schedules,
                                        "swaps": swap_log}
        recovered = None
        if proxy_events:
            for host, evs in proxy_events.items():
                last = max(e[0] for e in evs)
                remaining = (t0 + last) - time.monotonic()
                world.proxies[host].wait_program(
                    max(0.0, remaining) + 10.0)
            fault_log["proxies"] = {
                str(h): list(world.proxies[h].program_log)
                for h in proxy_events}
            healed = any(e[1] == "heal"
                         for evs in proxy_events.values() for e in evs)
            if healed:
                deadline = time.monotonic() + recovery_timeout_s
                while time.monotonic() < deadline and \
                        front.ready_hosts() < topo.hosts:
                    time.sleep(0.05)
                recovered = front.ready_hosts() >= topo.hosts
        if kill_schedules:
            ok = True
            for pqs in (pools if world is not None else [front]):
                ok = pqs.pool.wait_ready(recovery_timeout_s) and ok
            recovered = ok if recovered is None else (recovered and ok)

        c = front.admission_counters()
        perhost = None
        mesh_stats = None
        if world is not None:
            mesh_stats = front.stats()
            perhost = sum(h["replied"] for h in mesh_stats["hosts"])
        if recovered is not None:
            report["recovered"] = bool(recovered)

        # orphan audit must run AFTER close(): a pid still alive once
        # every pool drained is a leaked child
        if world is not None:
            all_pids = world.all_pids()
            world.close()
        else:
            all_pids = front.pool.all_pids_ever()
            front.close()
        closed = True
        orphans = [p for p in all_pids if proc_alive(p)]

        totals = {
            "offered": c["offered"], "admitted": c["admitted"],
            "replied": c["replied"],
            "rejected": sum(c["rejected"].values()),
            "shed": sum(c["shed"].values()),
            "depth": c["depth"], "inflight": c["inflight"],
            "lost": report["lost"], "completed": report["completed"]}
        result = {
            "scenario": spec.name,
            "seed": spec.seed,
            "spec": spec.to_dict(),
            "capacity_rps": round(topo.capacity_rps, 1),
            "segments": segments,
            "report": report,
            "admission": c,
            "totals": totals,
            "orphans": orphans,
            "fault_log": fault_log,
        }
        if perhost is not None:
            result["perhost_replied_sum"] = perhost
        if mesh_stats is not None:
            result["mesh"] = mesh_stats
        if check:
            rec = recorder
            if rec is None and flight_dir:
                from nnstreamer_tpu.runtime.flightrec import \
                    FlightRecorder

                rec = FlightRecorder(flight_dir, cooldown_s=0.0)
            result["check"] = check_result(result, spec, recorder=rec)
        return result
    finally:
        if not closed:
            if world is not None:
                world.close()
            else:
                front.close()


def replay_scenario(result_or_spec: dict, **kw) -> dict:
    """Re-run the scenario a result (or bare spec dict) records, under
    the same root seed, and — when the input carries ``totals`` —
    compare the quiesce ledgers: ``replay_match`` is True iff
    offered/admitted/replied/rejected/shed all reproduce exactly."""
    d = result_or_spec.get("spec") \
        if isinstance(result_or_spec.get("spec"), dict) \
        else result_or_spec
    spec = ScenarioSpec.from_dict(d)
    second = run_scenario(spec, **kw)
    prev = result_or_spec.get("totals")
    if isinstance(prev, dict):
        keys = ("offered", "admitted", "replied", "rejected", "shed")
        diff = {k: [prev.get(k), second["totals"][k]] for k in keys
                if prev.get(k) != second["totals"][k]}
        second["replay_match"] = not diff
        if diff:
            second["replay_diff"] = diff
    return second
