"""Deterministic delta-debugging shrinker for failing scenarios.

Given a spec whose run violates an invariant, find a *smaller* spec
that still fails — the classic ddmin loop (Zeller & Hildebrandt,
"Simplifying and Isolating Failure-Inducing Input"), specialised to
the two axes a scenario can shrink along:

1. drop fault programs (ddmin over the fault tuple),
2. drop arrival programs (ddmin, keeping at least one — a scenario
   with no load proves nothing),
3. halve each surviving arrival's ``n`` while the failure persists.

Determinism is the whole point: labels are assigned by ORIGINAL
position (spec.py), so a survivor keeps its exact sub-seed — and
therefore its exact arrival trace and fault randomness — no matter
which siblings were deleted around it. Candidates are memoised by
canonical spec JSON, the predicate is injected (tests use synthetic
predicates; the CLI uses a live `run_scenario` check), and the whole
search is bounded by ``max_runs``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Sequence, Tuple

from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.scenario.spec import ScenarioSpec

log = get_logger("scenario.shrink")


class ShrinkBudgetExceeded(RuntimeError):
    """Raised when the predicate budget runs out mid-search."""


def _with(spec: ScenarioSpec, *, arrivals=None, faults=None
          ) -> ScenarioSpec:
    """A candidate spec with some programs removed / resized. Labels
    are already pinned on the survivors (frozen fields), so their
    sub-seeds ride along untouched."""
    return dataclasses.replace(
        spec,
        arrivals=tuple(arrivals if arrivals is not None
                       else spec.arrivals),
        faults=tuple(faults if faults is not None else spec.faults))


def _ddmin(items: Sequence, rebuild: Callable[[list], ScenarioSpec],
           fails: Callable[[ScenarioSpec], bool],
           min_keep: int = 0) -> List:
    """Minimise `items` under `fails(rebuild(subset))` — returns a
    subset that still fails, of at most the input size. Deterministic:
    chunks are scanned in order, no randomness."""
    items = list(items)
    if len(items) <= min_keep:
        return items
    granularity = 2
    while len(items) > min_keep:
        chunk = max(1, len(items) // granularity)
        shrunk = False
        i = 0
        while i < len(items):
            rest = items[:i] + items[i + chunk:]
            if len(rest) >= min_keep and fails(rebuild(rest)):
                items = rest            # this chunk was irrelevant
                granularity = max(2, granularity - 1)
                shrunk = True
            else:
                i += chunk
        if not shrunk:
            if chunk == 1:
                break
            granularity = min(len(items), granularity * 2)
    return items


def shrink(spec: ScenarioSpec,
           fails: Callable[[ScenarioSpec], bool], *,
           max_runs: int = 200) -> Tuple[ScenarioSpec, dict]:
    """Shrink `spec` to a minimal still-failing repro.

    `fails(candidate)` must return True when the candidate still
    reproduces the violation (the CLI wires this to
    ``not run_scenario(candidate)["check"]["ok"]``; tests inject
    synthetic predicates). The ORIGINAL spec must fail — ValueError
    otherwise (there is nothing to shrink toward).

    Returns ``(minimal_spec, stats)`` with ``stats = {"runs",
    "cache_hits", "initial_size", "final_size"}``. Deterministic:
    same spec + same predicate → same minimal repro, run for run.
    """
    cache: Dict[str, bool] = {}
    stats = {"runs": 0, "cache_hits": 0,
             "initial_size": spec.size(), "final_size": None}

    def check(candidate: ScenarioSpec) -> bool:
        key = candidate.to_json()
        if key in cache:
            stats["cache_hits"] += 1
            return cache[key]
        if stats["runs"] >= max_runs:
            raise ShrinkBudgetExceeded(
                f"shrink exceeded max_runs={max_runs}")
        stats["runs"] += 1
        verdict = bool(fails(candidate))
        cache[key] = verdict
        return verdict

    if not check(spec):
        raise ValueError("original spec does not fail — nothing to "
                         "shrink (predicate returned False)")

    cur = spec
    # axis 1: drop fault programs
    faults = _ddmin(cur.faults,
                    lambda fs: _with(cur, faults=fs), check)
    cur = _with(cur, faults=faults)
    # axis 2: drop arrival programs (a scenario needs ≥1 load segment
    # unless a tenant_flood fault survives to provide the load)
    has_flood = any(f.kind == "tenant_flood" for f in cur.faults)
    arrivals = _ddmin(cur.arrivals,
                      lambda ars: _with(cur, arrivals=ars), check,
                      min_keep=0 if has_flood else 1)
    cur = _with(cur, arrivals=arrivals)
    # axis 3: halve each surviving arrival's n while still failing
    progressed = True
    while progressed:
        progressed = False
        for i, a in enumerate(cur.arrivals):
            while a.n > 1:
                smaller = dataclasses.replace(a, n=a.n // 2)
                cand = _with(cur, arrivals=[
                    smaller if j == i else x
                    for j, x in enumerate(cur.arrivals)])
                if not check(cand):
                    break
                cur, a = cand, smaller
                progressed = True
    stats["final_size"] = cur.size()
    log.info("shrink: %s size %d -> %d in %d runs (%d cached)",
             spec.name, stats["initial_size"], stats["final_size"],
             stats["runs"], stats["cache_hits"])
    return cur, stats
