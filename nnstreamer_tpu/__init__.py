"""nnstreamer_tpu — a TPU-native streaming-AI framework.

A ground-up re-design of the capabilities of NNStreamer (reference:
/root/reference, v2.3.0) for TPU hardware: typed, shape-negotiated tensor
stream pipelines whose tensor-domain subgraphs compile to single XLA
computations executed via jit/PJRT, with pallas kernels for hot ops and
jax.sharding meshes for multi-chip scale-out.

Layer map (mirrors SURVEY.md §1, re-architected):

  tensor/    — tensor data model: dtypes, TensorInfo/TensorsSpec, dim
               strings, self-describing meta header, sparse codec, buffers
               (reference L1: gst/nnstreamer/include/tensor_typedef.h)
  core/      — config, subplugin registry, logging, errors (reference L2)
  graph/     — pipeline graph, gst-launch-style DSL, static shape/dtype
               negotiation (reference: GStreamer caps negotiation)
  runtime/   — push-model streaming scheduler (reference: GStreamer core)
  elements/  — pipeline elements (reference L3: gst/nnstreamer/elements/)
  backends/  — filter backends: XLA/jit, custom callables, pallas
               (reference L4: ext/nnstreamer/tensor_filter/*)
  models/    — flagship model zoo (MobileNetV2, SSD, PoseNet) in flax
  parallel/  — mesh sharding, pod batch dispatcher, ring attention
  edge/      — among-device offload: query client/server, pub/sub
               (reference L5: tensor_query/, gst/edge/, gst/mqtt/)
  trainer/   — on-device training element (reference: tensor_trainer type)
"""

__version__ = "0.1.0"

from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec, TensorFormat
from nnstreamer_tpu.tensor.buffer import TensorBuffer

__all__ = [
    "DType",
    "TensorInfo",
    "TensorsSpec",
    "TensorFormat",
    "TensorBuffer",
    "Pipeline",
    "parse_launch",
    "run_pipeline",
    "PipelineRunner",
    "Tracer",
    "register_custom_easy",
    "StreamError",
    "ErrorPolicy",
    "__version__",
]

_LAZY = {
    "Pipeline": ("nnstreamer_tpu.graph.pipeline", "Pipeline"),
    "parse_launch": ("nnstreamer_tpu.graph.parse", "parse_launch"),
    "run_pipeline": ("nnstreamer_tpu.runtime.scheduler", "run_pipeline"),
    "PipelineRunner": ("nnstreamer_tpu.runtime.scheduler", "PipelineRunner"),
    "Tracer": ("nnstreamer_tpu.runtime.tracing", "Tracer"),
    "register_custom_easy": ("nnstreamer_tpu.backends.custom",
                             "register_custom_easy"),
    # error handling is public API: catch StreamError around wait()/run(),
    # pass ErrorPolicy (or its string form) as any element's error-policy
    "StreamError": ("nnstreamer_tpu.core.errors", "StreamError"),
    "ErrorPolicy": ("nnstreamer_tpu.core.errors", "ErrorPolicy"),
}


def __getattr__(name):
    # lazy so `import nnstreamer_tpu` stays light for wire-codec-only use
    if name in _LAZY:
        import importlib

        mod, attr = _LAZY[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
