"""Tensor data model — the framework's L1 (no device, no jax dependency).

Reference parity: gst/nnstreamer/include/tensor_typedef.h,
nnstreamer_plugin_api_util_impl.c (dim strings, info compare/size),
gst_tensor_meta_info_* (self-describing per-tensor header),
gsttensor_sparseutil.c (COO sparse codec).
"""

from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec, TensorFormat, MediaType
from nnstreamer_tpu.tensor.meta import MetaHeader
from nnstreamer_tpu.tensor.buffer import TensorBuffer

__all__ = [
    "DType",
    "TensorInfo",
    "TensorsSpec",
    "TensorFormat",
    "MediaType",
    "MetaHeader",
    "TensorBuffer",
]
