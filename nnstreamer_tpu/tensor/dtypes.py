"""Tensor element dtypes.

Reference parity: the 11 dtypes of `_nns_tensor_type`
(gst/nnstreamer/include/tensor_typedef.h:131-146). We keep the reference's
wire enum ordering (so serialized streams are stable) and extend with
``bfloat16`` — the TPU-native compute dtype the reference lacks — at the
tail of the enum space.

This module is pure python + numpy; jax is never imported here so the
tensor core stays usable host-side (wire codecs, CLI tools) with no
device runtime.
"""

from __future__ import annotations

import enum

import numpy as np


class DType(enum.IntEnum):
    """Element type of a tensor. Values are the wire/enum encoding."""

    INT32 = 0
    UINT32 = 1
    INT16 = 2
    UINT16 = 3
    INT8 = 4
    UINT8 = 5
    FLOAT64 = 6
    FLOAT32 = 7
    INT64 = 8
    UINT64 = 9
    FLOAT16 = 10
    # TPU extension (not in the reference enum): XLA's preferred matmul dtype.
    BFLOAT16 = 11

    @property
    def np_dtype(self) -> np.dtype:
        try:
            return _NP_DTYPES[self]
        except KeyError:
            raise TypeError(
                f"dtype {self.type_name} has no host numpy representation on "
                f"this system (bfloat16 requires the ml_dtypes package)"
            ) from None

    @property
    def itemsize(self) -> int:
        return self.np_dtype.itemsize

    @property
    def type_name(self) -> str:
        return _NAMES[self]

    @classmethod
    def from_name(cls, name: str) -> "DType":
        try:
            return _BY_NAME[name.strip().lower()]
        except KeyError:
            raise ValueError(
                f"unknown tensor dtype {name!r}; valid names: {sorted(_BY_NAME)}"
            ) from None

    @classmethod
    def from_np(cls, dtype) -> "DType":
        dtype = np.dtype(dtype) if not _is_ml_dtype(dtype) else dtype
        key = str(dtype)
        try:
            return _BY_NAME[key]
        except KeyError:
            raise ValueError(f"no tensor DType for numpy dtype {dtype!r}") from None


def _is_ml_dtype(dtype) -> bool:
    return str(dtype) == "bfloat16"


def _bfloat16_np():
    """bfloat16 numpy dtype via ml_dtypes (vendored with jax)."""
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


_NP_DTYPES = {
    DType.INT32: np.dtype(np.int32),
    DType.UINT32: np.dtype(np.uint32),
    DType.INT16: np.dtype(np.int16),
    DType.UINT16: np.dtype(np.uint16),
    DType.INT8: np.dtype(np.int8),
    DType.UINT8: np.dtype(np.uint8),
    DType.FLOAT64: np.dtype(np.float64),
    DType.FLOAT32: np.dtype(np.float32),
    DType.INT64: np.dtype(np.int64),
    DType.UINT64: np.dtype(np.uint64),
    DType.FLOAT16: np.dtype(np.float16),
}
try:  # bfloat16 requires ml_dtypes; degrade gracefully without it.
    _NP_DTYPES[DType.BFLOAT16] = _bfloat16_np()
except ImportError:  # pragma: no cover
    pass

_NAMES = {
    DType.INT32: "int32",
    DType.UINT32: "uint32",
    DType.INT16: "int16",
    DType.UINT16: "uint16",
    DType.INT8: "int8",
    DType.UINT8: "uint8",
    DType.FLOAT64: "float64",
    DType.FLOAT32: "float32",
    DType.INT64: "int64",
    DType.UINT64: "uint64",
    DType.FLOAT16: "float16",
    DType.BFLOAT16: "bfloat16",
}

_BY_NAME = {name: dt for dt, name in _NAMES.items()}
