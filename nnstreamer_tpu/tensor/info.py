"""Tensor stream type descriptors — the negotiation currency of the graph.

Reference parity:
- `GstTensorInfo` / `GstTensorsInfo` / `GstTensorsConfig`
  (gst/nnstreamer/include/tensor_typedef.h:229-258)
- dim-string parse/print and info compare/size helpers
  (gst/nnstreamer/nnstreamer_plugin_api_util_impl.c)
- formats static/flexible/sparse (tensor_typedef.h:185-193)

Design differences from the reference (TPU-first):
- Shapes are stored in **row-major (numpy/XLA) order** with arbitrary rank,
  because that is what jit/pallas consume. The reference's dim strings
  ("3:224:224:1", innermost-first, rank≤4 padded with 1s) are accepted and
  produced by `from_dim_string`/`to_dim_string` for CLI parity.
- A `TensorsSpec` is immutable and hashable → usable directly as a jit
  static argument and as a compilation-cache key for bucketed recompiles.
"""

from __future__ import annotations

import enum
import math
from dataclasses import dataclass, replace
from fractions import Fraction
from typing import Sequence, Tuple

from nnstreamer_tpu.tensor.dtypes import DType

#: The reference caps at 16 tensors per frame (tensor_typedef.h:35); we keep
#: the same limit so multi-tensor wire frames stay bounded.
MAX_TENSORS_PER_FRAME = 16

#: Reference dim-string rank limit is 4 (classic) / 16 (meta header,
#: tensor_typedef.h:34,:268-296). We accept up to 16 in strings.
MAX_RANK = 16


class TensorFormat(enum.IntEnum):
    """Stream data format (tensor_typedef.h:185-193)."""

    STATIC = 0    # shapes fixed by negotiation; zero per-frame metadata
    FLEXIBLE = 1  # every tensor carries a self-describing MetaHeader
    SPARSE = 2    # COO-encoded payload after a MetaHeader


class MediaType(enum.IntEnum):
    """Origin media domain of a tensor stream (for converters/decoders)."""

    TENSOR = 0
    VIDEO = 1
    AUDIO = 2
    TEXT = 3
    OCTET = 4
    ANY = 5


def parse_dim_string(s: str) -> Tuple[int, ...]:
    """Parse a reference-style dim string into a row-major shape.

    "3:224:224:1" (channel:width:height:batch, innermost first) →
    (1, 224, 224, 3) (row-major). Trailing reference dims of 1 are
    preserved; use `shapes_compatible` for rank-insensitive comparison.
    """
    if not s.strip():
        raise ValueError(f"empty tensor dimension string: {s!r}")
    parts = s.strip().split(":")
    if any(p == "" for p in parts):
        raise ValueError(
            f"malformed dimension string {s!r}: empty segment (did you mean "
            f"'3:224:224:1'?)"
        )
    if len(parts) > MAX_RANK:
        raise ValueError(
            f"dimension string {s!r} has rank {len(parts)} > limit {MAX_RANK}"
        )
    dims = []
    for p in parts:
        try:
            v = int(p)
        except ValueError:
            raise ValueError(
                f"invalid dimension {p!r} in {s!r}: dimensions must be "
                f"positive integers separated by ':' (e.g. '3:224:224:1')"
            ) from None
        if v <= 0:
            raise ValueError(
                f"invalid dimension {v} in {s!r}: dimensions must be >= 1"
            )
        dims.append(v)
    return tuple(reversed(dims))


def to_dim_string(shape: Sequence[int]) -> str:
    """Row-major shape → reference-style innermost-first dim string."""
    return ":".join(str(d) for d in reversed(tuple(shape)))


def shapes_compatible(a: Sequence[int], b: Sequence[int]) -> bool:
    """Shape equality ignoring leading (outermost) size-1 dims.

    Mirrors the reference treating trailing 1s in its dim arrays as
    padding (nnstreamer_plugin_api_util_impl.c dim compare).
    """
    def strip(s):
        s = tuple(s)
        while len(s) > 1 and s[0] == 1:
            s = s[1:]
        return s
    return strip(a) == strip(b)


@dataclass(frozen=True)
class TensorInfo:
    """Shape/dtype/name of one tensor in a stream (GstTensorInfo analog)."""

    shape: Tuple[int, ...]
    dtype: DType = DType.FLOAT32
    name: str = ""

    def __post_init__(self):
        object.__setattr__(self, "shape", tuple(int(d) for d in self.shape))
        if not isinstance(self.dtype, DType):
            object.__setattr__(self, "dtype", DType.from_name(str(self.dtype)))
        for d in self.shape:
            if d <= 0:
                raise ValueError(f"non-positive dim in shape {self.shape}")

    @classmethod
    def from_dim_string(cls, dims: str, dtype="float32", name: str = "") -> "TensorInfo":
        dt = dtype if isinstance(dtype, DType) else DType.from_name(str(dtype))
        return cls(shape=parse_dim_string(dims), dtype=dt, name=name)

    @property
    def num_elements(self) -> int:
        return math.prod(self.shape) if self.shape else 1

    @property
    def nbytes(self) -> int:
        """Byte size of one frame (gst_tensor_info_get_size analog)."""
        return self.num_elements * self.dtype.itemsize

    def to_dim_string(self) -> str:
        return to_dim_string(self.shape)

    def is_compatible(self, other: "TensorInfo") -> bool:
        return (
            self.dtype == other.dtype
            and shapes_compatible(self.shape, other.shape)
        )

    def __str__(self) -> str:
        n = f" name={self.name!r}" if self.name else ""
        return f"Tensor({self.dtype.type_name}[{','.join(map(str, self.shape))}]{n})"


@dataclass(frozen=True)
class TensorsSpec:
    """Type of a whole tensor stream (GstTensorsConfig analog).

    Immutable + hashable: used as the negotiation result on every link and
    as a jit static-arg / compile-cache key.
    """

    tensors: Tuple[TensorInfo, ...]
    format: TensorFormat = TensorFormat.STATIC
    rate: Fraction = Fraction(0, 1)  # frames/sec; 0/1 = unknown/unfixed
    #: >0: the stream carries dynamic micro-batches (tensor_batch
    #: upstream) of up to this many frames coalesced on a leading batch
    #: axis. `tensors` keeps the PER-FRAME shapes — the batch axis is a
    #: runtime property (each buffer's occupancy varies with load), not
    #: a type property, so downstream unbatch/decoders still negotiate
    #: per-frame specs. Elements that are not batch-aware refuse such
    #: streams at negotiation (Element.expect_tensors).
    dyn_batch: int = 0

    def __post_init__(self):
        object.__setattr__(self, "tensors", tuple(self.tensors))
        if len(self.tensors) > MAX_TENSORS_PER_FRAME:
            raise ValueError(
                f"{len(self.tensors)} tensors per frame exceeds limit "
                f"{MAX_TENSORS_PER_FRAME}"
            )
        if not isinstance(self.rate, Fraction):
            object.__setattr__(self, "rate", Fraction(self.rate))

    # -- constructors ------------------------------------------------------
    @classmethod
    def of(cls, *infos: TensorInfo, **kw) -> "TensorsSpec":
        return cls(tensors=tuple(infos), **kw)

    @classmethod
    def from_strings(cls, dims: str, types: str = "float32", names: str = "",
                     rate=Fraction(0, 1), format=TensorFormat.STATIC) -> "TensorsSpec":
        """Build from reference-style comma-separated property strings.

        e.g. dims="3:224:224:1,1001:1", types="uint8,float32".
        (tensor_filter properties input/inputtype/inputname,
        tensor_filter_common.c:899-1017)
        """
        dim_list = [d for d in dims.split(",") if d.strip()]
        type_list = [t for t in types.split(",") if t.strip()]
        name_list = names.split(",") if names else []
        if len(type_list) == 1 and len(dim_list) > 1:
            type_list = type_list * len(dim_list)
        if len(type_list) != len(dim_list):
            raise ValueError(
                f"dimension list has {len(dim_list)} entries but type list "
                f"has {len(type_list)}: {dims!r} vs {types!r}"
            )
        infos = []
        for i, d in enumerate(dim_list):
            nm = name_list[i].strip() if i < len(name_list) else ""
            infos.append(TensorInfo.from_dim_string(d.strip(), type_list[i].strip(), nm))
        return cls(tensors=tuple(infos), rate=rate, format=format)

    # -- queries -----------------------------------------------------------
    @property
    def num_tensors(self) -> int:
        return len(self.tensors)

    @property
    def nbytes(self) -> int:
        return sum(t.nbytes for t in self.tensors)

    def is_compatible(self, other: "TensorsSpec") -> bool:
        """Structural compatibility (gst_tensors_info_is_equal analog).

        Flexible streams match anything tensor-typed; static streams
        require per-tensor dtype+shape compatibility.
        """
        if self.format == TensorFormat.FLEXIBLE or other.format == TensorFormat.FLEXIBLE:
            return True
        if self.dyn_batch != other.dyn_batch:
            # a micro-batched stream is wire-incompatible with a
            # per-frame one: buffers carry an extra (variable) batch axis
            return False
        if self.format != other.format:
            # STATIC vs SPARSE payloads are wire-incompatible; only FLEXIBLE
            # streams self-describe per buffer (reference:
            # gst_tensors_config_is_equal compares format too).
            return False
        if self.num_tensors != other.num_tensors:
            return False
        return all(a.is_compatible(b) for a, b in zip(self.tensors, other.tensors))

    def with_rate(self, rate) -> "TensorsSpec":
        return replace(self, rate=Fraction(rate))

    def to_strings(self):
        """→ (dims, types, names) reference-style property strings."""
        return (
            ",".join(t.to_dim_string() for t in self.tensors),
            ",".join(t.dtype.type_name for t in self.tensors),
            ",".join(t.name for t in self.tensors),
        )

    def __str__(self) -> str:
        body = ", ".join(str(t) for t in self.tensors)
        fmt = self.format.name.lower()
        r = f" @{self.rate}fps" if self.rate else ""
        db = f" dyn_batch<={self.dyn_batch}" if self.dyn_batch else ""
        return f"TensorsSpec[{fmt}]({body}{r}{db})"
