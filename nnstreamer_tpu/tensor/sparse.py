"""Sparse tensor wire codec (COO over flat indices).

Reference parity: gst/nnstreamer/elements/gsttensor_sparseutil.c —
`gst_tensor_sparse_from_dense` (:116) / `gst_tensor_sparse_to_dense` (:27).
Wire frame = MetaHeader(format=SPARSE, extra=nnz) + values[nnz] (element
dtype) + indices[nnz] (uint32 flat row-major offsets).

Host-side codec uses numpy; `to_dense_jax`/`from_dense_topk_jax` in
backends/pallas_ops.py provide device-side scatter/gather equivalents.
"""

from __future__ import annotations

import math
from typing import Tuple

import numpy as np

#: Refuse to materialize dense outputs larger than this from wire data; a
#: corrupt/malicious header must not be able to OOM the pipeline process.
MAX_DENSE_BYTES = 1 << 31  # 2 GiB

from nnstreamer_tpu.tensor.info import MediaType, TensorFormat
from nnstreamer_tpu.tensor.meta import MetaHeader


def sparse_encode(dense: np.ndarray) -> bytes:
    """Dense array → sparse wire frame. Worth it when density < ~50%."""
    flat = np.ascontiguousarray(dense).reshape(-1)
    idx = np.flatnonzero(flat).astype(np.uint32)
    values = flat[idx]
    hdr = MetaHeader(
        shape=tuple(dense.shape) or (1,),
        dtype=_dtype_of(dense),
        format=TensorFormat.SPARSE,
        media=MediaType.TENSOR,
        extra=int(idx.size),
    )
    return hdr.pack() + values.tobytes() + idx.tobytes()


def sparse_decode(frame: bytes) -> np.ndarray:
    """Sparse wire frame → dense array."""
    hdr, off = MetaHeader.unpack(frame)
    if hdr.format != TensorFormat.SPARSE:
        raise ValueError(f"not a sparse tensor frame (format={hdr.format.name})")
    nnz = hdr.extra
    np_dt = hdr.dtype.np_dtype
    total = math.prod(hdr.shape)
    if total * np_dt.itemsize > MAX_DENSE_BYTES:
        raise ValueError(
            f"sparse frame dense size {total * np_dt.itemsize} bytes (shape "
            f"{hdr.shape}) exceeds decode limit {MAX_DENSE_BYTES}; refusing "
            f"allocation for a likely-corrupt header"
        )
    if nnz > total:
        raise ValueError(
            f"corrupt sparse frame: nnz {nnz} exceeds element count {total} "
            f"for shape {hdr.shape}"
        )
    vbytes = nnz * np_dt.itemsize
    need = off + vbytes + nnz * 4
    if len(frame) < need:
        raise ValueError(f"truncated sparse frame: have {len(frame)}, need {need}")
    values = np.frombuffer(frame, dtype=np_dt, count=nnz, offset=off)
    idx = np.frombuffer(frame, dtype=np.uint32, count=nnz, offset=off + vbytes)
    if nnz and int(idx.max()) >= total:
        raise ValueError(
            f"corrupt sparse frame: index {int(idx.max())} out of range for "
            f"{total} elements (shape {hdr.shape})"
        )
    dense = np.zeros(total, dtype=np_dt)
    dense[idx] = values
    return dense.reshape(hdr.shape)


def sparse_nbytes(dense: np.ndarray) -> Tuple[int, int]:
    """→ (sparse wire size, dense size) for the enc/dec worth-it check."""
    nnz = int(np.count_nonzero(dense))
    hdr = MetaHeader(
        shape=tuple(dense.shape) or (1,),
        dtype=_dtype_of(dense),
        format=TensorFormat.SPARSE,
        extra=nnz,
    )
    return hdr.header_size + nnz * (dense.dtype.itemsize + 4), dense.nbytes


def _dtype_of(arr: np.ndarray):
    from nnstreamer_tpu.tensor.dtypes import DType

    return DType.from_np(arr.dtype)
