"""TensorBuffer — the unit of data flowing through a pipeline.

Reference parity: a GstBuffer holding up to 16 GstTensorMemory chunks plus
PTS/duration (tensor_typedef.h:216-223, :35). Re-designed for TPU:

- Payloads are arrays, not byte blobs: numpy on the host path, `jax.Array`
  once a filter has staged them on device. Elements never copy; they pass
  array references (the reference achieves the same with GstMemory
  ref-counting and map/unmap).
- A buffer downstream of a filter may keep its tensors on device; the
  conversion back to host happens lazily at a sink/decoder boundary, so a
  converter→transform→filter→decoder chain does exactly one H2D and one
  D2H transfer per frame.
- `meta` carries out-of-band routing info (e.g. edge client_id — the
  GstMetaQuery analog, gst/nnstreamer/tensor_meta.c).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorFormat, TensorInfo, TensorsSpec


def _is_jax_array(x) -> bool:
    # Duck-typed so the tensor core never imports jax.
    return type(x).__module__.startswith("jax")


@dataclass
class TensorBuffer:
    tensors: Tuple[Any, ...]              # numpy arrays or jax.Arrays
    pts: Optional[int] = None             # presentation time, ns
    duration: Optional[int] = None        # ns
    format: TensorFormat = TensorFormat.STATIC
    meta: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        self.tensors = tuple(self.tensors)

    # -- constructors ------------------------------------------------------
    @classmethod
    def of(cls, *arrays, pts: Optional[int] = None, **kw) -> "TensorBuffer":
        return cls(tensors=tuple(arrays), pts=pts, **kw)

    # -- structure ---------------------------------------------------------
    @property
    def num_tensors(self) -> int:
        return len(self.tensors)

    def spec(self) -> TensorsSpec:
        """Runtime type of this buffer (for validation against negotiation)."""
        infos = []
        for t in self.tensors:
            infos.append(TensorInfo(shape=tuple(t.shape), dtype=DType.from_np(t.dtype)))
        return TensorsSpec(tensors=tuple(infos), format=self.format)

    def matches(self, spec: TensorsSpec) -> bool:
        return self.spec().is_compatible(spec)

    # -- device residency --------------------------------------------------
    @property
    def on_device(self) -> bool:
        return any(_is_jax_array(t) for t in self.tensors)

    def to_host(self) -> "TensorBuffer":
        """Materialize all tensors as numpy (the one D2H point per frame)."""
        if not self.on_device:
            return self
        host = tuple(np.asarray(t) for t in self.tensors)
        return replace(self, tensors=host, meta=dict(self.meta))

    def prefetch_host(self) -> "TensorBuffer":
        """Start async D2H copies for device tensors (copy_to_host_async).

        Non-blocking; a later to_host() then completes from the host
        staging buffer instead of paying the full transfer latency. On
        remote/tunneled devices this overlaps transfers with compute of
        other in-flight frames (measured ~17× e2e on the label pipeline);
        the scheduler calls it when a buffer is queued toward a
        host-consuming element (Element.WANTS_HOST)."""
        for t in self.tensors:
            fn = getattr(t, "copy_to_host_async", None)
            if fn is not None:
                try:
                    fn()
                except Exception:
                    pass   # best-effort: to_host() remains correct
        return self

    # -- functional updates ------------------------------------------------
    def with_tensors(self, tensors: Sequence[Any], **kw) -> "TensorBuffer":
        """New buffer with same timing, copied meta, different payload."""
        kw.setdefault("meta", dict(self.meta))
        return replace(self, tensors=tuple(tensors), **kw)

    def with_meta(self, **meta) -> "TensorBuffer":
        merged = dict(self.meta)
        merged.update(meta)
        return replace(self, meta=merged)

    def subset(self, indices: Sequence[int]) -> "TensorBuffer":
        """Pick tensors by index (input/output-combination analog,
        tensor_filter.c:697-735)."""
        if any(i < 0 or i >= self.num_tensors for i in indices):
            raise IndexError(
                f"tensor index out of range: buffer has {self.num_tensors} "
                f"tensors, requested {list(indices)}"
            )
        picked = tuple(self.tensors[i] for i in indices)
        return replace(self, tensors=picked, meta=dict(self.meta))

    def __repr__(self) -> str:
        shapes = ",".join(
            f"{np.dtype(t.dtype).name if not _is_jax_array(t) else t.dtype.name}"
            f"{list(t.shape)}" for t in self.tensors
        )
        where = "dev" if self.on_device else "host"
        return f"TensorBuffer({shapes} @{self.pts} {where})"


def now_ns() -> int:
    return time.monotonic_ns()
