"""Self-describing per-tensor wire header for flexible/sparse streams.

Reference parity: `GstTensorMetaInfo` + gst_tensor_meta_info_append_header /
parse (gst/nnstreamer/include/tensor_typedef.h:268-296,
nnstreamer_plugin_api_impl.c:1397). A flexible-format stream opts out of
static negotiation by prefixing every tensor payload with this header; the
sparse codec (sparse.py) adds an nnz field and COO payload layout.

Wire layout (little-endian uint32 fields, variable length):

  magic     'TPUT' (0x54505554)
  version   1
  dtype     DType enum value
  format    TensorFormat enum value
  media     MediaType enum value
  rank      r (1..16)
  dims[r]   row-major shape
  extra     sparse: nnz; otherwise 0
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Tuple

from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import MAX_RANK, MediaType, TensorFormat, TensorInfo

MAGIC = 0x54505554  # 'TPUT'
VERSION = 1
_FIXED = struct.Struct("<6I")  # magic, version, dtype, format, media, rank


@dataclass(frozen=True)
class MetaHeader:
    shape: Tuple[int, ...]
    dtype: DType
    format: TensorFormat = TensorFormat.FLEXIBLE
    media: MediaType = MediaType.TENSOR
    extra: int = 0  # sparse: number of non-zeros

    @classmethod
    def for_info(cls, info: TensorInfo, format=TensorFormat.FLEXIBLE,
                 media=MediaType.TENSOR, extra: int = 0) -> "MetaHeader":
        return cls(shape=info.shape, dtype=info.dtype, format=format,
                   media=media, extra=extra)

    def to_info(self) -> TensorInfo:
        return TensorInfo(shape=self.shape, dtype=self.dtype)

    @property
    def header_size(self) -> int:
        return _FIXED.size + 4 * len(self.shape) + 4

    def pack(self) -> bytes:
        rank = len(self.shape)
        if not 1 <= rank <= MAX_RANK:
            raise ValueError(f"rank {rank} out of range 1..{MAX_RANK}")
        return (
            _FIXED.pack(MAGIC, VERSION, int(self.dtype), int(self.format),
                        int(self.media), rank)
            + struct.pack(f"<{rank}I", *self.shape)
            + struct.pack("<I", self.extra)
        )

    @classmethod
    def unpack(cls, data: bytes) -> Tuple["MetaHeader", int]:
        """Parse a header from the front of `data` → (header, bytes consumed)."""
        if len(data) < _FIXED.size:
            raise ValueError(
                f"buffer too small for tensor meta header: {len(data)} bytes "
                f"< fixed header size {_FIXED.size}"
            )
        magic, version, dtype, fmt, media, rank = _FIXED.unpack_from(data, 0)
        if magic != MAGIC:
            raise ValueError(
                f"bad tensor meta magic 0x{magic:08x} (expected 0x{MAGIC:08x}); "
                f"is this a flexible-format tensor stream?"
            )
        if version != VERSION:
            raise ValueError(f"unsupported tensor meta version {version}")
        if not 1 <= rank <= MAX_RANK:
            raise ValueError(f"corrupt tensor meta: rank {rank}")
        need = _FIXED.size + 4 * rank + 4
        if len(data) < need:
            raise ValueError(
                f"truncated tensor meta header: have {len(data)}, need {need}"
            )
        shape = struct.unpack_from(f"<{rank}I", data, _FIXED.size)
        (extra,) = struct.unpack_from("<I", data, _FIXED.size + 4 * rank)
        hdr = cls(shape=tuple(shape), dtype=DType(dtype),
                  format=TensorFormat(fmt), media=MediaType(media), extra=extra)
        return hdr, need
