"""ctypes bindings for the native runtime library (native/libnnstpu.so).

Build it with `make -C native` (g++, no other deps). Everything here
degrades gracefully: `available()` is False when the .so is missing and
callers raise an actionable error telling the user to build it.

Components:
- ShmRing — shared-memory SPSC frame ring (native/nt_shmring.cc): the
  zero-copy local IPC transport behind ipc_sink/ipc_src.
- wire_frame_size — native wire-frame validator (native/nt_wire.cc).
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path
from typing import Optional

from nnstreamer_tpu.core.errors import StreamError

_LIB_PATHS = (
    Path(__file__).resolve().parents[2] / "native" / "libnnstpu.so",
    Path(os.environ.get("NNSTPU_NATIVE_LIB", "")),
)

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    for p in _LIB_PATHS:
        if p and p.is_file():
            try:
                lib = ctypes.CDLL(str(p))
            except OSError:
                continue
            lib.nt_ring_create.restype = ctypes.c_void_p
            lib.nt_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
            lib.nt_ring_open.restype = ctypes.c_void_p
            lib.nt_ring_open.argtypes = [ctypes.c_char_p]
            lib.nt_ring_write.restype = ctypes.c_int
            lib.nt_ring_write.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                ctypes.c_int]
            lib.nt_ring_next_len.restype = ctypes.c_int64
            lib.nt_ring_next_len.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.nt_ring_read.restype = ctypes.c_int64
            lib.nt_ring_read.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
            lib.nt_ring_mark_closed.argtypes = [ctypes.c_void_p]
            lib.nt_ring_close.argtypes = [ctypes.c_void_p]
            lib.nt_ring_unlink.argtypes = [ctypes.c_char_p]
            lib.nt_ring_capacity.restype = ctypes.c_uint64
            lib.nt_ring_capacity.argtypes = [ctypes.c_void_p]
            lib.nt_ring_used.restype = ctypes.c_uint64
            lib.nt_ring_used.argtypes = [ctypes.c_void_p]
            lib.nt_wire_frame_size.restype = ctypes.c_int64
            lib.nt_wire_frame_size.argtypes = [ctypes.c_char_p,
                                               ctypes.c_uint64]
            _lib = lib
            break
    return _lib


def available() -> bool:
    return _load() is not None


def require():
    lib = _load()
    if lib is None:
        raise StreamError(
            "the native runtime library is not built; run `make -C native` "
            "in the repository root (needs only g++) or set "
            "NNSTPU_NATIVE_LIB to a prebuilt libnnstpu.so")
    return lib


def wire_frame_size(data: bytes) -> int:
    """→ total frame bytes, 0 = incomplete, -1 = corrupt (native path)."""
    return int(require().nt_wire_frame_size(data, len(data)))


class ShmRing:
    """SPSC frame ring in shared memory (producer OR consumer side)."""

    def __init__(self, name: str, *, create: bool, capacity: int = 1 << 22):
        self._lib = require()
        self.name = name
        self._creator = create
        if create:
            self._h = self._lib.nt_ring_create(name.encode(), capacity)
        else:
            self._h = self._lib.nt_ring_open(name.encode())
        if not self._h:
            verb = "create" if create else "open"
            raise StreamError(
                f"cannot {verb} shared-memory ring {name!r}"
                + ("" if create else " — is the producer pipeline running?"))

    def write(self, frame: bytes, timeout_ms: int = 10_000) -> None:
        rc = self._lib.nt_ring_write(self._h, frame, len(frame), timeout_ms)
        if rc == -2:
            raise StreamError(
                f"frame of {len(frame)} bytes exceeds ring capacity "
                f"{self.capacity} (raise ipc_sink capacity=)")
        if rc == -4:
            raise StreamError(
                f"ring {self.name!r} full for {timeout_ms}ms — consumer "
                f"stalled or gone")
        if rc != 0:
            raise StreamError(f"ring {self.name!r} closed or broken ({rc})")

    def read(self, timeout_ms: int = 100) -> Optional[bytes]:
        """→ one frame, None on timeout; raises EOFError at EOS
        (callers: `except EOFError`, see elements/ipc.py)."""
        n = self._lib.nt_ring_next_len(self._h, timeout_ms)
        if n == 0:
            return None
        if n < 0:
            raise EOFError(f"ring {self.name!r} closed")
        buf = ctypes.create_string_buffer(int(n))
        got = self._lib.nt_ring_read(self._h, buf, int(n))
        if got < 0:
            if got == -1:
                raise EOFError(f"ring {self.name!r} closed")
            raise StreamError(f"ring {self.name!r} read error ({got})")
        return buf.raw[:got]

    @property
    def capacity(self) -> int:
        return int(self._lib.nt_ring_capacity(self._h))

    @property
    def used(self) -> int:
        return int(self._lib.nt_ring_used(self._h))

    def close_write(self) -> None:
        """Producer EOS: wake readers, they drain then see EOF."""
        self._lib.nt_ring_mark_closed(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.nt_ring_close(self._h)
            self._h = None
            if self._creator:
                self._lib.nt_ring_unlink(self.name.encode())
