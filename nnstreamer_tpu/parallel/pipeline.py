"""Pipeline parallelism — GPipe-style microbatched execution over the
``pp`` mesh axis.

Nothing in the reference corresponds to this (its multi-device story is
per-frame TCP offload, SURVEY.md §5.8); this is the TPU-native way to
run a model deeper than one chip's HBM: stages live on different devices
and activations flow stage-to-stage over ICI.

Design (collective SPMD, not per-device programs):
- stage parameters are *stacked* on a leading stage dim and sharded over
  ``pp``, so inside `shard_map` every device holds exactly its stage's
  weights;
- the input is split into microbatches; a `fori_loop` runs the classic
  GPipe schedule: at step t, stage s computes microbatch (t - s), then
  every stage ships its activation to the next stage with one
  `lax.ppermute` (nearest-neighbor ICI hop);
- the bubble is (n_stages - 1) of (n_micro + n_stages - 1) steps — more
  microbatches amortize it;
- stages must be shape-preserving (activation shape constant across
  stages), the standard homogeneous-pipeline restriction.

The final outputs are collected on the last stage and `psum`-broadcast
so the caller gets a replicated array; a production serving path would
keep them on the last stage (donate into the next pipeline step).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from nnstreamer_tpu.parallel._compat import shard_map


def stack_stage_params(per_stage_params) -> Any:
    """[stage0_tree, stage1_tree, ...] → one tree with leading stage dim
    (what pipeline_apply expects, sharded P("pp") on dim 0)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params)


def pipeline_apply(stage_fn: Callable, stage_params, x, *, mesh: Mesh,
                   axis: str = "pp"):
    """Run x through n_stages of `stage_fn`, pipelined over `axis`.

    stage_fn(params, a) -> a  (shape-preserving)
    stage_params: pytree, every leaf (n_stages, ...), sharded over axis
    x: (n_micro, mb, ...) microbatched input, replicated over axis
    → (n_micro, mb, ...) outputs, replicated over axis.
    """
    n = mesh.shape[axis]
    n_micro = x.shape[0]
    if n_micro < 1:
        raise ValueError("pipeline_apply needs at least one microbatch")

    def local(params, xs):
        params = jax.tree_util.tree_map(lambda a: a[0], params)  # my stage
        idx = lax.axis_index(axis)
        total = n_micro + n - 1
        state = jnp.zeros_like(xs[0])       # activation register from prev
        buf = jnp.zeros_like(xs)            # last stage's results

        def body(t, carry):
            state, buf = carry
            mb = jnp.clip(t, 0, n_micro - 1)
            x_in = jnp.where(idx == 0, xs[mb], state)
            y = stage_fn(params, x_in)
            # last stage owns microbatch t-(n-1) once the fill completes
            out_i = jnp.clip(t - (n - 1), 0, n_micro - 1)
            keep = (idx == n - 1) & (t >= n - 1)
            buf = buf.at[out_i].set(jnp.where(keep, y, buf[out_i]))
            # one ICI hop: every stage feeds the next (ring closes the
            # permutation; stage 0 ignores what it receives from n-1)
            state = lax.ppermute(y, axis,
                                 [(j, (j + 1) % n) for j in range(n)])
            return state, buf

        _, buf = lax.fori_loop(0, total, body, (state, buf))
        # broadcast the last stage's buffer to everyone (replicated out)
        return lax.psum(jnp.where(idx == n - 1, buf, jnp.zeros_like(buf)),
                        axis)

    # everything not named `axis` stays replicated in this collective;
    # callers compose dp outside (vmap/jit over a dp-sharded batch)
    return shard_map(
        local, mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        check_vma=False,
    )(stage_params, x)


def reference_pipeline(stage_fn: Callable, per_stage_params, x):
    """Serial ground truth: fold the stages over every microbatch."""
    def one(mb):
        a = mb
        for p in per_stage_params:
            a = stage_fn(p, a)
        return a

    return jnp.stack([one(x[i]) for i in range(x.shape[0])], axis=0)
