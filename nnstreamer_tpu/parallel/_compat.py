"""jax API compatibility shims shared by the parallel/ modules.

One blessed copy of the `shard_map` import dance (previously pasted
into moe.py, ring_attention.py and pipeline.py): jax >= 0.5 exports
`jax.shard_map` with the `check_vma` keyword; older releases keep it in
`jax.experimental.shard_map` under the `check_rep` spelling. Importers
write `from nnstreamer_tpu.parallel._compat import shard_map` and use
the modern keyword everywhere.
"""

from __future__ import annotations

try:
    from jax import shard_map
except ImportError:                     # jax < 0.5 keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, **kw):            # the experimental API spells
        kw["check_rep"] = kw.pop("check_vma", True)   # check_vma check_rep
        return _shard_map_exp(f, **kw)

__all__ = ["shard_map"]
