"""Pod batch dispatcher — mesh-sharded streaming inference.

North-star replacement for the reference's per-frame TCP request/reply
offload (`tensor_query_client` → server, SURVEY.md §3.4): instead of one
frame per round-trip, frames from any number of streams are coalesced
into batches, sharded over the mesh's dp axis, and executed as one pjit
computation whose collectives ride ICI. Off-pod clients still reach this
through edge/ (parity transport); on-pod, elements call it directly.

Flow: submit(frame) → future; a collector thread packs up to
`max_batch` frames (or flushes after `max_delay_ms`), pads the batch to
the bucket size (static shapes — no recompiles), runs the sharded fn,
and resolves futures with per-frame outputs.

The collector/completion machinery lives in `BatchCore`, shared with
the serving placement layer (serving/placement.py): each data-parallel
replica there is one BatchCore bound to one device, so per-chip queues
get the same linger/pad/overlap-D2H/count-before-resolve discipline the
mesh path has.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.core.log import get_logger

log = get_logger("parallel.dispatch")


class BatchCore:
    """Collector + completion stages behind a submit() → Future API.

    `run(batch, n)` is the device computation: `batch` is a numpy array
    already padded to one of the compiled `buckets` sizes, `n` the
    number of real frames at its front; it returns one device array or
    a tuple of them, resolved per-frame as host tuples.

    `capacity` bounds the replica queue: submit() raises a typed
    StreamError once `outstanding` (accepted but unresolved frames)
    reaches it, so a slow chip backpressures its callers instead of
    buffering unboundedly (0 = unbounded, the mesh dispatcher's
    historical behaviour).

    `raw=True` switches the payload currency from stackable arrays to
    opaque invocation payloads: no squeeze/stack/pad, `run(items, n)`
    gets the payload list verbatim and returns one output tuple per
    item. The serving replica path uses this — its unit of routing is
    a whole filter invocation (a tensor tuple or a micro-batch), not a
    single frame.

    Conservation contract (same as the worker pool's): counters are
    bumped under `_lock` BEFORE futures resolve, so a caller that
    observed its result and then read stats() always sees its own
    frame counted; every accepted frame ends in exactly one of
    frames / errors / shutdown-failed.
    """

    def __init__(self, run: Callable[[Any, int], Any],
                 buckets: Sequence[int], max_delay_s: float, *,
                 capacity: int = 0, raw: bool = False,
                 name: str = "dispatch"):
        self._run = run
        self.buckets = sorted({int(b) for b in buckets})
        if not self.buckets or self.buckets[0] < 1:
            raise StreamError(f"bad bucket set {buckets!r}")
        self.max_delay = max_delay_s
        self.capacity = int(capacity)
        self.raw = bool(raw)
        self.name = name
        self._pending: List[Tuple[Any, Future]] = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._shutdown_done = False
        # perf counters — mutated under _lock; read via stats() for a
        # consistent snapshot (bare attribute reads see a live value)
        self.frames = 0
        self.batches = 0
        self.errors = 0
        self._outstanding = 0
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        self._thread.start()
        # completion stage: device results queue here and a second
        # thread performs the host readback + future resolution, so the
        # batcher can dispatch batch N+1 while batch N's D2H is still in
        # flight (the readback dominates on remote/tunneled hosts —
        # overlapping it measured ~4x offload throughput)
        import queue as _q

        self._done_q: "_q.Queue" = _q.Queue(maxsize=4)
        self._completer = threading.Thread(target=self._complete_loop,
                                           name=f"{name}-complete",
                                           daemon=True)
        self._completer.start()

    # -- client API --------------------------------------------------------
    def submit(self, frame) -> Future:
        """frame: single-sample array (no batch dim or batch=1)."""
        fut: Future = Future()
        with self._lock:
            if self._stop:
                raise StreamError(f"{self.name}: dispatcher is shut down")
            if self.capacity and self._outstanding >= self.capacity:
                raise StreamError(
                    f"{self.name}: queue full "
                    f"({self._outstanding}/{self.capacity} outstanding)")
            self._outstanding += 1
            self._pending.append((frame, fut))
        self._wake.set()
        return fut

    def infer(self, frame, timeout: Optional[float] = 30.0):
        return self.submit(frame).result(timeout)

    @property
    def outstanding(self) -> int:
        """Frames accepted but not yet resolved (queue depth + in
        flight on device) — the least-outstanding router's load signal."""
        with self._lock:
            return self._outstanding

    def stats(self) -> dict:
        """Consistent counter snapshot (one lock hold — the counters
        are incremented together under _lock, so frames/batches never
        tear mid-batch)."""
        with self._lock:
            return {"frames": self.frames, "batches": self.batches,
                    "errors": self.errors,
                    "outstanding": self._outstanding}

    def shutdown(self, cause: str = "shut down") -> None:
        # idempotent: a second shutdown (supervisor drain racing a user
        # close) must not double-join or enqueue a second sentinel
        with self._lock:
            if self._shutdown_done:
                return
            self._shutdown_done = True
            self._stop = True
        self._wake.set()
        self._thread.join(timeout=30)
        if self._thread.is_alive():
            log.warning("dispatcher: batcher thread %s still alive after "
                        "30s join at shutdown — thread leaked",
                        self._thread.name)
        # the batcher normally drains _pending before exiting; if it
        # died or wedged, fail the leftovers with a typed error instead
        # of leaving callers blocked on futures nobody will resolve
        with self._lock:
            leftover = self._pending
            self._pending = []
            self._outstanding -= len(leftover)
            self.errors += len(leftover)
        for _, fut in leftover:
            if not fut.done():
                fut.set_exception(StreamError(
                    f"{self.name}: {cause} before the frame was "
                    f"dispatched"))
        # bounded sentinel enqueue: if the completion stage is wedged
        # (hung D2H) its queue may be full — shutdown must still return
        try:
            self._done_q.put(None, timeout=10)
        except Exception:
            log.warning("dispatcher completion queue wedged at shutdown")
        self._completer.join(timeout=10)
        if self._completer.is_alive():
            log.warning("dispatcher: completer thread %s still alive after "
                        "10s join at shutdown — thread leaked",
                        self._completer.name)

    # -- batcher loop ------------------------------------------------------
    def _loop(self) -> None:
        bucket = self.buckets[-1]
        while True:
            self._wake.wait(timeout=0.1)
            with self._lock:
                if self._stop and not self._pending:
                    return
                have = len(self._pending)
            if have == 0:
                self._wake.clear()
                continue
            if have < bucket:
                # linger briefly for more frames, then flush what we have
                time.sleep(self.max_delay)
            with self._lock:
                take = self._pending[:bucket]
                del self._pending[: len(take)]
                if not self._pending:
                    self._wake.clear()
            if take:
                self._run_batch(take)

    def _squeeze(self, f):
        """Accept samples with or without a leading batch=1 dim."""
        f = np.asarray(f)
        return f[0] if f.ndim > 1 and f.shape[0] == 1 else f

    def _run_batch(self, take) -> None:
        if self.raw:
            self._run_raw(take)
            return
        frames = [self._squeeze(f) for f, _ in take]
        n = len(frames)
        try:
            batch = np.stack(frames, axis=0)
            tgt = next(b for b in self.buckets if b >= n)
            if n < tgt:          # pad to the chosen compiled size
                pad = np.zeros((tgt - n,) + batch.shape[1:], batch.dtype)
                batch = np.concatenate([batch, pad], axis=0)
            out = self._run(batch, n)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for o in outs:       # start the D2H now; the completion
                start = getattr(o, "copy_to_host_async", None)
                if start is not None:    # thread reads it later
                    try:
                        start()
                    except Exception:
                        pass     # best-effort; np.asarray still correct
            # hand off to the completion stage (bounded: backpressure
            # keeps at most a few batches in flight on device)
            self._done_q.put((outs, take, n))
        except Exception as e:  # resolve futures, never hang clients
            self._fail(take, e)

    def _run_raw(self, take) -> None:
        """Raw-payload batch: one output tuple per payload, no
        stack/pad. The same overlapped-D2H handoff applies — device
        arrays start their host copy here, the completion thread reads
        them back."""
        n = len(take)
        try:
            outs = self._run([p for p, _ in take], n)
            if len(outs) != n:
                raise StreamError(
                    f"raw run returned {len(outs)} results for {n} "
                    f"payloads")
            for per_item in outs:
                for o in per_item:
                    start = getattr(o, "copy_to_host_async", None)
                    if start is not None:
                        try:
                            start()
                        except Exception:
                            pass
            self._done_q.put((outs, take, n))
        except Exception as e:
            self._fail(take, e)

    def abort(self, cause: str = "aborted") -> None:
        """Fence-style teardown: fail every queued-but-undispatched
        payload immediately (the chip is gone — draining would lie),
        let any batch already on device complete, then shut down. The
        caller re-routes the failed payloads to surviving replicas."""
        with self._lock:
            if self._shutdown_done:
                return
            self._stop = True    # refuse new submits before draining
            doomed = self._pending
            self._pending = []
            self.errors += len(doomed)
            self._outstanding -= len(doomed)
        for _, fut in doomed:
            if not fut.done():
                fut.set_exception(StreamError(f"{self.name}: {cause}"))
        self.shutdown(cause)

    def _fail(self, take, e: Exception) -> None:
        with self._lock:
            self.errors += len(take)
            self._outstanding -= len(take)
        for _, fut in take:
            if not fut.done():
                fut.set_exception(
                    StreamError(f"{self.name}: dispatch failed: {e}"))

    def _complete_loop(self) -> None:
        import queue as _q

        sentinel_seen = False
        while True:
            if sentinel_seen:
                # drain anything the batcher enqueued just before the
                # sentinel, then exit — no future may be left hanging
                try:
                    item = self._done_q.get_nowait()
                except _q.Empty:
                    return
            else:
                item = self._done_q.get()
            if item is None:
                sentinel_seen = True
                continue
            outs, take, n = item
            try:
                if self.raw:
                    results = [tuple(np.asarray(o) for o in per_item)
                               for per_item in outs]
                else:
                    host = [np.asarray(o) for o in outs]
                    results = [tuple(h[i] for h in host)
                               for i in range(len(take))]
                # count BEFORE resolving: a caller that observed its
                # result (and then read stats()) must see these frames
                with self._lock:
                    self.frames += n
                    self.batches += 1
                    self._outstanding -= n
                for i, (_, fut) in enumerate(take):
                    fut.set_result(results[i])
            except Exception as e:
                self._fail(take, e)


class MeshDispatcher:
    """Batches single-frame requests onto a dp-sharded jit computation.

    fn(params, x) must accept a leading batch dim; `bucket` is the
    compiled batch size (requests are padded up to it, so there is
    exactly one compilation).
    """

    def __init__(self, fn: Callable, params, mesh: Mesh, *,
                 bucket: int = 8, max_delay_ms: float = 2.0,
                 batch_axis: str = "dp"):
        if bucket % mesh.shape[batch_axis] != 0:
            raise StreamError(
                f"bucket {bucket} must be divisible by mesh axis "
                f"{batch_axis!r} size {mesh.shape[batch_axis]}"
            )
        self.mesh = mesh
        self.bucket = bucket
        self.max_delay = max_delay_ms / 1e3
        x_sharding = NamedSharding(mesh, P(batch_axis))

        def batched(params, x):
            x = jax.lax.with_sharding_constraint(x, x_sharding)
            return fn(params, x)

        self._params = params
        self._fn = jax.jit(batched)
        # compiled batch sizes: a partial flush pads only up to the
        # SMALLEST bucket that fits it — a lone closed-loop frame rides
        # the dp-sized program (1 on a single chip) instead of paying
        # the full bucket's H2D/compute/D2H (jit compiles each size
        # lazily on first use; at most these two shapes exist)
        self._core = BatchCore(
            self._exec, sorted({mesh.shape[batch_axis], bucket}),
            self.max_delay, name="mesh-dispatch")

    def _exec(self, batch: np.ndarray, n: int):
        return self._fn(self._params, jnp.asarray(batch))

    # -- client API --------------------------------------------------------
    def submit(self, frame) -> Future:
        """frame: single-sample array (no batch dim or batch=1)."""
        return self._core.submit(frame)

    def infer(self, frame, timeout: Optional[float] = 30.0):
        return self._core.infer(frame, timeout)

    def set_params(self, params) -> None:
        """Swap the model parameters (hot swap). A plain reference
        assignment: batches already collected keep the params they were
        dispatched with; every later batch sees the new tree. Shapes
        must match the old tree — same compiled program, no retrace."""
        self._params = params

    @property
    def buckets(self) -> List[int]:
        return list(self._core.buckets)

    @property
    def frames(self) -> int:
        return self._core.frames

    @property
    def batches(self) -> int:
        return self._core.batches

    def stats(self) -> dict:
        return self._core.stats()

    def shutdown(self) -> None:
        self._core.shutdown()
