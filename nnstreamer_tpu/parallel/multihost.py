"""Multi-host scale-out: one global mesh across TPU hosts over ICI + DCN.

Reference parity (SURVEY.md §5.8): the reference's cross-host story is
point-to-point TCP/MQTT/gRPC with NCCL/MPI-style backends left to the
NN frameworks. TPU-native, the whole problem collapses into JAX's
runtime: every host calls `initialize()` once, after which
`jax.devices()` spans the pod slice, a `make_mesh` over it yields a
global mesh, and the SAME sharded code from mesh.py/train.py/
ring_attention.py/pipeline.py/moe.py runs unchanged — XLA routes
collectives over ICI within a slice and DCN across slices. No wire
protocol of ours is involved in the data plane (edge/ remains the
off-pod transport for clients).

Single-host (or driver dryrun) use degrades gracefully: with one
process, `initialize()` is a no-op and the global mesh equals the local
one, so code written multi-host-first runs everywhere — including this
repo's tests.
"""

from __future__ import annotations

import os
from typing import Optional

from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.parallel.mesh import MeshSpec, make_mesh

log = get_logger("parallel.multihost")


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Join the multi-host JAX runtime (jax.distributed.initialize).

    Arguments default from the standard env (COORDINATOR_ADDRESS,
    NUM_PROCESSES, PROCESS_ID) or the TPU metadata autodetection JAX
    ships. Returns True if a multi-process runtime was joined, False for
    the single-process fallback (no coordinator configured). Call once,
    before any device use.
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS")
    num_processes = num_processes if num_processes is not None else (
        int(os.environ["NUM_PROCESSES"])
        if "NUM_PROCESSES" in os.environ else None)
    process_id = process_id if process_id is not None else (
        int(os.environ["PROCESS_ID"])
        if "PROCESS_ID" in os.environ else None)
    if coordinator_address is None and num_processes is None:
        try:   # cloud TPU pods autodetect without explicit coordination
            jax.distributed.initialize()
        except Exception as e:
            log.info("single-process runtime (no coordinator): %s", e)
            return False
        started = jax.process_count() > 1
        if started:
            log.info("joined multi-host runtime: process %d/%d, %d devices",
                     jax.process_index(), jax.process_count(),
                     len(jax.devices()))
        return started
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    log.info("joined multi-host runtime: process %d/%d, %d global devices",
             jax.process_index(), jax.process_count(), len(jax.devices()))
    return True


def global_mesh(spec: MeshSpec = MeshSpec()):
    """Mesh over ALL devices in the (possibly multi-host) runtime.

    Axis layout guidance for pods: keep `sp`/`ep` (latency-critical
    ppermute/all_to_all) within a slice's ICI by sizing them ≤ the
    per-slice device count; put `dp`/`pp` across slices — their
    collectives (gradient reduce, stage handoff) amortize DCN latency.
    """
    # trivial delegation: make_mesh already spans jax.devices(), which is
    # global after initialize(); this name exists for the pod guidance
    # above and so multi-host code reads as such
    return make_mesh(spec)


def host_local_batch(mesh, *arrays, axis_name: str = "dp"):
    """Assemble per-host input arrays into global arrays sharded over
    `axis_name` (multihost_utils.host_local_array_to_global_array): each
    host feeds only its shard — the canonical multi-host input path."""
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    spec = P(axis_name)
    out = tuple(
        multihost_utils.host_local_array_to_global_array(a, mesh, spec)
        for a in arrays)
    return out[0] if len(out) == 1 else out


def fetch_replicated(x):
    """Bring a (replicated) global result to every host as numpy
    (process_allgather, tiled: no artificial leading process axis)."""
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x, tiled=True)
