"""Device mesh construction and pytree sharding rules.

TPU-first design (scaling-book recipe): pick a mesh, annotate shardings
with PartitionSpec, let XLA insert the collectives, profile, iterate.
Axes:

- ``dp``  — data parallel (batch dim; gradients all-reduced over ICI)
- ``pp``  — pipeline parallel (model stages; parallel/pipeline.py)
- ``tp``  — tensor parallel (channel/feature dims of weights)
- ``ep``  — expert parallel (MoE experts; parallel/moe.py)
- ``sp``  — sequence/spatial parallel (long-context; ring attention)

The reference's closest analogs are tensor_split/tensor_merge (manual
per-dim shard/unshard of one tensor, SURVEY.md §5.7) — here sharding is a
type annotation on `jax.Array` and the runtime moves nothing by hand.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.core.log import get_logger

log = get_logger("parallel.mesh")

AXES = ("dp", "pp", "tp", "ep", "sp")


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: sizes per logical axis; -1 = absorb rest."""

    dp: int = -1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> Tuple[int, int, int, int, int]:
        sizes = {"dp": self.dp, "pp": self.pp, "tp": self.tp,
                 "ep": self.ep, "sp": self.sp}
        wild = [a for a, s in sizes.items() if s == -1]
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if n_devices % max(1, fixed) != 0:
            raise PipelineError(
                f"mesh {sizes} does not divide {n_devices} devices"
            )
        if len(wild) > 1:
            raise PipelineError("at most one mesh axis may be -1")
        if wild:
            sizes[wild[0]] = n_devices // fixed
        if math.prod(sizes.values()) > n_devices:
            raise PipelineError(
                f"mesh {sizes} needs {math.prod(sizes.values())} devices but "
                f"only {n_devices} are visible"
            )
        return tuple(sizes[a] for a in AXES)


def make_mesh(spec: MeshSpec = MeshSpec(), devices=None) -> Mesh:
    """Build a ("dp","pp","tp","ep","sp") mesh over the given (or all)
    devices.

    Device order preserves JAX's default enumeration, which follows the
    physical torus on real TPU slices — the innermost axes (sp, then ep)
    map to nearest-neighbor ICI links, which is what ring attention's
    ppermute and MoE's all_to_all want; pp sits outer (stage hops are
    once per microbatch, the least-frequent traffic).
    """
    devices = list(devices if devices is not None else jax.devices())
    shape = spec.resolve(len(devices))
    arr = np.array(devices[: math.prod(shape)]).reshape(shape)
    return Mesh(arr, AXES)


# ---------------------------------------------------------------------------
# Sharding rules: pytree-path pattern → PartitionSpec
# ---------------------------------------------------------------------------

def _path_str(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def default_param_rules() -> Sequence[Tuple[str, P]]:
    """Megatron-style rules for the zoo's conv models.

    Conv kernels are HWIO: shard O (last dim) over tp; the following
    projection shards I — XLA then inserts one all-reduce per block pair.
    BN/bias vectors follow their conv's output sharding. Dense classifier
    shards the feature dim.
    """
    return (
        ("bn/", P()),                    # small vectors: replicate
        ("classifier/w", P("tp", None)),  # (in, out): row-parallel
        ("classifier/b", P()),
        ("conv/w", P(None, None, None, "tp")),
        ("heatmap/w", P(None, None, None, "tp")),
        ("offset/w", P(None, None, None, "tp")),
        ("", P()),                        # default: replicate
    )


def spec_for_path(path_s: str, rules: Sequence[Tuple[str, P]]) -> P:
    for pat, spec in rules:
        if pat in path_s:
            return spec
    return P()


def _clip_spec(spec: P, ndim: int, shape, mesh: Mesh) -> P:
    """Drop axis annotations that don't divide the dim (tiny test models)
    or exceed rank — sharding must never change numerics."""
    entries = list(spec) + [None] * (ndim - len(spec))
    entries = entries[:ndim]
    out = []
    for dim, ax in zip(shape, entries):
        if ax is None:
            out.append(None)
        else:
            size = mesh.shape[ax] if not isinstance(ax, tuple) else math.prod(
                mesh.shape[a] for a in ax)
            out.append(ax if dim % size == 0 else None)
    return P(*out)


def sharding_for(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def shard_params(params, mesh: Mesh,
                 rules: Optional[Sequence[Tuple[str, P]]] = None):
    """device_put every leaf with its rule's NamedSharding."""
    rules = rules if rules is not None else default_param_rules()

    def place(path, leaf):
        p = spec_for_path(_path_str(path), rules)
        p = _clip_spec(p, getattr(leaf, "ndim", 0), getattr(leaf, "shape", ()), mesh)
        return jax.device_put(leaf, NamedSharding(mesh, p))

    return jax.tree_util.tree_map_with_path(place, params)


def param_specs(params, mesh: Mesh,
                rules: Optional[Sequence[Tuple[str, P]]] = None):
    """Pytree of PartitionSpec matching shard_params placement (for use as
    jit in_shardings/out_shardings)."""
    rules = rules if rules is not None else default_param_rules()

    def to_spec(path, leaf):
        p = spec_for_path(_path_str(path), rules)
        return _clip_spec(p, getattr(leaf, "ndim", 0), getattr(leaf, "shape", ()), mesh)

    return jax.tree_util.tree_map_with_path(to_spec, params)
