"""Multi-chip scale-out: meshes, sharding rules, distributed train/infer.

The reference has NO collectives — its "distributed" story is
point-to-point TCP/MQTT/gRPC offload (SURVEY.md §5.8). The TPU-native
equivalent is first-class: device meshes (`jax.sharding.Mesh`) with
dp/tp/sp axes, XLA collectives over ICI inserted by pjit from sharding
annotations, ring attention for sequence parallelism, and a pod batch
dispatcher that replaces per-frame TCP request/reply (edge/ still
provides the off-pod parity transport).

Modules:
- mesh.py           — mesh construction + pytree sharding rules
- train.py          — sharded train step (optax) + TrainState
- ring_attention.py — sequence-parallel attention via shard_map/ppermute
- pipeline.py       — GPipe-style pipeline parallelism over pp
- moe.py            — expert-parallel mixture-of-experts over ep
- dispatch.py       — pod batch dispatcher (mesh-sharded inference)
"""

from nnstreamer_tpu.parallel.mesh import (
    MeshSpec,
    make_mesh,
    shard_params,
    sharding_for,
)
from nnstreamer_tpu.parallel.moe import init_moe_params, moe_apply
from nnstreamer_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from nnstreamer_tpu.parallel.train import TrainState, make_train_step

__all__ = [
    "MeshSpec",
    "make_mesh",
    "shard_params",
    "sharding_for",
    "TrainState",
    "make_train_step",
    "pipeline_apply",
    "stack_stage_params",
    "init_moe_params",
    "moe_apply",
]
