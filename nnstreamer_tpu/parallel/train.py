"""Sharded training step.

The reference is inference-only (SURVEY.md §0) with a `tensor_trainer`
subplugin *type* reserved in its registry (nnstreamer_subplugin.h). Here
training is first-class and TPU-native: one jitted step, params/opt-state
sharded per mesh rules, batch sharded over (dp, sp), gradients reduced by
XLA collectives over ICI — no NCCL/MPI analog, no hand-written reduce.

Donation: params and opt_state are donated into the step so the update is
in-place in HBM (no 2× weight memory).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nnstreamer_tpu.parallel.mesh import default_param_rules, param_specs

LossFn = Callable[..., jnp.ndarray]  # loss_fn(params, *batch) -> scalar


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any


def init_state(params, optimizer: optax.GradientTransformation) -> TrainState:
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=optimizer.init(params),
    )


def shard_state(state: TrainState, mesh: Mesh, rules=None) -> TrainState:
    """Place a TrainState on the mesh: params by rules, opt_state mirrors
    params leaf-by-leaf shape (moments share param sharding), scalars
    replicated."""
    rules = rules if rules is not None else default_param_rules()
    pspecs = param_specs(state.params, mesh, rules)
    params_treedef = jax.tree_util.tree_structure(state.params)

    def place(leaf, spec):
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    def place_opt(node):
        # optax states are (named)tuples whose param-shaped members mirror
        # the params pytree exactly (e.g. Adam's mu/nu); match by tree
        # STRUCTURE, not leaf shape, so same-shaped params with different
        # partition rules keep distinct moment shardings
        if jax.tree_util.tree_structure(node) == params_treedef:
            return jax.tree_util.tree_map(place, node, pspecs)
        if isinstance(node, tuple):
            children = [place_opt(c) for c in node]
            if hasattr(node, "_fields"):  # NamedTuple optax states
                return type(node)(*children)
            return tuple(children)
        if isinstance(node, (list,)):
            return [place_opt(c) for c in node]
        if isinstance(node, dict):
            return {k: place_opt(v) for k, v in node.items()}
        return place(node, P())  # counts/scalars: replicate

    return TrainState(
        step=place(state.step, P()),
        params=jax.tree_util.tree_map(place, state.params, pspecs),
        opt_state=place_opt(state.opt_state),
    )


def make_train_step(loss_fn: LossFn, optimizer: optax.GradientTransformation,
                    mesh: Optional[Mesh] = None,
                    batch_spec: Optional[Sequence[P]] = None,
                    donate: bool = True):
    """Build a jitted `step(state, *batch) -> (state, loss)`.

    With a mesh, batch args get in_shardings (default: shard leading dim
    over dp) and XLA inserts the gradient all-reduce implied by sharded
    batch + replicated-or-tp-sharded params. Without a mesh, plain jit.
    """

    def step(state: TrainState, *batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, *batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(step=state.step + 1, params=params,
                          opt_state=opt_state), loss

    donate_argnums = (0,) if donate else ()
    if mesh is None:
        return jax.jit(step, donate_argnums=donate_argnums)

    if batch_spec is None:
        batch_spec = (P("dp"),) * 8  # enough for any arity; trimmed below

    def wrapped(state, *batch):
        return step(state, *batch)

    # Rely on sharding propagation from the placed TrainState (shard_state)
    # + constrained batch inputs.
    def constrained(state, *batch):
        batch = tuple(
            jax.lax.with_sharding_constraint(b, NamedSharding(mesh, s))
            for b, s in zip(batch, batch_spec)
        )
        return wrapped(state, *batch)

    return jax.jit(constrained, donate_argnums=donate_argnums)
