"""Ring attention — sequence-parallel attention over the ``sp`` mesh axis.

Long-context support the reference does not have (SURVEY.md §5.7: its
"sequence" story is tensor_aggregator windowing). TPU-native design:

- the sequence dim is sharded over ``sp``; each device holds one Q/K/V
  block of shape (B, S/n, H, D);
- K/V blocks rotate around the ring with `lax.ppermute` (nearest-neighbor
  ICI hops — the mesh builder puts sp innermost for exactly this);
- softmax is accumulated online (flash-attention style running max /
  normalizer), so the full (S × S) score matrix never materializes and
  per-device HBM stays O(S/n · D + S/n · S/n);
- compute of block i overlaps the transfer of block i+1 because XLA
  schedules the ppermute DMA concurrently with the matmuls.

Causal masking uses the *rotating block index* so each device only
applies the triangular mask on its own diagonal block.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from nnstreamer_tpu.parallel._compat import shard_map

NEG_INF = -1e30


def _block_attn(q, k, v, m_prev, l_prev, o_prev, mask=None):
    """One online-softmax accumulation step.

    q: (B, Sq, H, D), k/v: (B, Sk, H, D); m/l: (B, H, Sq) running max /
    normalizer; o: (B, Sq, H, D) unnormalized output accumulator.
    """
    scale = q.shape[-1] ** -0.5
    # scores: (B, H, Sq, Sk)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_cur = jnp.max(s, axis=-1)                  # (B, H, Sq)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new[..., None])            # (B, H, Sq, Sk)
    # fully-masked rows have s == m_new == NEG_INF → exp(0) = 1; zero them
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    corr = jnp.exp(m_prev - m_new)               # (B, H, Sq)
    l_new = l_prev * corr + jnp.sum(p, axis=-1)
    o_new = o_prev * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def ring_attention(q, k, v, *, mesh: Mesh, axis: str = "sp",
                   causal: bool = False, block_impl: str = "auto",
                   batch_axis: str = None):
    """Sequence-parallel attention. q/k/v: (B, S, H, D) with S sharded
    over `axis`; returns (B, S, H, D) with the same sharding.

    `batch_axis` composes sequence parallelism with data parallelism:
    B additionally shards over that mesh axis (each dp group runs its
    own independent ring over `axis`) — the dp×sp layout of a composed
    dp×tp×sp mesh. None keeps B replicated within the shard_map.

    block_impl picks the per-rotation block math: "pallas" runs each
    incoming K/V block through the flash_block_update kernel (MXU
    dot_generals, VMEM-resident online softmax), "xla" is the jnp
    einsum path, "auto" = pallas on TPU when the local block divides
    128 (CPU tests keep xla — interpret-mode grids are slow)."""

    n = mesh.shape[axis]
    s_local = q.shape[1] // n
    use_pallas = block_impl == "pallas" or (
        block_impl == "auto" and jax.default_backend() == "tpu"
        and s_local % 128 == 0)
    if use_pallas:
        return _ring_attention_pallas(q, k, v, mesh=mesh, axis=axis,
                                      causal=causal, n=n,
                                      batch_axis=batch_axis)

    def local(q, k, v):
        # q/k/v here: the per-device shard (B, S/n, H, D)
        b, sq, h, d = q.shape
        my = lax.axis_index(axis)

        m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, sq), jnp.float32)
        o0 = jnp.zeros((b, sq, h, d), jnp.float32)

        def attend(i, m, l, o, k_blk, v_blk):
            # blocks rotate j→j+1 each step, so after i steps this device
            # holds the block that started on device (my - i) mod n
            src = (my - i) % n
            if causal:
                # query global index = my*sq + iq; key global = src*sk + ik
                iq = my * sq + jnp.arange(sq)[:, None]
                ik = src * k_blk.shape[1] + jnp.arange(k_blk.shape[1])[None, :]
                mask = (iq >= ik)[None, None, :, :]
            else:
                mask = None
            return _block_attn(q, k_blk, v_blk, m, l, o, mask)

        def body(i, carry):
            m, l, o, k_blk, v_blk = carry
            m, l, o = attend(i, m, l, o, k_blk, v_blk)
            perm = [(j, (j + 1) % n) for j in range(n)]
            k_blk = lax.ppermute(k_blk, axis, perm)
            v_blk = lax.ppermute(v_blk, axis, perm)
            return m, l, o, k_blk, v_blk

        # n-1 rotating steps, then the final block without the (wasted)
        # n-th ICI rotation
        m, l, o, k_last, v_last = lax.fori_loop(0, n - 1, body,
                                                (m0, l0, o0, k, v))
        m, l, o = attend(n - 1, m, l, o, k_last, v_last)
        l = jnp.maximum(l, 1e-20)
        out = o / l.transpose(0, 2, 1)[..., None]
        return out.astype(q.dtype)

    spec = P(batch_axis, axis, None, None)
    return shard_map(
        local, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def _ring_attention_pallas(q, k, v, *, mesh, axis, causal, n,
                           batch_axis=None):
    """Ring rotation with the Pallas flash block kernel doing each
    device's attend step (backends/pallas_ops.flash_block_update)."""
    from nnstreamer_tpu.backends.pallas_ops import (
        flash_block_update, flash_carry_finalize, flash_carry_init)

    def local(q, k, v):
        b, sq, h, d = q.shape
        my = lax.axis_index(axis)
        qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
        q_off = (my * sq).astype(jnp.int32)

        def flat(t):
            return t.transpose(0, 2, 1, 3).reshape(b * h, -1, d)

        m, l, acc = flash_carry_init(b * h, sq, d)

        def attend(i, m, l, acc, k_blk, v_blk):
            src = (my - i) % n
            k_off = (src * k_blk.shape[1]).astype(jnp.int32)
            return flash_block_update(
                qf, flat(k_blk), flat(v_blk), m, l, acc,
                q_offset=q_off, k_offset=k_off, causal=causal)

        def body(i, carry):
            m, l, acc, k_blk, v_blk = carry
            m, l, acc = attend(i, m, l, acc, k_blk, v_blk)
            perm = [(j, (j + 1) % n) for j in range(n)]
            k_blk = lax.ppermute(k_blk, axis, perm)
            v_blk = lax.ppermute(v_blk, axis, perm)
            return m, l, acc, k_blk, v_blk

        m, l, acc, k_last, v_last = lax.fori_loop(
            0, n - 1, body, (m, l, acc, k, v))
        m, l, acc = attend(n - 1, m, l, acc, k_last, v_last)
        out = flash_carry_finalize(l, acc)
        return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3).astype(q.dtype)

    spec = P(batch_axis, axis, None, None)
    return shard_map(
        local, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def reference_attention(q, k, v, *, causal: bool = False):
    """Single-device ground truth for tests."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(q.dtype)
