"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

No reference counterpart (SURVEY.md §5.8 — the reference has no
collectives at all); this is the TPU-native sparse-capacity scale-out:
experts live on different devices, tokens travel to their expert and
back with two `lax.all_to_all` collectives over ICI.

Switch-transformer-style design (static shapes throughout — XLA needs
them, and so does the MXU):
- top-1 gating with a fixed per-expert capacity C; tokens over capacity
  are dropped from the expert path (their contribution is zero and the
  caller's residual connection carries them — standard Switch behavior);
- dispatch is a one-hot einsum into an (E, C, d) buffer, so routing is
  dense matmul work, not scatter;
- all_to_all #1 re-shards the buffer from token-owners to expert-owners
  (split the E dim, concat the sender dim); experts run as one batched
  einsum over their local expert group; all_to_all #2 reverses the
  exchange; a final one-hot einsum combines results back per token,
  scaled by the gate probability.

Tokens are sharded over ``ep`` too (each device both owns tokens and
hosts experts), which is what makes the exchange an all_to_all instead
of an all_gather.
"""

from __future__ import annotations

import math
from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from nnstreamer_tpu.parallel._compat import shard_map


def init_moe_params(key, d_model: int, d_hidden: int, n_experts: int,
                    dtype=jnp.float32) -> Dict[str, jnp.ndarray]:
    kg, k1, k2 = jax.random.split(key, 3)
    s = d_model ** -0.5
    return {
        "gate": (jax.random.normal(kg, (d_model, n_experts)) * s).astype(dtype),
        "w1": (jax.random.normal(k1, (n_experts, d_model, d_hidden)) * s
               ).astype(dtype),
        "w2": (jax.random.normal(k2, (n_experts, d_hidden, d_model))
               * d_hidden ** -0.5).astype(dtype),
    }


def moe_param_specs() -> Dict[str, P]:
    """Sharding rules: experts over ep, gate replicated."""
    return {"gate": P(), "w1": P("ep"), "w2": P("ep")}


def _route(x, gate_w, n_experts: int, capacity: int):
    """Top-1 routing for local tokens x: (t, d) →
    dispatch (t, E, C) one-hot, probs (t,)."""
    logits = x @ gate_w                                   # (t, E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                   # (t,)
    p = jnp.max(probs, axis=-1)                           # (t,)
    onehot_e = jax.nn.one_hot(expert, n_experts, dtype=x.dtype)   # (t, E)
    # position of each token within its expert's buffer (arrival order).
    # Counting runs in int32 NO MATTER the activation dtype: a bf16
    # cumsum cannot represent integers above 256, which would collapse
    # distinct slots and silently sum two tokens into one buffer entry
    counts = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)
    pos = (jnp.cumsum(counts, axis=0) - 1) * counts               # (t, E)
    pos_i = jnp.sum(pos, axis=-1)                                 # (t,) i32
    keep = (pos_i < capacity).astype(x.dtype)
    onehot_c = jax.nn.one_hot(pos_i, capacity, dtype=x.dtype)     # (t, C)
    dispatch = onehot_e[:, :, None] * onehot_c[:, None, :] \
        * keep[:, None, None]                                     # (t, E, C)
    return dispatch, p.astype(x.dtype)


def moe_apply(params, x, *, mesh: Mesh, axis: str = "ep",
              capacity_factor: float = 1.25):
    """Expert-parallel MoE layer. x: (T, d) with T sharded over `axis`;
    params per init_moe_params with w1/w2 sharded over `axis` dim 0.
    Returns (T, d), same sharding. Add the residual outside."""
    n = mesh.shape[axis]
    n_experts = params["w1"].shape[0]
    if n_experts % n:
        raise ValueError(
            f"{n_experts} experts do not divide over ep={n} devices")
    t_local = x.shape[0] // n
    capacity = max(1, math.ceil(capacity_factor * t_local / n_experts))

    def local(gate_w, w1, w2, xs):
        # xs: (t, d) local tokens; w1/w2: (E/n, ...) local expert group
        dispatch, p = _route(xs, gate_w, n_experts, capacity)
        buf = jnp.einsum("tec,td->ecd", dispatch, xs)     # (E, C, d)
        # token-owner → expert-owner exchange: (E, C, d) → (E/n, n·C, d)
        recv = lax.all_to_all(buf, axis, split_axis=0, concat_axis=1,
                              tiled=True)
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", recv, w1))
        y = jnp.einsum("ech,ehd->ecd", h, w2)             # (E/n, n·C, d)
        back = lax.all_to_all(y, axis, split_axis=1, concat_axis=0,
                              tiled=True)                 # (E, C, d)
        out = jnp.einsum("tec,ecd->td", dispatch, back)
        return out * p[:, None]

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )(params["gate"], params["w1"], params["w2"], x)


def reference_moe(params, x):
    """Serial ground truth (no capacity drops): every token goes to its
    argmax expert, scaled by the gate prob."""
    logits = x @ params["gate"]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    p = jnp.max(probs, axis=-1).astype(x.dtype)
    h = jax.nn.gelu(jnp.einsum("td,edh->teh", x, params["w1"]))
    y = jnp.einsum("teh,ehd->ted", h, params["w2"])       # (t, E, d)
    sel = jnp.take_along_axis(
        y, expert[:, None, None].repeat(y.shape[-1], -1), axis=1)[:, 0]
    return sel * p[:, None]
