"""Supervised multi-process worker pool for the serving edge.

ROADMAP item 4's last piece: N copies of a pipeline in child processes
behind one `QueryServer`, with a supervisor that keeps the pool alive
through worker crashes, hangs, and restarts — and keeps the PR-9
admission conservation invariants exact through every one of them:

    offered  == admitted + rejected
    admitted == replied + shed + depth + inflight

The process tree::

    PooledQueryServer                 (parent process)
      ├─ QueryServer transport        HELLO/DATA/RESULT/BUSY wire
      ├─ router thread                admission queue -> least-
      │                               outstanding ready worker
      ├─ per-worker reader threads    results / errors / heartbeats
      ├─ supervisor thread            liveness + restart + circuit
      └─ worker processes (spawn)     serving/worker.py, one pipeline
                                      copy each — crash isolation AND
                                      a GIL sidestep in one move

Supervision contract (docs/robustness.md):

- **Crash** (nonzero exit, SIGKILL, lost pipe): the reader drains every
  result the worker managed to send, then the supervisor *re-offers*
  each remaining in-flight frame to a live worker (up to
  ``max_redeliver`` times) and *sheds* the rest with a typed
  ``BUSY(worker_lost)`` — a killed worker never turns into client-side
  silence.
- **Hang** (heartbeat older than ``hb_timeout_s``, or any in-flight
  frame older than ``frame_deadline_s``): the worker is SIGKILLed and
  handled as a crash. Heartbeats ride a dedicated child thread, so a
  busy worker is distinguished from a wedged one by its *frames*, not
  its pulse.
- **Restart**: exponential backoff (``restart_backoff_s`` doubling to
  ``restart_backoff_max_s``) per slot. A slot that restarts more than
  ``restart_budget`` times inside ``restart_window_s`` is *disabled* —
  the pool degrades to fewer workers and records it (stats +
  ``record_worker_event``) instead of flapping forever.
- **Drain** (`close()` / SIGTERM via `install_signal_handlers`): stop
  admitting (queued frames get ``BUSY(shutdown)``), let in-flight
  frames finish within ``drain_timeout_s``, BUSY whatever remains,
  then stop children gracefully and escalate terminate -> kill. No
  orphan processes, ever (children also self-exit when the pipe dies).

Hot swap: ``swap(name, version)`` broadcasts a two-phase
prepare/commit to every ready worker; any prepare failure aborts every
worker, so the pool's model epoch flips all-or-none — the PR-5 epoch
semantics lifted across process boundaries.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import signal
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence

from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.edge.query import QueryServer
from nnstreamer_tpu.edge.wire import encode_buffer
from nnstreamer_tpu.runtime.tracing import NULL_TRACER, get_trace_ctx
from nnstreamer_tpu.serving.worker import RID_META, WorkerSpec, worker_main
from nnstreamer_tpu.tensor.info import TensorsSpec

log = get_logger("serving.pool")

#: worker lifecycle states (docs/robustness.md supervision tree)
STARTING, READY, DEAD, DISABLED, STOPPING = (
    "starting", "ready", "dead", "disabled", "stopping")


def proc_alive(pid: int) -> bool:
    """True when `pid` is a live (non-zombie) process — a psutil-free
    /proc probe, the orphan audit the chaos tests and harness run after
    close(): `any(proc_alive(p) for p in pool.all_pids_ever())` must be
    False once the pool is down."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read().decode("ascii", "replace")
        # field 3 is the state char; the comm field may contain spaces
        # and parens, so split from the LAST ')'
        state = data.rsplit(")", 1)[1].split()[0]
        return state != "Z"
    except (OSError, IndexError):
        return False


class _Request:
    """One admitted frame in flight somewhere in the pool. Carries the
    re-encoded wire payload so a re-offer after a worker death needs no
    surviving TensorBuffer."""

    __slots__ = ("rid", "client_id", "pts", "payload", "attempts",
                 "t_sent", "traced", "hops", "cls", "model")

    def __init__(self, rid: int, client_id, pts, payload: bytes,
                 traced: bool = False, cls: Optional[str] = None,
                 model: Optional[str] = None):
        self.rid = rid
        self.client_id = client_id
        self.pts = pts
        self.payload = payload
        self.attempts = 0             # deliveries so far
        self.t_sent = 0.0
        # tenancy: the admission-resolved class (for per-class shed
        # accounting on this request's failure paths) and the model it
        # routes to (for bound-slot dispatch preference)
        self.cls = cls
        self.model = model
        # parent-side hop records (dispatch/reoffer): the payload is
        # already-encoded bytes when the router touches it, so router
        # hops are kept here and merged into the reply's trace context
        # at _on_result — this is what makes a redelivered frame's
        # timeline show BOTH the dead and the replacement worker (the
        # dead worker's own stamps died with it; the parent's dispatch
        # record carries its wid/pid)
        self.traced = traced
        self.hops: List[dict] = []

    def hop(self, name: str, **extra) -> None:
        if self.traced:
            rec = {"hop": name, "t": time.perf_counter(),
                   "pid": os.getpid()}
            rec.update(extra)
            self.hops.append(rec)


class _Slot:
    """One supervised worker slot: the process occupying it now plus
    the slot's restart history (the circuit breaker is per-slot, so one
    poisoned pipeline copy cannot disable its healthy siblings)."""

    def __init__(self, wid: int):
        self.wid = wid
        self.state = STARTING
        self.proc: Optional[mp.process.BaseProcess] = None
        self.conn = None
        self.reader: Optional[threading.Thread] = None
        self.send_lock = threading.Lock()
        self.pid: Optional[int] = None
        self.started_t = 0.0
        self.last_hb = 0.0            # parent-clock arrival time
        self.inflight: Dict[int, _Request] = {}
        # perf_counter skew vs this worker (≈0 on Linux, where
        # perf_counter is the system-wide CLOCK_MONOTONIC); sampled at
        # the ready handshake, applied when merging its trace deltas
        self.clock_offset_s = 0.0
        self.restart_times: Deque[float] = deque()
        self.backoff_s = 0.0
        self.next_restart_t = 0.0
        self.restarts = 0             # lifetime counters (stats)
        self.kills = 0
        self.replied = 0
        self.version: Optional[tuple] = None
        self.bound_model: Optional[str] = None   # rebind() routing hint
        self.chips: tuple = ()        # leased device ordinals (placement)
        # same-host shm lane (serving/shm.py): the parent-created ring
        # pair for THIS process occupancy; shm_ok flips true only after
        # the child acks attach at handshake, so the lane is negotiated,
        # never assumed. spawns makes ring names unique per occupancy.
        self.shm_req = None           # parent→child ring (parent writes)
        self.shm_res = None           # child→parent ring (parent reads)
        self.shm_ok = False
        self.spawns = 0

    def hb_age_s(self, now: float) -> float:
        return now - max(self.last_hb, self.started_t)


class WorkerPool:
    """Supervised pool of worker processes behind one QueryServer
    (module docstring). Use `PooledQueryServer` unless you already own
    the QueryServer lifecycle."""

    def __init__(self, qs: QueryServer, spec: WorkerSpec, workers: int,
                 *,
                 per_worker_queue: int = 4,
                 max_redeliver: int = 1,
                 hb_timeout_s: float = 2.0,
                 frame_deadline_s: float = 30.0,
                 restart_backoff_s: float = 0.05,
                 restart_backoff_max_s: float = 2.0,
                 restart_budget: int = 5,
                 restart_window_s: float = 30.0,
                 drain_timeout_s: float = 10.0,
                 spawn_grace_s: float = 20.0,
                 chips: Optional[Sequence[int]] = None,
                 shm_transport: bool = True,
                 shm_ring_bytes: int = 0,
                 name: str = "worker_pool"):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        if per_worker_queue < 1:
            raise ValueError("per_worker_queue must be >= 1")
        # chip ownership (serving/placement.ChipLeaseTable): device
        # ordinals partitioned across the slots — worker i owns chips
        # i*K..(i+1)*K-1. The supervisor fences a dead worker's chips
        # and re-leases them to the replacement; a K-chip slot counts
        # as K capacity slots (capacity_slots / slot_weights).
        self.chip_table = None
        self._chips_per_slot = 0
        if chips:
            if len(chips) % workers != 0:
                raise ValueError(
                    f"chips ({len(chips)}) must divide evenly across "
                    f"workers ({workers})")
            from nnstreamer_tpu.serving.placement import ChipLeaseTable

            self.chip_table = ChipLeaseTable(chips)
            self._chips_per_slot = len(chips) // workers
        self.qs = qs
        # a traced pool runs traced workers: the child spins up its own
        # Tracer and ships deltas back over the pipe ("tr" lane)
        if getattr(qs.tracer, "active", False) and not spec.trace:
            import dataclasses

            spec = dataclasses.replace(spec, trace=True)
        self.spec = spec
        self.name = name
        self.n_workers = workers
        self.per_worker_queue = per_worker_queue
        self.max_redeliver = max(0, max_redeliver)
        self.hb_timeout_s = hb_timeout_s
        self.frame_deadline_s = frame_deadline_s
        self.restart_backoff_s = restart_backoff_s
        self.restart_backoff_max_s = restart_backoff_max_s
        self.restart_budget = restart_budget
        self.restart_window_s = restart_window_s
        self.drain_timeout_s = drain_timeout_s
        self.spawn_grace_s = spawn_grace_s
        # spawn, never fork: the parent runs transport + router threads
        # (and often a JAX runtime) — forked locks/engines in the child
        # are exactly the wedge class this pool exists to survive
        self._ctx = mp.get_context("spawn")
        self._lock = threading.RLock()
        self._slots: List[_Slot] = [_Slot(i) for i in range(workers)]
        self._pending: Deque[_Request] = deque()   # awaiting (re)dispatch
        self._dispatch_evt = threading.Event()
        self._stop_evt = threading.Event()
        self._closed = False
        self._close_lock = threading.Lock()
        self._next_rid = 0
        self.epoch = 0                # bumps on every committed swap
        self.degraded = 0             # slots disabled by the circuit
        self.reoffered = 0
        # same-host shm lane (serving/shm.py): enabled pools give every
        # slot a per-spawn ring pair; payloads that fit ride shared
        # memory, everything else transparently stays on pickle+pipe
        from nnstreamer_tpu.serving.shm import (
            DEFAULT_RING_BYTES, shm_supported)

        self.shm_transport = bool(shm_transport) and shm_supported()
        self.shm_ring_bytes = int(shm_ring_bytes) or DEFAULT_RING_BYTES
        self._shm_stat_lock = threading.Lock()
        self.shm_frames = 0           # records moved via shm (both dirs)
        self.shm_bytes = 0
        self.shm_fallbacks = 0        # lane bypasses (full/unattached)
        self.rebinds = 0              # committed rebind broadcasts
        self.tenant_table = None      # serving.tenancy.TenantTable
        self.last_worker_error: Optional[BaseException] = None
        self._resident_versions: Dict[str, list] = {}
        self._all_pids: List[int] = []   # every pid ever spawned
        self._router: Optional[threading.Thread] = None
        self._supervisor: Optional[threading.Thread] = None
        self._started = False

    # -- tracer ------------------------------------------------------------
    @property
    def tracer(self):
        return self.qs.tracer or NULL_TRACER

    def _event(self, wid: int, kind: str, **args) -> None:
        tr = self.tracer
        if tr.active:
            tr.record_worker_event(self.name, wid, kind,
                                   time.perf_counter(), **args)

    # -- lifecycle ---------------------------------------------------------
    def start(self, ready_timeout_s: float = 30.0) -> "WorkerPool":
        with self._lock:
            if self._started:
                return self
            self._started = True
            for slot in self._slots:
                self._spawn(slot)
        self._router = threading.Thread(
            target=self._route_loop, name=f"{self.name}-router",
            daemon=True)
        self._router.start()
        self._supervisor = threading.Thread(
            target=self._supervise_loop, name=f"{self.name}-supervisor",
            daemon=True)
        self._supervisor.start()
        self.qs.pool = self
        if ready_timeout_s:
            self.wait_ready(ready_timeout_s)
        return self

    def wait_ready(self, timeout_s: float = 30.0,
                   n: Optional[int] = None) -> bool:
        """Block until `n` workers (default: all non-disabled) are
        ready; False on timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                ready = sum(1 for s in self._slots if s.state == READY)
                want = n if n is not None else sum(
                    1 for s in self._slots if s.state != DISABLED)
            if want and ready >= want:
                return True
            time.sleep(0.01)
        return False

    def _spawn(self, slot: _Slot) -> None:
        """Start a worker in `slot` (under `_lock`)."""
        spec = self.spec
        if self.chip_table is not None:
            # (re-)lease the slot's chips: a restarted slot gets its own
            # fenced chips back first, so "worker wid owns chips i..j"
            # survives the crash
            slot.chips = self.chip_table.lease(
                slot.wid, self._chips_per_slot)
            import dataclasses

            spec = dataclasses.replace(spec, chips=slot.chips)
        slot.spawns += 1
        slot.shm_ok = False
        if self.shm_transport:
            # per-spawn ring pair with unique names: a respawned slot
            # can never attach its predecessor's (possibly half-written)
            # segments. Create failure degrades to pipe-only, silently.
            import dataclasses

            from nnstreamer_tpu.serving.shm import ShmRing, ring_name

            try:
                slot.shm_req = ShmRing.create(
                    ring_name("rq", self.name, slot.wid, slot.spawns),
                    self.shm_ring_bytes)
                slot.shm_res = ShmRing.create(
                    ring_name("rs", self.name, slot.wid, slot.spawns),
                    self.shm_ring_bytes)
                spec = dataclasses.replace(
                    spec, shm_req=slot.shm_req.name,
                    shm_res=slot.shm_res.name)
            except Exception as e:
                log.warning("pool %s: shm ring create failed (%s) — "
                            "slot %d stays on pipe", self.name, e,
                            slot.wid)
                self._drop_rings(slot)
        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        proc = self._ctx.Process(
            target=worker_main, args=(child_conn, spec, slot.wid),
            name=f"{self.name}-w{slot.wid}", daemon=True)
        proc.start()
        child_conn.close()            # child's end lives in the child
        slot.proc = proc
        slot.conn = parent_conn
        slot.pid = proc.pid
        slot.state = STARTING
        slot.started_t = time.monotonic()
        slot.last_hb = 0.0
        self._all_pids.append(proc.pid)
        slot.reader = threading.Thread(
            target=self._read_loop, args=(slot, parent_conn),
            name=f"{self.name}-read-w{slot.wid}", daemon=True)
        slot.reader.start()
        self._event(slot.wid, "spawn", pid=proc.pid)

    def _drop_rings(self, slot: _Slot) -> None:
        """Close AND unlink a slot's ring pair (parent is the creator,
        so the name dies here — the /dev/shm audit in the worker-kill
        drill counts on this being unconditional). Serialized against
        in-flight ring writes via send_lock."""
        with slot.send_lock:
            slot.shm_ok = False
            for ring in (slot.shm_req, slot.shm_res):
                if ring is not None:
                    ring.close()
                    ring.unlink()
            slot.shm_req = slot.shm_res = None

    # -- per-worker reader -------------------------------------------------
    def _read_loop(self, slot: _Slot, conn) -> None:
        """Drains one worker's pipe until EOF. Runs everything the
        worker managed to say before dying — which is what makes the
        post-mortem re-offer safe: a result can never race its own
        redelivery, because reaping waits for this thread."""
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                return
            tag = msg[0]
            if tag == "hb":
                slot.last_hb = time.monotonic()
            elif tag == "res":
                self._on_result(slot, msg[1], msg[2])
            elif tag == "ress":
                self._on_shm_result(slot, msg[1], msg[2], msg[3])
            elif tag == "err":
                self._on_request_error(slot, msg[1], msg[2])
            elif tag == "ready":
                slot.last_hb = time.monotonic()
                with self._lock:
                    if slot.state == STARTING:
                        slot.state = READY
                info = msg[1]
                if isinstance(info, dict) and slot.shm_req is not None:
                    if info.get("shm"):
                        slot.shm_ok = True
                    else:
                        # child couldn't attach: the lane is dead for
                        # this occupancy — reclaim the segments now
                        # rather than carrying them as ballast
                        with self._shm_stat_lock:
                            self.shm_fallbacks += 1
                        self._drop_rings(slot)
                t_child = info.get("t_perf") if isinstance(info, dict) \
                    else None
                if t_child is not None:
                    # perf_counter is the system-wide CLOCK_MONOTONIC
                    # on Linux, so a small delta here is just pipe
                    # latency — only a genuinely different clock base
                    # (>1s apart) is treated as skew to correct
                    raw = time.perf_counter() - float(t_child)
                    slot.clock_offset_s = raw if abs(raw) > 1.0 else 0.0
                self._adopt_out_spec(info)
                self._event(slot.wid, "ready", pid=slot.pid)
                self._dispatch_evt.set()
            elif tag == "tr":
                tr = self.tracer
                if tr.active:
                    tr.ingest_child(
                        slot.wid, slot.pid or 0, msg[1],
                        offset_s=slot.clock_offset_s,
                        label=f"{self.name}-w{slot.wid}")
            elif tag == "swap_ack":
                with self._lock:
                    acks = self._swap_acks
                if acks is not None:
                    acks.put((slot.wid, msg[1], msg[2], msg[3]))
            elif tag == "bind_ack":
                with self._lock:
                    acks = self._bind_acks
                if acks is not None:
                    acks.put((slot.wid, msg[1], msg[2], msg[3]))
            elif tag == "fatal":
                self._note_worker_error(slot, msg[1])
            elif tag == "bye":
                return

    def _adopt_out_spec(self, info: dict) -> None:
        """First ready worker declares the pool's output spec (HELLO
        contract) unless the owner already set one. The worker's
        resident ``store://`` versions ride the same ready info — the
        mesh REGISTER ad advertises them for locality routing."""
        versions = info.get("versions")
        if isinstance(versions, dict) and versions:
            with self._lock:
                self._resident_versions = versions
        if self.qs.out_spec is not None:
            return
        dims, types = info.get("out_dims"), info.get("out_types")
        if dims:
            try:
                self.qs.out_spec = TensorsSpec.from_strings(dims, types)
            except ValueError:
                pass

    def resident_versions(self) -> Dict[str, list]:
        """{model name: [resident versions]} as the most recent ready
        worker reported them (empty for echo pools)."""
        with self._lock:
            return dict(self._resident_versions)

    def _on_result(self, slot: _Slot, rid: int, payload: bytes) -> None:
        from nnstreamer_tpu.edge.wire import decode_buffer

        with self._lock:
            req = slot.inflight.pop(rid, None)
        if req is None:
            # already re-offered/shed (abandoned at drain) — the
            # admission accounting closed this request elsewhere
            return
        slot.replied += 1
        try:
            buf, _ = decode_buffer(payload)
        except ValueError as e:
            log.warning("pool %s: worker %d returned a corrupt frame "
                        "for pts=%s: %s", self.name, slot.wid,
                        req.pts, e)
            self.qs.frames.note_failed("worker_error", cls=req.cls)
            self.qs.send_busy(req.client_id, req.pts, "worker_error")
            return
        buf.meta.pop(RID_META, None)
        if req.hops:
            # merge the parent-side router hops (dispatch/reoffer) into
            # the reply's trace context, in time order: one timeline
            # per trace_id even across a redelivery
            ctx = get_trace_ctx(buf.meta)
            if ctx is not None:
                ctx["hops"].extend(req.hops)
                ctx["hops"].sort(
                    key=lambda h: h.get("t", 0.0)
                    if isinstance(h, dict) else 0.0)
        self.qs.reply(int(req.client_id), buf.with_tensors(
            buf.tensors, pts=req.pts))
        self._dispatch_evt.set()

    def _on_shm_result(self, slot: _Slot, rid: int, nbytes: int,
                       seq: int) -> None:
        """A result whose payload rode the res ring. Any ring fault
        (mismatch, torn record, ring gone) sheds exactly this request —
        the control message is still the unit of accounting, so
        conservation can't drift whatever the lane does."""
        ring = slot.shm_res
        try:
            if ring is None:
                raise ValueError("shm result with no attached ring")
            payload = ring.read_record(nbytes, seq)
        except Exception as e:
            log.warning("pool %s: worker %d shm result fault for "
                        "rid=%s: %s", self.name, slot.wid, rid, e)
            with self._lock:
                req = slot.inflight.pop(rid, None)
            if req is not None:
                self.qs.frames.note_failed("worker_error", cls=req.cls)
                self.qs.send_busy(req.client_id, req.pts, "worker_error")
                self._dispatch_evt.set()
            return
        with self._shm_stat_lock:
            self.shm_frames += 1
            self.shm_bytes += nbytes
        self._on_result(slot, rid, payload)

    def _on_request_error(self, slot: _Slot, rid: int,
                          exc_bytes: bytes) -> None:
        """Request-scoped failure: the worker survives, this one frame
        is shed with a typed BUSY."""
        with self._lock:
            req = slot.inflight.pop(rid, None)
        try:
            exc = pickle.loads(exc_bytes)
        except Exception:
            exc = StreamError("worker error (unpicklable)")
        self.last_worker_error = exc
        if req is None:
            return
        log.warning("pool %s: worker %d failed frame pts=%s: %s",
                    self.name, slot.wid, req.pts, exc)
        self.qs.frames.note_failed("worker_error", cls=req.cls)
        self.qs.send_busy(req.client_id, req.pts, "worker_error")
        self._dispatch_evt.set()

    def _note_worker_error(self, slot: _Slot, exc_bytes: bytes) -> None:
        try:
            self.last_worker_error = pickle.loads(exc_bytes)
        except Exception:
            self.last_worker_error = StreamError(
                "worker fatal error (unpicklable)")
        log.error("pool %s: worker %d fatal: %s", self.name, slot.wid,
                  self.last_worker_error)

    # -- router ------------------------------------------------------------
    def _route_loop(self) -> None:
        """Admission queue -> least-outstanding ready worker. Holds at
        most one undispatched request in hand (plus re-offers); real
        backpressure lives in the admission queue, where it turns into
        typed BUSY at the door instead of unbounded memory."""
        import queue as _queue

        while not self._stop_evt.is_set():
            req = None
            with self._lock:
                if self._pending:
                    req = self._pending.popleft()
            if req is None:
                try:
                    buf = self.qs.frames.get(timeout=0.05)
                except _queue.Empty:
                    continue
                if buf is None:       # teardown sentinel
                    continue
                req = self._admit(buf)
            if not self._dispatch(req):
                with self._lock:
                    self._pending.appendleft(req)
                # no routable worker right now: wait for a reply slot,
                # a ready worker, or teardown
                self._dispatch_evt.wait(0.05)
                self._dispatch_evt.clear()

    def set_tenants(self, table) -> None:
        """Install a `serving.tenancy.TenantTable` for tenant→model
        routing (bound-slot dispatch preference + per-class shed
        accounting on this pool's failure paths)."""
        with self._lock:
            self.tenant_table = table

    def _admit(self, buf) -> _Request:
        with self._lock:
            self._next_rid += 1
            rid = self._next_rid
            table = self.tenant_table
        client_id = buf.meta.pop("client_id", None)
        buf.meta[RID_META] = rid
        cls = buf.meta.get("_tenant_class") \
            if isinstance(buf.meta, dict) else None
        model = table.model_of(cls) if table is not None else None
        return _Request(rid, client_id, buf.pts, encode_buffer(buf),
                        traced=get_trace_ctx(buf.meta) is not None,
                        cls=cls, model=model)

    def _dispatch(self, req: _Request) -> bool:
        """Send to the least-outstanding READY worker with queue room;
        False when no worker can take it right now. A request routed to
        a model prefers slots bound to that model (rebind()); when none
        has room it falls back to any candidate — a multiplex worker
        can serve every model, a bound slot is just warmer."""
        with self._lock:
            candidates = [s for s in self._slots
                          if s.state == READY
                          and len(s.inflight) < self.per_worker_queue]
            if not candidates:
                return False
            if req.model is not None:
                bound = [s for s in candidates
                         if s.bound_model == req.model]
                if bound:
                    candidates = bound
            slot = min(candidates, key=lambda s: len(s.inflight))
            req.attempts += 1
            req.t_sent = time.monotonic()
            slot.inflight[req.rid] = req
        req.hop("dispatch", wid=slot.wid, wpid=slot.pid,
                attempt=req.attempts)
        try:
            with slot.send_lock:
                # same-host shm lane: payload into the req ring, a tiny
                # control message on the pipe; ring-full (or no lane)
                # falls back to the classic pickle+pipe send — same
                # rid, same accounting, just a fatter message
                seq = slot.shm_req.try_write(req.payload) \
                    if slot.shm_ok and slot.shm_req is not None else None
                if seq is not None:
                    slot.conn.send(("reqs", req.rid, len(req.payload),
                                    seq))
                    with self._shm_stat_lock:
                        self.shm_frames += 1
                        self.shm_bytes += len(req.payload)
                else:
                    if slot.shm_ok:
                        with self._shm_stat_lock:
                            self.shm_fallbacks += 1
                    slot.conn.send(("req", req.rid, req.payload))
        except (OSError, ValueError, BrokenPipeError):
            # worker died between pick and send: undo, let the
            # supervisor reap it; the request goes back to pending
            with self._lock:
                slot.inflight.pop(req.rid, None)
                req.attempts -= 1
            if req.hops:
                req.hops.pop()
            return False
        return True

    # -- supervisor --------------------------------------------------------
    def _supervise_loop(self) -> None:
        poll = max(0.02, min(0.25, self.hb_timeout_s / 4.0))
        while not self._stop_evt.wait(poll):
            self._scan(time.monotonic())

    def _scan(self, now: float) -> None:
        """One supervision pass: detect death/hang, reap, restart."""
        for slot in self._slots:
            with self._lock:
                state = slot.state
            if state in (STARTING, READY):
                if slot.proc is not None and not slot.proc.is_alive():
                    self._reap(slot, "exit",
                               exitcode=slot.proc.exitcode)
                    continue
                grace = self.spawn_grace_s if state == STARTING \
                    else self.hb_timeout_s
                if slot.hb_age_s(now) > grace:
                    self._kill(slot, "hb_timeout")
                    continue
                oldest = None
                with self._lock:
                    if slot.inflight:
                        oldest = min(r.t_sent
                                     for r in slot.inflight.values())
                if oldest is not None and \
                        now - oldest > self.frame_deadline_s:
                    self._kill(slot, "frame_deadline")
                    continue
            elif state == DEAD and now >= slot.next_restart_t:
                self._restart(slot, now)

    def _kill(self, slot: _Slot, cause: str) -> None:
        """Hard-stop a hung worker (SIGKILL — it is by definition not
        listening) and handle it as a death."""
        slot.kills += 1
        log.warning("pool %s: killing worker %d (pid %s): %s",
                    self.name, slot.wid, slot.pid, cause)
        self._event(slot.wid, "kill", cause=cause, pid=slot.pid)
        try:
            if slot.proc is not None:
                slot.proc.kill()
        except (OSError, ValueError):
            pass
        self._reap(slot, cause)

    def _reap(self, slot: _Slot, cause: str, exitcode=None) -> None:
        """Post-mortem: drain the reader, then re-offer or shed every
        in-flight frame so conservation holds exactly through the
        death. Runs on the supervisor thread only."""
        with self._lock:
            if slot.state not in (STARTING, READY, STOPPING):
                return
            slot.state = DEAD
        if slot.proc is not None:
            slot.proc.join(timeout=5)     # reap the zombie
        try:
            if slot.conn is not None:
                slot.conn.close()         # unblocks the reader at EOF
        except OSError:
            pass
        if slot.reader is not None:
            slot.reader.join(timeout=5)
            if slot.reader.is_alive():
                log.warning("pool %s: reader of worker %d still alive "
                            "after join — leaked", self.name, slot.wid)
        self._event(slot.wid, "exit", cause=cause, exitcode=exitcode,
                    pid=slot.pid)
        # shm reclamation: the reader has drained (no more ring reads
        # can race), the process is dead (no more ring writes) — close
        # and unlink both segments so a killed worker leaks nothing;
        # the replacement spawn creates a fresh, differently-named pair
        self._drop_rings(slot)
        if self.chip_table is not None and slot.chips:
            # the dead worker's chips go out of service until the
            # replacement process re-leases them at _spawn
            fenced = self.chip_table.fence(slot.wid)
            if fenced:
                self._event(slot.wid, "chips_fenced", chips=list(fenced))
        with self._lock:
            orphaned = list(slot.inflight.values())
            slot.inflight.clear()
            live_possible = any(s.state in (STARTING, READY)
                                for s in self._slots) or \
                self._restartable(slot, time.monotonic())
        for req in orphaned:
            if req.attempts <= self.max_redeliver and live_possible \
                    and not self._stop_evt.is_set():
                # re-offer: still `inflight` in admission accounting —
                # nothing changes until it is replied or shed
                with self._lock:
                    self._pending.appendleft(req)
                self.reoffered += 1
                req.hop("reoffer", wid=slot.wid, cause=cause,
                        attempt=req.attempts)
                self._event(slot.wid, "reoffer", pts=req.pts,
                            attempts=req.attempts)
            else:
                self.qs.frames.note_failed("worker_lost", cls=req.cls)
                self.qs.send_busy(req.client_id, req.pts, "worker_lost")
        # exponential backoff before the slot restarts
        slot.backoff_s = min(
            self.restart_backoff_max_s,
            self.restart_backoff_s * (2 ** len(slot.restart_times)))
        slot.next_restart_t = time.monotonic() + slot.backoff_s
        self._dispatch_evt.set()

    def _restartable(self, slot: _Slot, now: float) -> bool:
        while slot.restart_times and \
                now - slot.restart_times[0] > self.restart_window_s:
            slot.restart_times.popleft()
        return len(slot.restart_times) < self.restart_budget

    def _restart(self, slot: _Slot, now: float) -> None:
        """Restart a dead slot — or trip its circuit: more than
        `restart_budget` restarts inside `restart_window_s` means the
        worker is systematically dying (bad model, poisoned input,
        broken native dep); the pool degrades to fewer workers and
        says so, instead of burning CPU flapping forever."""
        if not self._restartable(slot, now):
            with self._lock:
                slot.state = DISABLED
                self.degraded += 1
            log.error(
                "pool %s: worker slot %d exceeded its restart budget "
                "(%d restarts in %.0fs) — slot DISABLED, pool degraded "
                "to %d worker(s)", self.name, slot.wid,
                self.restart_budget, self.restart_window_s,
                self.live_workers())
            self._event(slot.wid, "degraded",
                        restarts_in_window=len(slot.restart_times),
                        window_s=self.restart_window_s)
            if self.chip_table is not None and slot.chips:
                # a disabled slot surrenders its chips instead of
                # pinning them fenced forever; capacity_slots drops
                freed = self.chip_table.release(slot.wid)
                slot.chips = ()
                self._event(slot.wid, "chips_released",
                            chips=list(freed))
            return
        slot.restart_times.append(now)
        slot.restarts += 1
        with self._lock:
            self._spawn(slot)
        self._event(slot.wid, "restart", backoff_s=slot.backoff_s)

    # -- hot swap ----------------------------------------------------------
    _swap_acks = None

    def swap(self, name: str, version=None,
             timeout_s: float = 30.0) -> dict:
        """Broadcast a two-phase model hot swap to every ready worker.
        All-or-none: any prepare failure aborts everywhere and the pool
        epoch does not move (PR-5 semantics across processes)."""
        import queue as _queue

        with self._lock:
            targets = [s for s in self._slots if s.state == READY]
            if not targets:
                return {"ok": False, "error": "no ready workers",
                        "epoch": self.epoch}
            acks: "_queue.Queue" = _queue.Queue()
            self._swap_acks = acks

        def phase(ph: str, slots) -> Dict[int, tuple]:
            got: Dict[int, tuple] = {}
            for s in slots:
                try:
                    with s.send_lock:
                        s.conn.send(("swap", ph, name, version))
                except (OSError, ValueError, BrokenPipeError):
                    got[s.wid] = (False, "worker died mid-swap")
            deadline = time.monotonic() + timeout_s
            while len(got) < len(slots):
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                try:
                    wid, ph_got, ok, err = acks.get(timeout=remain)
                except _queue.Empty:
                    break
                if ph_got == ph:
                    got[wid] = (ok, err)
            for s in slots:
                got.setdefault(s.wid, (False, f"no {ph} ack"))
            return got

        try:
            prep = phase("prepare", targets)
            report = {"name": name, "version": version,
                      "workers": {w: {"prepare_ok": ok, "error": err}
                                  for w, (ok, err) in prep.items()}}
            if not all(ok for ok, _ in prep.values()):
                phase("abort", targets)
                report["ok"] = False
                report["epoch"] = self.epoch
                self._event(-1, "swap_abort", model=name)
                return report
            comm = phase("commit", targets)
            for w, (ok, err) in comm.items():
                report["workers"][w]["commit_ok"] = ok
                if err:
                    report["workers"][w]["error"] = err
            report["ok"] = all(ok for ok, _ in comm.values())
            if report["ok"]:
                with self._lock:
                    self.epoch += 1
                    for s in targets:
                        s.version = (name, version)
                report["epoch"] = self.epoch
                self._event(-1, "swap_commit", model=name,
                            epoch=self.epoch)
            else:
                # a commit failure after unanimous prepare means that
                # worker is now inconsistent with its siblings: kill it
                # so the restart comes back clean
                report["epoch"] = self.epoch
                for s in targets:
                    if not comm.get(s.wid, (True, None))[0]:
                        self._kill(s, "swap_commit_failed")
            return report
        finally:
            with self._lock:
                self._swap_acks = None

    # -- replica rebinding (serving/tenancy.ScalingController) -------------
    _bind_acks = None

    def bindings(self) -> Dict[int, Optional[str]]:
        """{wid: bound model (or None)} for every ready slot — the
        ScalingController's view of the current replica assignment."""
        with self._lock:
            return {s.wid: s.bound_model for s in self._slots
                    if s.state == READY}

    @property
    def size(self) -> int:
        """Configured slot count (the scaler's allocation budget)."""
        return self.n_workers

    @property
    def capacity_slots(self) -> int:
        """Chip-weighted capacity: a slot bound to K chips serves K
        replicas' worth of traffic, so the scaler allocates against
        Σ weights, not the process count. Plain pools (no chip table)
        weigh every slot 1 — identical to `size`. DISABLED slots have
        surrendered their chips and count 0."""
        return sum(self.slot_weights().values()) or 1

    def slot_weights(self) -> Dict[int, int]:
        """{wid: capacity weight} for every non-disabled slot — chip
        count when leases exist, else 1."""
        with self._lock:
            out: Dict[int, int] = {}
            for s in self._slots:
                if s.state == DISABLED:
                    continue
                out[s.wid] = len(s.chips) if self.chip_table is not None \
                    else 1
            return out

    def rebind(self, mapping: Dict[int, Optional[str]],
               timeout_s: float = 30.0) -> dict:
        """Re-bind pool slots to models, epoch-atomically.

        `mapping` is {wid: model name or None}; slots it omits keep
        their binding. Reuses the swap broadcast's two-phase shape:
        every targeted ready worker gets prepare, any refusal (e.g. a
        multiplex worker without that model) aborts everywhere, and
        only a unanimous commit flips the parent's routing table and
        bumps the pool epoch — dispatch never sees a half-applied
        binding. A commit failure after unanimous prepare kills that
        worker (same reasoning as swap: it is now inconsistent)."""
        import queue as _queue

        with self._lock:
            targets = [s for s in self._slots
                       if s.state == READY and s.wid in mapping]
            if not targets:
                return {"ok": False, "error": "no ready workers in "
                        "mapping", "epoch": self.epoch}
            acks: "_queue.Queue" = _queue.Queue()
            self._bind_acks = acks

        def phase(ph: str, slots) -> Dict[int, tuple]:
            got: Dict[int, tuple] = {}
            for s in slots:
                try:
                    with s.send_lock:
                        s.conn.send(("bind", ph, mapping[s.wid]))
                except (OSError, ValueError, BrokenPipeError):
                    got[s.wid] = (False, "worker died mid-rebind")
            deadline = time.monotonic() + timeout_s
            while len(got) < len(slots):
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                try:
                    wid, ph_got, ok, err = acks.get(timeout=remain)
                except _queue.Empty:
                    break
                if ph_got == ph:
                    got[wid] = (ok, err)
            for s in slots:
                got.setdefault(s.wid, (False, f"no {ph} ack"))
            return got

        try:
            prep = phase("prepare", targets)
            report = {"mapping": {s.wid: mapping[s.wid]
                                  for s in targets},
                      "workers": {w: {"prepare_ok": ok, "error": err}
                                  for w, (ok, err) in prep.items()}}
            if not all(ok for ok, _ in prep.values()):
                phase("abort", targets)
                report["ok"] = False
                report["epoch"] = self.epoch
                self._event(-1, "rebind_abort")
                return report
            comm = phase("commit", targets)
            for w, (ok, err) in comm.items():
                report["workers"][w]["commit_ok"] = ok
                if err:
                    report["workers"][w]["error"] = err
            report["ok"] = all(ok for ok, _ in comm.values())
            if report["ok"]:
                with self._lock:
                    self.epoch += 1
                    self.rebinds += 1
                    for s in targets:
                        s.bound_model = mapping[s.wid]
                report["epoch"] = self.epoch
                self._event(-1, "rebind_commit", epoch=self.epoch,
                            bindings=len(targets))
            else:
                report["epoch"] = self.epoch
                for s in targets:
                    if not comm.get(s.wid, (True, None))[0]:
                        self._kill(s, "rebind_commit_failed")
            return report
        finally:
            with self._lock:
                self._bind_acks = None

    # -- introspection -----------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._closed

    def live_workers(self) -> int:
        with self._lock:
            return sum(1 for s in self._slots
                       if s.state in (STARTING, READY))

    def ready_workers(self) -> int:
        with self._lock:
            return sum(1 for s in self._slots if s.state == READY)

    def pids(self) -> Dict[int, Optional[int]]:
        with self._lock:
            return {s.wid: s.pid for s in self._slots
                    if s.state in (STARTING, READY)}

    def all_pids_ever(self) -> List[int]:
        """Every child pid this pool ever spawned (orphan audits)."""
        with self._lock:
            return list(self._all_pids)

    def shm_segments(self) -> List[str]:
        """Names of this pool's shm segments still present in /dev/shm
        — the shm half of the orphan audit: after close() (or a reap)
        this must be empty for the affected slots, exactly like
        `all_pids_ever` must be all-dead."""
        from nnstreamer_tpu.serving.shm import shm_safe

        marker = f"_{shm_safe(self.name)}_"
        try:
            return sorted(n for n in os.listdir("/dev/shm")
                          if n.startswith("nns_") and marker in n
                          and n.endswith(f"_{os.getpid()}"))
        except OSError:
            return []

    def kill_worker(self, wid: Optional[int] = None,
                    sig: int = signal.SIGKILL) -> Optional[int]:
        """Chaos surface: signal one live worker (default SIGKILL,
        random-ish: the first live slot when wid is None). Returns the
        pid signalled, None when no live worker."""
        with self._lock:
            live = [s for s in self._slots
                    if s.state in (STARTING, READY) and s.pid]
            if not live:
                return None
            slot = live[0] if wid is None else next(
                (s for s in live if s.wid == wid), None)
            if slot is None:
                return None
            pid = slot.pid
        os.kill(pid, sig)
        return pid

    def stats(self) -> dict:
        now = time.monotonic()
        with self._lock:
            workers = [{
                "wid": s.wid,
                "pid": s.pid,
                "state": s.state,
                "inflight": len(s.inflight),
                "hb_age_ms": round(1e3 * s.hb_age_s(now), 1),
                "restarts": s.restarts,
                "kills": s.kills,
                "replied": s.replied,
                "bound_model": s.bound_model,
                "chips": list(s.chips),
                "shm": s.shm_ok,
            } for s in self._slots]
            return {
                "pool": {
                    "workers": self.n_workers,
                    "live": sum(1 for s in self._slots
                                if s.state in (STARTING, READY)),
                    "ready": sum(1 for s in self._slots
                                 if s.state == READY),
                    "degraded": self.degraded,
                    "restarts": sum(s.restarts for s in self._slots),
                    "kills": sum(s.kills for s in self._slots),
                    "reoffered": self.reoffered,
                    "pending": len(self._pending),
                    "epoch": self.epoch,
                    "rebinds": self.rebinds,
                    "shm_frames": self.shm_frames,
                    "shm_bytes": self.shm_bytes,
                    "shm_fallbacks": self.shm_fallbacks,
                },
                "workers": workers,
                **({"chips": self.chip_table.snapshot()}
                   if self.chip_table is not None else {}),
            }

    def extra_stats(self) -> Dict[str, Any]:
        """Flat numeric view merged into serversrc extra_stats."""
        s = self.stats()
        out = {f"pool_{k}": v for k, v in s["pool"].items()}
        for w in s["workers"]:
            p = f"worker{w['wid']}_"
            out[p + "state"] = w["state"]
            out[p + "inflight"] = w["inflight"]
            out[p + "restarts"] = w["restarts"]
            out[p + "kills"] = w["kills"]
            out[p + "hb_age_ms"] = w["hb_age_ms"]
        return out

    # -- drain / close -----------------------------------------------------
    def close(self) -> None:
        """Graceful drain (module docstring): stop admitting, finish
        in-flight within the drain budget, BUSY the rest, stop the
        children, escalate to terminate/kill, leave no orphan.
        Idempotent — a supervisor drain racing a user close is a
        no-op, not a double-shed."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        # 1. stop admitting; queued-but-undispatched frames get a
        #    typed BUSY(shutdown) while the transport is still up
        for v in self.qs.frames.shed_remaining("shutdown"):
            if v is not None:
                self.qs.send_busy(v.meta.get("client_id"), v.pts,
                                  "shutdown")
        # 2. stop the router (it may be mid-dispatch; join it) and
        #    shed whatever it still held in hand
        self._stop_evt.set()
        self._dispatch_evt.set()
        if self._router is not None:
            self._router.join(timeout=5)
        with self._lock:
            undispatched = list(self._pending)
            self._pending.clear()
        for req in undispatched:
            self.qs.frames.note_failed("shutdown", cls=req.cls)
            self.qs.send_busy(req.client_id, req.pts, "shutdown")
        # 3. drain: in-flight frames keep completing through the live
        #    reader threads until the budget expires
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not any(s.inflight for s in self._slots):
                    break
            time.sleep(0.02)
        # 4. whatever outlived the budget is shed — abandoning the rid
        #    first so a late result is ignored, never double-counted
        abandoned: List[_Request] = []
        with self._lock:
            for s in self._slots:
                abandoned.extend(s.inflight.values())
                s.inflight.clear()
        for req in abandoned:
            self.qs.frames.note_failed("shutdown", cls=req.cls)
            self.qs.send_busy(req.client_id, req.pts, "shutdown")
        # 5. stop the supervisor, then the children: graceful stop
        #    first, escalate terminate -> kill; join readers
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
        for slot in self._slots:
            with self._lock:
                if slot.state in (DEAD, DISABLED) or slot.proc is None:
                    continue
                slot.state = STOPPING
            try:
                with slot.send_lock:
                    slot.conn.send(("stop",))
            except (OSError, ValueError, BrokenPipeError):
                pass
        for slot in self._slots:
            proc = slot.proc
            if proc is None:
                continue
            proc.join(timeout=2)
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=2)
            if proc.is_alive():
                proc.kill()
                proc.join(timeout=5)
            try:
                if slot.conn is not None:
                    slot.conn.close()
            except OSError:
                pass
            if slot.reader is not None:
                slot.reader.join(timeout=2)
            self._drop_rings(slot)
            self._event(slot.wid, "drain_stop", pid=slot.pid)
        # 6. transport down last: every owed BUSY has been sent
        self.qs.pool = None
        self.qs.stop()


class PooledQueryServer:
    """A query server whose service plane is a supervised worker pool:
    the multi-process sibling of `BatchedQueryServer` (edge/query.py).
    Same wire contract (HELLO caps, DATA/RESULT/BUSY), same admission
    accounting — plus crash isolation, restart, and drain.

    ``PooledQueryServer.echo(workers=2, service_ms=5)`` builds the
    known-capacity form the traffic harness and the chaos tests use;
    pass a full `WorkerSpec` for real pipelines.
    """

    def __init__(self, spec: WorkerSpec, *, workers: int = 2,
                 sid: int = 0, host: str = "127.0.0.1", port: int = 0,
                 max_pending: int = 64, max_inflight: int = 0,
                 shed_policy: str = "reject-newest",
                 tenants=None,
                 tracer=None, ready_timeout_s: float = 30.0,
                 **pool_kwargs):
        self.qs = QueryServer.get(sid)
        self.sid = sid
        self.qs.in_spec = TensorsSpec.from_strings(spec.dims, spec.types)
        if spec.kind == "echo":
            self.qs.out_spec = self.qs.in_spec
        self.qs.frames.configure(max_pending=max_pending,
                                 max_inflight=max_inflight,
                                 shed_policy=shed_policy)
        # tenancy: one table drives all three layers — the WFQ
        # admission front, the pool's tenant→model dispatch routing,
        # and (for multiplex workers) the spec's child-side copy
        self.tenants = tenants
        if tenants is not None:
            self.qs.frames.set_tenants(tenants)
            if spec.kind == "multiplex" and not spec.tenants:
                import dataclasses

                spec = dataclasses.replace(
                    spec, tenants=tenants.to_dict())
        if tracer is not None:
            self.qs.tracer = tracer
        self.qs.start(host, port)
        self.pool = WorkerPool(self.qs, spec, workers, **pool_kwargs)
        if tenants is not None:
            self.pool.set_tenants(tenants)
        self.pool.start(ready_timeout_s=ready_timeout_s)
        self._sig_prev: Dict[int, Any] = {}

    @classmethod
    def echo(cls, *, workers: int = 2, service_ms: float = 5.0,
             dims: str = "8:1", types: str = "float32",
             **kwargs) -> "PooledQueryServer":
        return cls(WorkerSpec(kind="echo", service_ms=service_ms,
                              dims=dims, types=types),
                   workers=workers, **kwargs)

    @property
    def port(self) -> int:
        assert self.qs.server is not None
        return self.qs.server.port

    @property
    def capacity_rps(self) -> float:
        """Aggregate known capacity (echo mode only)."""
        if self.pool.spec.kind != "echo" or \
                self.pool.spec.service_ms <= 0:
            return float("inf")
        return self.pool.n_workers * 1e3 / self.pool.spec.service_ms

    def depth_probe(self) -> int:
        return self.qs.frames.depth

    def admission_counters(self) -> dict:
        return self.qs.frames.counters()

    def stats(self) -> dict:
        out = self.pool.stats()
        out["admission"] = self.qs.frames.counters()
        return out

    def swap(self, name: str, version=None, **kw) -> dict:
        return self.pool.swap(name, version, **kw)

    def rebind(self, mapping, **kw) -> dict:
        return self.pool.rebind(mapping, **kw)

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT -> graceful drain (serve CLI): the contract a
        process manager expects from a serving edge."""
        def handler(signum, frame):
            log.info("signal %d: draining worker pool", signum)
            self.close()
            prev = self._sig_prev.get(signum)
            if callable(prev):
                prev(signum, frame)
        for signum in (signal.SIGTERM, signal.SIGINT):
            self._sig_prev[signum] = signal.signal(signum, handler)

    def close(self) -> None:
        self.pool.close()   # idempotent; also stops the QueryServer
