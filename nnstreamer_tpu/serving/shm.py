"""Same-host shared-memory ring transport for pool worker hops.

Every parent↔worker tensor hop used to pay ``pickle.dumps`` + a pipe
write + ``pickle.loads`` — fine for control traffic, a real tax when
the payload is a multi-megabyte wire frame moving twice per request.
This module gives each worker slot a pair of single-producer /
single-consumer byte rings over ``multiprocessing.shared_memory``:

- ``req`` ring: parent writes, child reads (request payloads);
- ``res`` ring: child writes, parent reads (result payloads).

The payload bytes are the *same* wire-frame bytes the pipe would have
carried (edge/wire.py — the cross-host protocol is untouched); only the
carrier changes. A tiny control message still rides the existing pipe
(``("reqs", rid, nbytes, seq)`` / ``("ress", rid, nbytes, seq)``), which
gives ordering for free: the producer finishes the ring write *before*
the pipe send, and the consumer only reads a record the pipe told it
about, so the syscall pair in the middle is the memory barrier and the
ring needs no locks at all.

Ring layout (offsets within the segment)::

    u64 write_pos   # monotonic byte count, producer-owned
    u64 read_pos    # monotonic byte count, consumer-owned
    capacity bytes of ring data  (records: SHM_REC header + payload)

Failure handling is transparency, not correctness theatre:

- ring full (or payload bigger than the ring) → the producer sends the
  whole payload on the pipe as before and counts a fallback;
- child can't attach (permissions, platform) → it acks ``shm: False``
  at handshake and both sides stay on pickle;
- worker killed → the parent's conservation story is unchanged because
  request payloads are retained parent-side for redelivery; the slot's
  rings are closed **and unlinked** at reap, and a respawn creates
  fresh uniquely-named rings, so no stale record is ever read and no
  segment outlives its slot (the worker-kill drill audits /dev/shm).
"""

from __future__ import annotations

import os
import struct
from typing import Optional

from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.edge.wire import SHM_REC, pack_shm_record, \
    unpack_shm_record

log = get_logger("serving.shm")

#: bytes of the two cursor words ahead of the ring data
_HDR = 16
_POS = struct.Struct("<Q")

#: default per-direction ring capacity (bytes); a knob on WorkerSpec
DEFAULT_RING_BYTES = 1 << 22


def shm_supported() -> bool:
    """Whether this interpreter can create POSIX shared memory at all
    (the transport self-disables rather than erroring where it can't —
    the pipe lane is always there)."""
    try:
        from multiprocessing import shared_memory  # noqa: F401
    except Exception:
        return False
    return True


class ShmRing:
    """One SPSC byte ring over one shared-memory segment.

    Exactly one process calls ``try_write`` (producer) and exactly one
    calls ``read_record`` (consumer); each cursor word has a single
    writer, which is the whole synchronization story — ordering comes
    from the pipe control message (see module docstring).
    """

    __slots__ = ("name", "capacity", "_shm", "_buf", "_owner", "_seq")

    def __init__(self, shm, capacity: int, owner: bool):
        self.name = shm.name
        self.capacity = capacity
        self._shm = shm
        self._buf = shm.buf
        self._owner = owner
        self._seq = 0

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def create(cls, name: str, capacity: int = DEFAULT_RING_BYTES
               ) -> "ShmRing":
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(
            name=name, create=True, size=_HDR + int(capacity))
        shm.buf[:_HDR] = b"\x00" * _HDR
        return cls(shm, int(capacity), owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        from multiprocessing import shared_memory

        # NOTE on the resource tracker: a spawned worker shares the
        # pool parent's tracker process, and attaching registers the
        # name there as a (deduplicated) set entry. We deliberately do
        # NOT unregister here — the parent's unlink at reap/close
        # removes the single entry cleanly, and if the whole tree dies
        # hard the tracker's exit sweep unlinks the segment instead of
        # orphaning it in /dev/shm.
        shm = shared_memory.SharedMemory(name=name, create=False)
        return cls(shm, shm.size - _HDR, owner=False)

    def close(self) -> None:
        try:
            self._buf = None
            self._shm.close()
        except Exception:
            pass

    def unlink(self) -> None:
        """Creator-side removal of the segment name. Idempotent — reap
        and close() may both land here."""
        if not self._owner:
            return
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass
        except Exception:
            pass

    # -- cursors -----------------------------------------------------------
    def _load(self, off: int) -> int:
        return _POS.unpack_from(self._buf, off)[0]

    def _store(self, off: int, val: int) -> None:
        _POS.pack_into(self._buf, off, val)

    @property
    def used(self) -> int:
        return self._load(0) - self._load(8)

    @property
    def free(self) -> int:
        return self.capacity - self.used

    # -- producer ----------------------------------------------------------
    def try_write(self, payload: bytes) -> Optional[int]:
        """Append one record; returns its seq, or ``None`` when the
        record doesn't fit (caller falls back to the pipe lane — never
        blocks, never partially writes)."""
        need = SHM_REC.size + len(payload)
        if need > self.free or self._buf is None:
            return None
        self._seq += 1
        w = self._load(0)
        self._copy_in(w, pack_shm_record(payload, self._seq))
        self._copy_in(w + SHM_REC.size, payload)
        self._store(0, w + need)
        return self._seq

    def _copy_in(self, pos: int, data: bytes) -> None:
        off = pos % self.capacity
        first = min(len(data), self.capacity - off)
        self._buf[_HDR + off:_HDR + off + first] = data[:first]
        if first < len(data):          # wrap
            rest = len(data) - first
            self._buf[_HDR:_HDR + rest] = data[first:]

    # -- consumer ----------------------------------------------------------
    def read_record(self, expect_len: int, expect_seq: int) -> bytes:
        """Pop the next record, which the pipe control message promised
        is ``(expect_len, expect_seq)``; raises ValueError on any
        mismatch (stale/torn record — the reader treats the lane as
        faulted and the request is recovered via redelivery)."""
        r = self._load(8)
        head = self._copy_out(r, SHM_REC.size)
        length, seq = unpack_shm_record(head)
        if length != expect_len or seq != expect_seq:
            raise ValueError(
                f"shm record mismatch: ring has len={length} seq={seq}, "
                f"control said len={expect_len} seq={expect_seq}")
        payload = self._copy_out(r + SHM_REC.size, length)
        self._store(8, r + SHM_REC.size + length)
        return payload

    def _copy_out(self, pos: int, n: int) -> bytes:
        off = pos % self.capacity
        first = min(n, self.capacity - off)
        out = bytes(self._buf[_HDR + off:_HDR + off + first])
        if first < n:                  # wrap
            out += bytes(self._buf[_HDR:_HDR + (n - first)])
        return out


def _hop_child(conn, ring_req: str, ring_res: str, n: int) -> None:
    """Child half of `hop_latency_ab` (spawn target — must live in an
    importable module, not the bench script): echo `n` payloads back
    over whichever lane the parent chose."""
    rq = rs = None
    if ring_req:
        rq = ShmRing.attach(ring_req)
        rs = ShmRing.attach(ring_res)
    try:
        for _ in range(n):
            if rq is not None:
                _, rid, nbytes, seq = conn.recv()
                payload = rq.read_record(nbytes, seq)
                seq2 = rs.try_write(payload)
                conn.send(("ress", rid, len(payload), seq2))
            else:
                _, rid, payload = conn.recv()
                conn.send(("res", rid, payload))
    finally:
        for ring in (rq, rs):
            if ring is not None:
                ring.close()
        conn.close()


def hop_latency_ab(payload_bytes: int = 1 << 20, n: int = 200,
                   ring_bytes: int = DEFAULT_RING_BYTES) -> dict:
    """Closed-loop same-host hop A/B: one payload round-trips
    parent↔child `n` times over (a) the pickle pipe — the payload
    inside a control tuple, ``conn.send(("req", rid, payload))``,
    exactly what pool dispatch does when the lane is off — and (b) the
    shm ring pair with the same control tuple minus the payload. Both
    lanes are the pool's real message shapes with nothing else on the
    clock. Returns per-lane round-trip p50/p99 (ms) and the pipe/shm
    speedup; `shm_ok` is the bench's "the lane earns its keep"
    verdict."""
    import multiprocessing as mp
    import time

    ctx = mp.get_context("spawn")
    payload = b"\xa5" * int(payload_bytes)
    out: dict = {"payload_bytes": int(payload_bytes), "round_trips": n}
    for key in ("pipe", "shm"):
        rq = rs = None
        if key == "shm":
            rq = ShmRing.create(ring_name("hq", "hopab", 0, 0),
                                ring_bytes)
            rs = ShmRing.create(ring_name("hs", "hopab", 0, 0),
                                ring_bytes)
        a, b = ctx.Pipe()
        proc = ctx.Process(
            target=_hop_child,
            args=(b, rq.name if rq else "", rs.name if rs else "",
                  n + 5))
        proc.start()
        b.close()
        lats = []
        try:
            def round_trip():
                if rq is not None:
                    seq = rq.try_write(payload)
                    a.send(("reqs", 1, len(payload), seq))
                    _, _, nbytes, seq2 = a.recv()
                    rs.read_record(nbytes, seq2)
                else:
                    a.send(("req", 1, payload))
                    a.recv()

            for _ in range(5):        # spawn + import warmup, untimed
                round_trip()
            for _ in range(n):
                t0 = time.perf_counter()
                round_trip()
                lats.append((time.perf_counter() - t0) * 1e3)
        finally:
            a.close()
            proc.join(timeout=10)
            if proc.is_alive():
                proc.kill()
                proc.join()
            for ring in (rq, rs):
                if ring is not None:
                    ring.close()
                    ring.unlink()
        lats.sort()
        out[key + "_p50_ms"] = round(lats[len(lats) // 2], 3)
        out[key + "_p99_ms"] = round(lats[min(len(lats) - 1,
                                              int(len(lats) * 0.99))], 3)
    out["hop_speedup"] = (round(out["pipe_p50_ms"] / out["shm_p50_ms"], 2)
                          if out["shm_p50_ms"] else 0.0)
    out["shm_ok"] = out["shm_p50_ms"] <= out["pipe_p50_ms"]
    return out


def shm_safe(name: str) -> str:
    """Pool names may be arbitrary; segment names may not."""
    return "".join(c if c.isalnum() else "-" for c in name)[:32]


def ring_name(kind: str, pool_name: str, wid: int, spawn: int) -> str:
    """Unique-per-spawn segment name: a respawned slot never attaches
    its predecessor's ring, so a killed worker's half-written state is
    unreachable by construction. The creating pid suffixes the name so
    one host's concurrent pools (tests!) can never collide."""
    return (f"nns_{kind}_{shm_safe(pool_name)}_{wid}_{spawn}_"
            f"{os.getpid()}")
