"""Online SLO autotuner — closed-loop control of the serving knobs.

Every load-sensitive knob the runtime has grown (admission
``max_pending``, the ``tensor_batch`` deadline, the compile-bucket set,
shed policy, pool sizing) is set by hand, while the tracer already
measures exactly what a controller needs. This module closes the loop
(docs/autotune.md):

- **SLOSpec** — the declared contract: a p99 latency budget, an
  optional goodput floor, optional per-tenant budget overrides, and
  declared min/max ranges per knob. JSON-loadable like the tenant
  table (``serve --slo FILE``), eagerly validated with typed errors.

- **AutoTuner** — a controller thread (same lifecycle shape as the
  tenancy ``ScalingController``: ``start()``/``stop()``/``tick()``,
  injectable clock) closing sensor→decision→actuation:

  * sensors read only existing surfaces — ``AdmissionQueue.counters()``
    (depth, per-cause sheds, the EWMA reply interval), the tracer's
    interlatency percentiles and ``tenant_summary()``, the batch
    element's occupancy stats, and the XLA backend's observed
    batch-size histogram;
  * actuators are existing live-reconfiguration paths —
    ``AdmissionQueue.configure()`` with a ``max_pending`` derived from
    the *measured* service rate (Little's law: the depth the p99
    budget can absorb at the observed per-reply interval), the batch
    deadline via ``tensor_batch``'s live-read props, and bucket-set
    refinement staged through the backend's pre-warm path
    (``stage_bucket``) so a bucket change never recompiles in-band;
  * shed-policy and pool-scaling decisions are **hints only**
    (outcome ``proposed``): the tenancy ScalingController stays the
    single binding owner — the autotuner proposes, the scaler binds.

Every decision passes one guardrail ladder (`_drive`): clamp to the
declared knob range, a hysteresis band (small deviations are held, so
flapping sensors cannot oscillate the knob), a per-knob cooldown, and
a bounded step toward the target. Each decision lands in a bounded
audit ring (knob, old, new, sensor evidence, outcome) with exact
accounting across ring wrap, is recorded on the tracer
(``record_autotune``), and is exported as ``nns_autotune_*`` series
(serving/metrics.py). ``dry_run=True`` evaluates and records every
decision without applying anything.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from nnstreamer_tpu.serving.tenancy import validate_tenant_name

log = logging.getLogger("nnstreamer_tpu.autotune")

#: decision outcomes (audit ring / metrics label values)
OUTCOMES = ("applied", "dry_run", "proposed", "hysteresis", "cooldown",
            "error")

#: headroom factor on the Little's-law admission target: a queue sized
#: to exactly budget/ewma puts the last admitted request AT the budget,
#: and the wait the bound predicts is a floor — the in-service request,
#: host scheduling jitter, and reply overhead all add on top (the ramp
#: drill measures the tail ~1.3x over (depth+1)*ewma on a loaded CPU
#: host). Aim the settled wait at mid-budget so the observed p99 lands
#: under the budget, not on it.
LITTLE_MARGIN = 0.5


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (int(n) - 1).bit_length()


@dataclass(frozen=True)
class KnobRange:
    """Declared [lo, hi] clamp for one knob (both inclusive)."""

    knob: str
    lo: float
    hi: float

    def __post_init__(self):
        for side, v in (("min", self.lo), ("max", self.hi)):
            if not isinstance(v, (int, float)) or not math.isfinite(v):
                raise ValueError(
                    f"knob {self.knob!r}: {side} must be a finite "
                    f"number, got {v!r}")
        if self.lo > self.hi:
            raise ValueError(
                f"knob {self.knob!r}: min {self.lo} > max {self.hi}")

    def clamp(self, v: float) -> float:
        return min(max(v, self.lo), self.hi)


#: knobs the controller understands, with conservative default ranges
#: (an SLO file narrows them; it cannot invent new knob names)
DEFAULT_KNOB_RANGES: Dict[str, KnobRange] = {
    "max_pending": KnobRange("max_pending", 2, 4096),
    "batch_deadline_ms": KnobRange("batch_deadline_ms", 0.25, 200.0),
    "max_batch": KnobRange("max_batch", 1, 1024),
}


@dataclass(frozen=True)
class SLOSpec:
    """The declared serving contract the controller defends.

    JSON shape (``serve --slo FILE``, mirroring the tenant table)::

        {"p99_budget_ms": 90,
         "goodput_floor_rps": 50,
         "tenants": {"acme": {"p99_budget_ms": 50}},
         "knobs": {"max_pending": {"min": 4, "max": 256},
                   "batch_deadline_ms": {"min": 1, "max": 20}}}
    """

    p99_budget_ms: float
    goodput_floor_rps: float = 0.0
    tenants: Dict[str, float] = field(default_factory=dict)
    knobs: Dict[str, KnobRange] = field(default_factory=dict)

    def __post_init__(self):
        b = self.p99_budget_ms
        if not isinstance(b, (int, float)) or not math.isfinite(b) \
                or b <= 0:
            raise ValueError(
                f"p99_budget_ms must be a finite number > 0, got {b!r}")
        g = self.goodput_floor_rps
        if not isinstance(g, (int, float)) or not math.isfinite(g) \
                or g < 0:
            raise ValueError(
                f"goodput_floor_rps must be a finite number >= 0, "
                f"got {g!r}")
        for name, budget in self.tenants.items():
            if not validate_tenant_name(name):
                raise ValueError(
                    f"tenant override {name!r} is invalid: must match "
                    f"[a-zA-Z0-9_-]{{1,64}}")
            if not isinstance(budget, (int, float)) \
                    or not math.isfinite(budget) or budget <= 0:
                raise ValueError(
                    f"tenant {name!r}: p99_budget_ms must be a finite "
                    f"number > 0, got {budget!r}")
        for knob in self.knobs:
            if knob not in DEFAULT_KNOB_RANGES:
                raise ValueError(
                    f"unknown knob {knob!r}: declared knobs are "
                    f"{' | '.join(sorted(DEFAULT_KNOB_RANGES))}")

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SLOSpec":
        """Parse + validate eagerly — a malformed SLO file fails at
        load time with a typed error, never mid-control-loop."""
        if not isinstance(d, dict):
            raise ValueError(
                f"SLO spec must be a JSON object, got {type(d).__name__}")
        if "p99_budget_ms" not in d:
            raise ValueError("SLO spec needs p99_budget_ms")
        tenants: Dict[str, float] = {}
        raw_t = d.get("tenants", {})
        if not isinstance(raw_t, dict):
            raise ValueError(
                f"tenants must be a name -> override mapping, "
                f"got {type(raw_t).__name__}")
        for name, spec in raw_t.items():
            if isinstance(spec, dict):
                if "p99_budget_ms" not in spec:
                    raise ValueError(
                        f"tenant {name!r}: override needs p99_budget_ms")
                tenants[name] = _num(spec["p99_budget_ms"],
                                     f"tenant {name!r} p99_budget_ms")
            else:
                tenants[name] = _num(spec,
                                     f"tenant {name!r} p99_budget_ms")
        knobs: Dict[str, KnobRange] = {}
        raw_k = d.get("knobs", {})
        if not isinstance(raw_k, dict):
            raise ValueError(
                f"knobs must be a name -> {{min, max}} mapping, "
                f"got {type(raw_k).__name__}")
        for knob, rng in raw_k.items():
            if not isinstance(rng, dict) or "min" not in rng \
                    or "max" not in rng:
                raise ValueError(
                    f"knob {knob!r}: range must be an object with "
                    f"min and max, got {rng!r}")
            knobs[knob] = KnobRange(
                knob, _num(rng["min"], f"knob {knob!r} min"),
                _num(rng["max"], f"knob {knob!r} max"))
        return cls(
            p99_budget_ms=_num(d["p99_budget_ms"], "p99_budget_ms"),
            goodput_floor_rps=_num(d.get("goodput_floor_rps", 0.0),
                                   "goodput_floor_rps"),
            tenants=tenants, knobs=knobs)

    @classmethod
    def from_json(cls, path: str) -> "SLOSpec":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    def knob_range(self, knob: str) -> KnobRange:
        return self.knobs.get(knob) or DEFAULT_KNOB_RANGES[knob]

    def tenant_budget_ms(self, tenant: str) -> float:
        return self.tenants.get(tenant, self.p99_budget_ms)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "p99_budget_ms": self.p99_budget_ms,
            "goodput_floor_rps": self.goodput_floor_rps,
            "tenants": dict(self.tenants),
            "knobs": {k: {"min": r.lo, "max": r.hi}
                      for k, r in self.knobs.items()},
        }


def _num(v: Any, what: str) -> float:
    if isinstance(v, bool) or not isinstance(v, (int, float)) \
            or not math.isfinite(v):
        raise ValueError(f"{what} must be a finite number, got {v!r}")
    return float(v)


class AutoTuner:
    """The controller thread (module docstring; docs/autotune.md).

    Bindings are all optional — the controller only drives the knobs
    it was given targets for, so tests can bind a single fake:

    admission       — an AdmissionQueue (configure()/counters())
    batch_elements  — tensor_batch elements (live ``props`` actuation)
    filters         — tensor_filter elements whose backend exposes the
                      observed ``batch_size_hist`` (bucket refinement)
    scaler          — tenancy ScalingController (hints only; it binds)
    tracer          — decisions recorded via ``record_autotune``
    on_apply        — callback(record) after each applied decision
                      (the bench drill checks conservation here)
    on_victims      — callback(list) for entries a configure() shrink
                      shed (each is owed a BUSY reply by the caller)
    """

    def __init__(self, slo: SLOSpec, admission: Any = None,
                 batch_elements: Tuple[Any, ...] = (),
                 filters: Tuple[Any, ...] = (),
                 scaler: Any = None, tracer: Any = None,
                 interval_s: float = 1.0, dry_run: bool = False,
                 step_frac: float = 0.5, hysteresis_frac: float = 0.15,
                 cooldown_s: float = 5.0, audit_size: int = 256,
                 on_apply: Optional[Callable[[dict], None]] = None,
                 on_victims: Optional[Callable[[List[Any]], None]] = None,
                 now: Callable[[], float] = time.monotonic,
                 name: str = "autotune"):
        self.slo = slo
        self.admission = admission
        self.batch_elements = tuple(batch_elements)
        self.filters = tuple(filters)
        self.scaler = scaler
        self.tracer = tracer
        self.interval_s = float(interval_s)
        self.dry_run = bool(dry_run)
        self.step_frac = float(step_frac)
        self.hysteresis_frac = float(hysteresis_frac)
        self.cooldown_s = float(cooldown_s)
        self.name = name
        # flight recorder (runtime/flightrec.py attach()): when set,
        # tick() feeds it observed-p99-over-budget breaches
        self.flight: Any = None
        self._on_apply = on_apply
        self._on_victims = on_victims
        self._now = now
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # accounting (under _lock): the audit ring holds the last
        # `audit_size` decisions; the per-knob/outcome counters keep
        # the exact totals across ring wrap
        self._audit: deque = deque(maxlen=max(1, int(audit_size)))
        self._audit_total = 0
        self._decisions: Dict[str, Dict[str, int]] = {}
        self.ticks = 0
        self._last_apply: Dict[str, float] = {}
        self._last_hint: Dict[str, Any] = {}
        # bucket refinement never raises max_batch past what the batch
        # element negotiated downstream — record the ceiling at bind
        self._batch_ceilings = {
            id(el): int(el.props["max_batch"])
            for el in self.batch_elements}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "AutoTuner":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="slo-autotuner", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                log.exception("autotune tick failed")

    # -- one control-loop pass ---------------------------------------------
    def tick(self) -> List[dict]:
        """One sensor→decision→actuation pass; returns the decision
        records produced (possibly empty). Callable from tests with an
        injected clock."""
        now = self._now()
        with self._lock:
            self.ticks += 1
        out: List[dict] = []
        for fn in (self._tick_admission, self._tick_batch_deadline,
                   self._tick_buckets, self._tick_hints):
            try:
                out.extend(fn(now))
            except Exception:
                log.exception("autotune stage %s failed", fn.__name__)
        if self.flight is not None:
            try:
                p99 = self._observed_p99_ms()
                if p99 is not None and p99 > self.slo.p99_budget_ms:
                    self.flight.note_slo_breach(
                        p99, self.slo.p99_budget_ms, source=self.name)
            except Exception:
                log.exception("flight-recorder SLO feed failed")
        return out

    # -- stages ------------------------------------------------------------
    def _tick_admission(self, now: float) -> List[dict]:
        """Little's-law admission bound: the p99 budget divided by the
        measured per-reply interval is the deepest queue whose wait
        still fits the budget — that is what max_pending should be,
        not a guess."""
        if self.admission is None:
            return []
        c = self.admission.counters()
        ewma = c.get("ewma_reply_s")
        if not ewma or not math.isfinite(ewma) or ewma <= 0:
            return []                   # no service-rate signal yet
        target = LITTLE_MARGIN * (self.slo.p99_budget_ms / 1e3) / ewma
        evidence = {"ewma_reply_s": round(ewma, 6),
                    "p99_budget_ms": self.slo.p99_budget_ms,
                    "depth": c["depth"], "depth_peak": c["depth_peak"]}

        def apply(v: float) -> None:
            victims = self.admission.configure(max_pending=int(round(v)))
            if victims and self._on_victims is not None:
                self._on_victims(victims)

        rec = self._drive("max_pending", float(c["max_pending"]), target,
                          evidence, apply, now, integer=True)
        return [rec] if rec else []

    def _tick_batch_deadline(self, now: float) -> List[dict]:
        """Adaptive batch deadline: grow it while the observed p99 has
        headroom and batches flush half-empty (occupancy is where
        throughput comes from); shrink it the moment the p99 budget is
        threatened — latency wins over occupancy."""
        if not self.batch_elements:
            return []
        p99 = self._observed_p99_ms()
        if p99 is None:
            return []
        budget = self.slo.p99_budget_ms
        out = []
        for el in self.batch_elements:
            cur = float(el.props["max_latency_ms"])
            st = el.extra_stats()
            occ = float(st.get("occupancy_avg", 0.0))
            max_batch = int(el.props["max_batch"])
            if p99 > 0.8 * budget:
                target = cur * 0.5
            elif p99 < 0.4 * budget and st.get("batches_out", 0) \
                    and occ < 0.5 * max_batch:
                target = cur * 2.0
            else:
                continue
            evidence = {"p99_ms": round(p99, 3), "p99_budget_ms": budget,
                        "occupancy_avg": round(occ, 2),
                        "max_batch": max_batch}
            rec = self._drive(
                "batch_deadline_ms", cur, target, evidence,
                lambda v, el=el: el.props.__setitem__(
                    "max_latency_ms", float(v)),
                now, label=el.name)
            if rec:
                out.append(rec)
        return out

    def _tick_buckets(self, now: float) -> List[dict]:
        """Bucket-set refinement from the observed batch-size
        histogram: when the p95 observed occupancy fits a smaller pow2
        bucket than max_batch advertises, shrink max_batch to that
        bucket — batches then fill their compile bucket exactly
        instead of padding. The smaller bucket is staged through the
        backend's pre-warm path first, so the flip never recompiles
        in-band. Shrink-only: the negotiated ceiling is never raised."""
        if not self.batch_elements or not self.filters:
            return []
        hist: Dict[int, int] = {}
        backends = []
        for f in self.filters:
            h = getattr(getattr(f, "backend", None),
                        "batch_size_hist", None)
            if h:
                backends.append(f.backend)
                for n, cnt in dict(h).items():
                    hist[int(n)] = hist.get(int(n), 0) + int(cnt)
        total = sum(hist.values())
        if total < 8:
            return []                  # not enough signal to refine on
        p95 = _hist_percentile(hist, 95.0)
        target_bucket = _next_pow2(p95)
        out = []
        for el in self.batch_elements:
            cur = float(el.props["max_batch"])
            ceiling = self._batch_ceilings.get(id(el), int(cur))
            target = float(min(target_bucket, ceiling))
            if target >= cur:
                continue               # refinement only ever shrinks
            evidence = {"occupancy_p95": p95,
                        "target_bucket": target_bucket,
                        "invokes": total}

            def apply(v: float, el=el, backends=tuple(backends)) -> None:
                nb = int(round(v))
                for be in backends:
                    stage = getattr(be, "stage_bucket", None)
                    if stage is not None:
                        stage(nb)      # off-band compile, never in-band
                el.props["max_batch"] = nb

            rec = self._drive("max_batch", cur, target, evidence,
                              apply, now, integer=True, label=el.name)
            if rec:
                out.append(rec)
        return out

    def _tick_hints(self, now: float) -> List[dict]:
        """Advisory decisions (outcome ``proposed``; never actuated):
        pool scaling when the measured reply rate sits under the
        declared goodput floor at a saturated queue, and a shed-policy
        suggestion when a saturated reject-newest queue is serving
        requests that then miss the budget anyway. The tenancy scaler
        stays the binding owner for both."""
        if self.admission is None:
            return []
        c = self.admission.counters()
        ewma = c.get("ewma_reply_s")
        out = []
        if self.slo.goodput_floor_rps > 0 and ewma and ewma > 0:
            rate = 1.0 / ewma
            saturated = c["depth"] >= max(1, c["max_pending"] // 2)
            if rate < self.slo.goodput_floor_rps and saturated:
                rec = self._propose(
                    "pool_slots", "current", "scale_up",
                    {"reply_rate_rps": round(rate, 2),
                     "goodput_floor_rps": self.slo.goodput_floor_rps,
                     "depth": c["depth"]}, now)
                if rec:
                    out.append(rec)
        p99 = self._observed_p99_ms()
        if p99 is not None and p99 > self.slo.p99_budget_ms \
                and c["shed_policy"] == "reject-newest" \
                and c["depth_peak"] >= c["max_pending"]:
            rec = self._propose(
                "shed_policy", "reject-newest", "reject-oldest",
                {"p99_ms": round(p99, 3),
                 "p99_budget_ms": self.slo.p99_budget_ms,
                 "depth_peak": c["depth_peak"]}, now)
            if rec:
                out.append(rec)
        return out

    # -- sensors -----------------------------------------------------------
    def _observed_p99_ms(self) -> Optional[float]:
        """Worst observed p99 across the tracer's surfaces: tenant
        request latency when tenancy records it, else the widest
        per-element interlatency."""
        tr = self.tracer
        if tr is None or not getattr(tr, "active", False):
            return None
        vals: List[float] = []
        try:
            for row in tr.tenant_summary().values():
                vals.append(float(row.get("p99_ms", 0.0)))
        except Exception:
            pass
        if not vals:
            try:
                for row in tr.interlatency().values():
                    vals.append(float(row.get("p99_ms", 0.0)))
            except Exception:
                pass
        return max(vals) if vals else None

    # -- the guardrail ladder ----------------------------------------------
    def _drive(self, knob: str, current: float, target: float,
               evidence: Dict[str, Any], apply: Callable[[float], Any],
               now: float, integer: bool = False,
               label: Optional[str] = None) -> Optional[dict]:
        """Clamp → hysteresis → cooldown → bounded step → actuate.
        Returns the audit record for a decision that moved (applied /
        dry_run / error); holds count in the outcome counters only, so
        a flapping sensor cannot flood the ring."""
        rng = self.slo.knob_range(knob)
        clamped = rng.clamp(target)
        if abs(clamped - current) <= \
                self.hysteresis_frac * max(abs(current), 1e-9):
            self._count(knob, "hysteresis")
            return None
        last = self._last_apply.get(knob)
        if last is not None and now - last < self.cooldown_s:
            self._count(knob, "cooldown")
            return None
        step = abs(current) * self.step_frac
        if integer:
            step = max(step, 1.0)
        new = rng.clamp(current + min(max(clamped - current, -step), step))
        if integer:
            new = float(int(round(new)))
        if new == current:
            self._count(knob, "hysteresis")
            return None
        outcome = "dry_run" if self.dry_run else "applied"
        if not self.dry_run:
            try:
                apply(new)
            except Exception:
                log.exception("actuating %s=%s failed", knob, new)
                outcome = "error"
        # dry_run honors the cooldown too: the decision stream must
        # look exactly like the live one, just without actuation
        self._last_apply[knob] = now
        return self._record(knob, current, new, evidence, outcome, now,
                            label=label)

    def _propose(self, knob: str, old: Any, new: Any,
                 evidence: Dict[str, Any], now: float) -> Optional[dict]:
        """Hint path: cooldown + dedup (the same proposal is not
        re-recorded every tick), never actuates."""
        last = self._last_apply.get(knob)
        if last is not None and now - last < self.cooldown_s:
            self._count(knob, "cooldown")
            return None
        if self._last_hint.get(knob) == new:
            self._count(knob, "hysteresis")
            return None
        self._last_hint[knob] = new
        self._last_apply[knob] = now
        return self._record(knob, old, new, evidence, "proposed", now)

    def _count(self, knob: str, outcome: str) -> None:
        with self._lock:
            d = self._decisions.setdefault(knob, {})
            d[outcome] = d.get(outcome, 0) + 1

    def _record(self, knob: str, old: Any, new: Any,
                evidence: Dict[str, Any], outcome: str, now: float,
                label: Optional[str] = None) -> dict:
        rec = {"t": now, "knob": knob, "old": old, "new": new,
               "evidence": dict(evidence), "outcome": outcome}
        if label:
            rec["target"] = label
        with self._lock:
            self._audit.append(rec)
            self._audit_total += 1
            d = self._decisions.setdefault(knob, {})
            d[outcome] = d.get(outcome, 0) + 1
        # side effects outside the lock (tracer/callback take their own)
        tr = self.tracer
        if tr is not None:
            try:
                tr.record_autotune(
                    self.name, knob, time.perf_counter(), old=old,
                    new=new, outcome=outcome, **evidence)
            except Exception:
                pass
        if outcome == "applied" and self._on_apply is not None:
            try:
                self._on_apply(rec)
            except Exception:
                log.exception("on_apply callback failed")
        return rec

    # -- introspection -----------------------------------------------------
    def audit(self) -> List[dict]:
        """The bounded audit ring, oldest first (the exact totals
        across wrap are in stats()["decisions"])."""
        with self._lock:
            return [dict(r) for r in self._audit]

    def knob_values(self) -> Dict[str, float]:
        """Current knob readings from the bound targets (gauges for
        the metrics plane)."""
        out: Dict[str, float] = {}
        if self.admission is not None:
            try:
                c = self.admission.counters()
                out["max_pending"] = float(c["max_pending"])
            except Exception:
                pass
        for i, el in enumerate(self.batch_elements):
            sfx = "" if len(self.batch_elements) == 1 else f"_{i}"
            try:
                out[f"batch_deadline_ms{sfx}"] = \
                    float(el.props["max_latency_ms"])
                out[f"max_batch{sfx}"] = float(el.props["max_batch"])
            except Exception:
                pass
        return out

    def stats(self) -> Dict[str, Any]:
        knobs = self.knob_values()       # targets' locks, not ours
        with self._lock:
            decisions = {k: dict(v) for k, v in self._decisions.items()}
            applied = sum(v.get("applied", 0)
                          for v in decisions.values())
            proposed = sum(v.get("proposed", 0)
                           for v in decisions.values())
            dry = sum(v.get("dry_run", 0) for v in decisions.values())
            return {
                "name": self.name,
                "dry_run": self.dry_run,
                "interval_s": self.interval_s,
                "ticks": self.ticks,
                "decisions": decisions,
                "applied_total": applied,
                "proposed_total": proposed,
                "dry_run_total": dry,
                "audit": [dict(r) for r in list(self._audit)[-32:]],
                "audit_len": len(self._audit),
                "audit_total": self._audit_total,
                "audit_dropped": self._audit_total - len(self._audit),
                "knobs": knobs,
                "hints": dict(self._last_hint),
                "slo": self.slo.to_dict(),
            }


def _hist_percentile(hist: Dict[int, int], p: float) -> int:
    """Nearest-rank percentile over a {value: count} histogram."""
    total = sum(hist.values())
    if total == 0:
        return 1
    rank = max(1, math.ceil(total * p / 100.0))
    seen = 0
    for v in sorted(hist):
        seen += hist[v]
        if seen >= rank:
            return int(v)
    return int(max(hist))
