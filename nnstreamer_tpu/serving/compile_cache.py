"""Persistent compile cache + bucket manifest for served models.

Two cooperating layers (docs/serving.md):

- **XLA executable cache**: ``jax_compilation_cache_dir`` pointed at a
  persistent directory, so the *compilations* themselves survive
  process restarts (the same mechanism bench.py uses across family
  subprocesses).
- **Bucket manifest**: XLA's cache is keyed by HLO — it can only hit
  once something asks to compile. The manifest records *what to ask
  for*: every (model name, version) → the compile-bucket set it has
  served (dyn_batch pow2 buckets + fixed shapes). On the next process
  start, ``tensor_filter`` replays the manifest at element start()
  (backend ``warm_start``), compiling the whole working set off the
  hot path — against a warm XLA disk cache those are fast loads, not
  recompiles.

Configured via the ``[serving]`` group in core/config.py (opt-in:
``compile_cache=1``; env ``NNSTREAMER_TPU_SERVING_COMPILE_CACHE=1``).
Every disk write is best-effort — the cache is an optimization, never
a gate.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

from nnstreamer_tpu.core.config import get_config
from nnstreamer_tpu.core.log import get_logger

log = get_logger("serving.cache")

_lock = threading.Lock()
_enabled: Optional[bool] = None     # memoized maybe_enable verdict
_dir: Optional[str] = None


def reset() -> None:
    """Forget the memoized enable verdict (tests re-point the config)."""
    global _enabled, _dir
    with _lock:
        _enabled = None
        _dir = None


def cache_dir() -> Optional[str]:
    return _dir if _enabled else None


def maybe_enable_compile_cache() -> bool:
    """Wire jax's persistent compilation cache per the ``[serving]``
    config group. Idempotent; returns whether the cache is active."""
    global _enabled, _dir
    with _lock:
        if _enabled is not None:
            return _enabled
        cfg = get_config()
        if not cfg.get_bool("serving", "compile_cache", False):
            _enabled = False
            return False
        d = os.path.expanduser(
            cfg.get("serving", "compile_cache_dir")
            or "~/.cache/nnstreamer_tpu/xla")
        try:
            os.makedirs(d, exist_ok=True)
            import jax

            jax.config.update("jax_compilation_cache_dir", d)
            try:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
            except Exception:
                pass             # older jax: keep its default threshold
        except Exception as e:
            log.warning("compile cache disabled: %s", e)
            _enabled = False
            return False
        _dir = d
        _enabled = True
        log.info("persistent compile cache at %s", d)
        return True


# -- bucket manifest ---------------------------------------------------------
# Layout: <cache_dir>/manifest.json =
#   {"<name>@<version>": [{"kind": "dynb"|"fix", "nb": 8,
#                          "tensors": [{"shape": [...], "dtype": "f32"}]}]}

def _manifest_path() -> Optional[str]:
    return os.path.join(_dir, "manifest.json") if _dir else None


def _bucket_to_json(bk: tuple) -> Optional[dict]:
    kind = bk[0]
    if kind in ("llmp", "llmd", "llmp_chunk"):
        # LLM serving buckets (backends/llm_exec.py): prefill prompt
        # bucket / decode batch bucket / chunked-prefill chunk bucket —
        # one pow2 int, no tensor pairs
        return {"kind": kind, "n": int(bk[1])}
    if kind == "dynb":
        nb, pairs = bk[1], bk[2:]
    elif kind == "fix":
        nb, pairs = None, bk[1:]
    else:
        return None              # flexible seq/bat buckets: not replayed
    out = {"kind": kind,
           "tensors": [{"shape": list(s), "dtype": d} for s, d in pairs]}
    if nb is not None:
        out["nb"] = nb
    return out


def _bucket_from_json(obj: dict) -> Optional[tuple]:
    try:
        if obj["kind"] in ("llmp", "llmd", "llmp_chunk"):
            return (str(obj["kind"]), int(obj["n"]))
        pairs = tuple((tuple(t["shape"]), str(t["dtype"]))
                      for t in obj["tensors"])
        if obj["kind"] == "dynb":
            return ("dynb", int(obj["nb"])) + pairs
        if obj["kind"] == "fix":
            return ("fix",) + pairs
    except (KeyError, TypeError, ValueError):
        pass
    return None


def record_bucket(name: str, version: int, bucket_key: tuple) -> None:
    """Append one served bucket to the on-disk manifest (no-op when the
    cache is disabled). Called once per new bucket per process (the
    store entry dedups), so the read-modify-write stays cheap."""
    if not maybe_enable_compile_cache():
        return
    jb = _bucket_to_json(bucket_key)
    if jb is None:
        return
    path = _manifest_path()
    key = f"{name}@{version}"
    with _lock:
        try:
            data: Dict[str, list] = {}
            if os.path.exists(path):
                with open(path) as f:
                    data = json.load(f)
            rows = data.setdefault(key, [])
            if jb not in rows:
                rows.append(jb)
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(data, f, indent=1, sort_keys=True)
                os.replace(tmp, path)
        except Exception as e:
            log.warning("manifest write failed (%s@%d): %s",
                        name, version, e)


def manifest_buckets(name: str, version: int) -> List[tuple]:
    """The bucket set a previous process served for name@version, for
    warm-start replay. Empty when the cache is off or unseen."""
    if not maybe_enable_compile_cache():
        return []
    path = _manifest_path()
    try:
        if not os.path.exists(path):
            return []
        with open(path) as f:
            data = json.load(f)
        rows = data.get(f"{name}@{version}", [])
        out = [_bucket_from_json(r) for r in rows]
        return [b for b in out if b is not None]
    except Exception as e:
        log.warning("manifest read failed (%s@%d): %s", name, version, e)
        return []
