"""Pool worker — one pipeline copy in a child process.

This is the child half of the supervised worker pool (serving/pool.py):
`worker_main` runs inside a spawned process, receives frames from the
supervisor over a multiprocessing duplex pipe, services them, and sends
results back. Everything that crosses the pipe is a small tagged tuple;
tensor payloads travel as wire-frame bytes (edge/wire.py) so the child
never needs the parent's negotiation context.

Parent -> child messages::

    ("req",  rid, payload)            one frame to service
    ("swap", phase, name, version)    two-phase model hot swap
                                      (phase: prepare | commit | abort)
    ("bind", phase, model)            two-phase slot→model rebinding
                                      (replica scaling, pool.rebind)
    ("stop",)                         graceful stop (drain then exit 0)

Child -> parent messages::

    ("ready", info)                   setup done; info carries pid and,
                                      in pipeline mode, the negotiated
                                      output spec strings
    ("hb", seq, t_mono)               heartbeat (dedicated thread, so a
                                      GIL-bound service loop still beats;
                                      only a truly wedged process stops)
    ("res", rid, payload)             one serviced frame
    ("err", rid, pickled_exc)         one frame failed (request-scoped)
    ("swap_ack", phase, ok, err)      swap phase outcome
    ("bind_ack", phase, ok, err)      bind phase outcome
    ("fatal", pickled_exc)            unrecoverable worker error; the
                                      child exits nonzero right after
    ("bye",)                          graceful-stop acknowledgement

Service modes (`WorkerSpec.kind`):

- ``echo``     — sleep `service_ms` then return the frame unchanged.
  The known-capacity worker the traffic harness and the chaos tests
  build on (capacity = 1000/service_ms rps per worker, serialized in
  the worker's main loop exactly like a GIL-bound pipeline stage).
- ``pipeline`` — parse `pipeline` (a mid-pipeline description, e.g.
  ``tensor_filter framework=xla model=store://m``) into
  ``appsrc ! <pipeline> ! tensor_sink`` and stream frames through it.
- ``multiplex`` — M `store://` models resident in one worker, each
  frame routed by its tenant class (serving/tenancy.py); cold models'
  compiled jits are LRU-evicted under a residency bound.

Chaos hooks (`crash_pts`, `hang_pts`, `crash_after_s`,
`swap_fail_version`) let tests inject deterministic worker failure
without reaching into a live process; they are inert by default.

Exceptions cross the process boundary pickled — which is why every
public error class in core/errors.py is pickle-round-trip safe (the
base class carries `__reduce__`; tests/test_faults.py pins it).
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from dataclasses import dataclass
from typing import Optional

#: pts is client-owned; requests are keyed across the pool by a
#: supervisor-assigned rid riding the buffer meta instead
RID_META = "_pool_rid"


@dataclass
class WorkerSpec:
    """Picklable description of what one worker runs (spawn-safe: no
    callables, no open handles — the child rebuilds everything)."""

    kind: str = "echo"                    # echo | pipeline | multiplex
    service_ms: float = 0.0               # echo: per-frame service time
    pipeline: str = ""                    # pipeline: mid-pipeline desc
    dims: str = "8:1"                     # accepted input dims (HELLO)
    types: str = "float32"
    hb_interval_s: float = 0.1            # heartbeat period
    # run a child-side Tracer and ship its deltas over the pipe ("tr"
    # messages on the heartbeat cadence); set automatically by a traced
    # pool. Costs the echo path a decode/encode per frame (hop stamps
    # need the meta), so it defaults off to keep the known-capacity
    # semantics exact for untraced chaos/flood runs.
    trace: bool = False
    # chaos hooks (tests / harness only; all inert by default)
    crash_pts: Optional[int] = None       # os._exit(3) on this pts
    hang_pts: Optional[int] = None        # sleep forever on this pts
    crash_after_s: Optional[float] = None  # os._exit(3) after t seconds
    swap_fail_version: Optional[int] = None  # swap prepare refuses this
    # multiplex mode (serving/tenancy.py): the worker keeps several
    # store:// models resident and routes each frame by its tenant
    # class. `tenants` is a TenantTable.to_dict() snapshot (picklable);
    # `preload` entries (name, version, ref) are registered into the
    # CHILD's model store before the service opens — spawn children
    # only inherit zoo seeds (@0), so extra versions for hot-swap must
    # travel as recipes, not objects. resident_models/resident_bytes
    # bound the LRU jit residency (0 = unbounded).
    tenants: Optional[dict] = None
    preload: tuple = ()                   # ((name, version, ref), ...)
    resident_models: int = 0
    resident_bytes: int = 0
    # same-host shared-memory lane (serving/shm.py): names of the two
    # per-spawn rings the supervisor created for this slot ("" = pipe
    # only). The child *attaches*; attach failure is not an error — it
    # acks ``shm: False`` at handshake and both sides stay on pickle.
    shm_req: str = ""                     # parent→child payload ring
    shm_res: str = ""                     # child→parent payload ring
    # chip ownership (serving/placement.ChipLeaseTable): device ordinals
    # this worker is leased. Informational to the child (it pins its
    # own placement from these); authoritative to the SUPERVISOR, which
    # fences the chips when the worker dies and re-leases them to the
    # replacement — a K-chip worker counts as K slots of capacity in
    # the scaler (tenancy.ScalingController).
    chips: tuple = ()

    def __post_init__(self):
        if self.kind not in ("echo", "pipeline", "multiplex"):
            raise ValueError(
                f"WorkerSpec.kind must be echo|pipeline|multiplex, "
                f"got {self.kind!r}")
        if self.kind == "pipeline" and not self.pipeline:
            raise ValueError("WorkerSpec(kind='pipeline') needs a "
                             "pipeline description")
        if self.kind == "multiplex" and not self.tenants:
            raise ValueError("WorkerSpec(kind='multiplex') needs a "
                             "tenants table (TenantTable.to_dict())")


def _pickle_exc(exc: BaseException) -> bytes:
    """Best-effort exception pickling: a framework error pickles whole
    (core/errors.py guarantees it); anything else degrades to a
    RuntimeError carrying the repr, never to a poisoned pipe."""
    try:
        return pickle.dumps(exc)
    except Exception:
        return pickle.dumps(RuntimeError(
            f"[unpicklable {type(exc).__name__}] {exc}"))


class _Heartbeat(threading.Thread):
    """Beats on its own thread so a busy (but alive) service loop keeps
    beating; only a wedged process — native hang, hard GIL capture —
    goes silent and trips the supervisor's hb_timeout."""

    def __init__(self, conn, send_lock, interval_s: float, tracer=None):
        super().__init__(name="pool-worker-hb", daemon=True)
        self._conn = conn
        self._lock = send_lock
        self._interval = max(0.01, interval_s)
        self._tracer = tracer
        self._stop = threading.Event()

    def run(self) -> None:
        seq = 0
        while not self._stop.wait(self._interval):
            seq += 1
            # trace deltas ride the heartbeat cadence as their own
            # pipe lane: drained event batches + monotone counter /
            # histogram deltas (runtime/tracing.py ship_delta)
            delta = self._tracer.ship_delta() \
                if self._tracer is not None and self._tracer.active \
                else None
            try:
                with self._lock:
                    self._conn.send(("hb", seq, time.monotonic()))
                    if delta is not None:
                        self._conn.send(("tr", delta))
            except (OSError, ValueError, BrokenPipeError):
                # parent gone: nothing left to serve, don't linger as
                # an orphan
                os._exit(0)

    def stop(self) -> None:
        self._stop.set()


class _EchoService:
    """Known-capacity service: sleep then echo the payload bytes
    untouched (no decode on the hot path unless a chaos hook needs the
    pts, or tracing needs the meta for hop stamps)."""

    def __init__(self, spec: WorkerSpec, tracer=None, wid: int = 0):
        from nnstreamer_tpu.runtime.tracing import NULL_TRACER

        self._spec = spec
        self._tracer = tracer or NULL_TRACER
        self._wid = wid
        self._needs_pts = (spec.crash_pts is not None
                           or spec.hang_pts is not None)
        self._needs_decode = self._needs_pts or self._tracer.active

    def ready_info(self) -> dict:
        # echo's out spec is its in spec
        return {"out_dims": self._spec.dims,
                "out_types": self._spec.types}

    def serve(self, rid: int, payload: bytes, reply) -> None:
        buf = None
        if self._needs_decode:
            from nnstreamer_tpu.edge.wire import decode_buffer

            buf, _ = decode_buffer(payload)
            if buf.pts == self._spec.crash_pts:
                os._exit(3)
            if buf.pts == self._spec.hang_pts:
                time.sleep(3600)          # wedged: supervisor's problem
        tr = self._tracer
        if tr.active and buf is not None:
            from nnstreamer_tpu.edge.wire import encode_buffer
            from nnstreamer_tpu.runtime.tracing import stamp_hop

            stamp_hop(buf.meta, "worker_recv", wid=self._wid)
            t0 = time.perf_counter()
            if self._spec.service_ms > 0:
                time.sleep(self._spec.service_ms / 1e3)
            t1 = time.perf_counter()
            tr.record_process("echo", buf, t0, t1)
            stamp_hop(buf.meta, "worker_done", wid=self._wid)
            reply(("res", rid, encode_buffer(buf)))
            return
        if self._spec.service_ms > 0:
            time.sleep(self._spec.service_ms / 1e3)
        reply(("res", rid, payload))

    def close(self) -> None:
        pass


def _resident_versions() -> dict:
    """{model name: [versions]} resident in THIS process's store —
    advertised through the pool's ready info so a mesh REGISTER ad can
    route for model locality without an extra round trip."""
    from nnstreamer_tpu.serving.store import get_store

    store = get_store()
    return {n: sorted(store.entry(n).versions) for n in store.names()}


class _PipelineService:
    """One full pipeline copy: appsrc ! <spec.pipeline> ! tensor_sink.

    Frames are pushed as they arrive (the pipeline pipelines them); a
    collector thread drains the sink and ships results, matching
    request to result by the RID_META stamp that rides buffer meta
    end-to-end."""

    def __init__(self, spec: WorkerSpec, reply, tracer=None,
                 wid: int = 0):
        import queue as _queue

        import nnstreamer_tpu as nns
        from nnstreamer_tpu.edge.wire import encode_buffer
        from nnstreamer_tpu.runtime.tracing import (
            NULL_TRACER, stamp_hop)

        self._reply = reply
        self._tracer = tracer or NULL_TRACER
        self._wid = wid
        self._outq: "_queue.Queue" = _queue.Queue()
        desc = (f"appsrc name=_pool_src dims={spec.dims} "
                f"types={spec.types} ! {spec.pipeline} ! "
                f"tensor_sink name=_pool_sink collect=false")
        pipe = nns.parse_launch(desc)
        self._src = pipe.get("_pool_src")
        sink = pipe.get("_pool_sink")
        sink.props["new_data"] = self._outq.put
        # a traced worker hands ITS tracer to the runner: the child's
        # pipeline elements record spans locally, shipped as deltas
        self.runner = nns.PipelineRunner(
            pipe, trace=self._tracer if self._tracer.active
            else False).start()
        out_spec = sink.in_specs[0] if sink.in_specs else None
        dims, types = "", ""
        if out_spec is not None and hasattr(out_spec, "to_strings"):
            dims, types, _ = out_spec.to_strings()
        self._out_info = {"out_dims": dims, "out_types": types}
        self._stop = threading.Event()

        def collect():
            while not self._stop.is_set():
                try:
                    buf = self._outq.get(timeout=0.1)
                except _queue.Empty:
                    continue
                rid = buf.meta.pop(RID_META, None)
                if rid is None:
                    continue          # not ours (defensive)
                stamp_hop(buf.meta, "worker_done", wid=wid)
                reply(("res", int(rid), encode_buffer(buf)))

        self._collector = threading.Thread(
            target=collect, name="pool-worker-collect", daemon=True)
        self._collector.start()

    def ready_info(self) -> dict:
        info = dict(self._out_info)
        info["versions"] = _resident_versions()
        return info

    def serve(self, rid: int, payload: bytes, reply) -> None:
        from nnstreamer_tpu.edge.wire import decode_buffer
        from nnstreamer_tpu.runtime.tracing import stamp_hop

        # runner death is worker-fatal, not request-scoped: the
        # supervisor restarts the whole process
        err = getattr(self.runner, "_error", None)
        if err is not None:
            raise err
        buf, _ = decode_buffer(payload)
        stamp_hop(buf.meta, "worker_recv", wid=self._wid)
        self._src.push(buf)           # RID_META already rides buf.meta

    def close(self) -> None:
        self._stop.set()
        try:
            self.runner.stop()
        except Exception:
            pass


class _MultiplexService:
    """M models, one worker: per-tenant model routing (serving/tenancy).

    Every model the TenantTable binds gets its own store-attached
    XLABackend, opened once at startup; each frame routes by the tenant
    class riding its meta (``_tenant_class`` stamped at admission, or
    the raw ``tenant`` claim when driven without an admission front).
    A `ModelResidency` LRU bounds the compiled state: after each invoke
    the served model is touched and cold models beyond the bound have
    their bucketed jits released — the next frame for an evicted model
    recompiles (counted, correct, never an error).

    Store hot swaps work unchanged: the backends track the child
    store's epoch and adopt at their next invoke boundary, so an
    ``update(name, version)`` from a committed swap flips exactly the
    swapped model — other tenants' backends (and compiled buckets) are
    untouched.
    """

    def __init__(self, spec: WorkerSpec, tracer=None, wid: int = 0):
        from nnstreamer_tpu.backends.xla import XLABackend
        from nnstreamer_tpu.runtime.tracing import NULL_TRACER
        from nnstreamer_tpu.serving.tenancy import (
            ModelResidency, TenantTable)
        from nnstreamer_tpu.tensor.info import TensorsSpec

        self._spec = spec
        self._tracer = tracer or NULL_TRACER
        self._wid = wid
        self._table = TenantTable.from_dict(spec.tenants)
        self._in_spec = TensorsSpec.from_strings(spec.dims, spec.types)
        self._residency = ModelResidency(
            max_models=spec.resident_models,
            max_bytes=spec.resident_bytes)
        self._backends: dict = {}
        self.invokes_by_model: dict = {}
        models = self._table.models()
        if not models:
            raise ValueError("multiplex worker: tenant table binds no "
                             "models")
        for name in models:
            b = XLABackend()
            b.open({"model": f"store://{name}"})
            b.set_input_info(self._in_spec)
            self._backends[name] = b
            self._residency.register(name, b)
        self._default_model = (self._table.model_of(None)
                               or models[0])

    def _route(self, meta) -> str:
        cls = None
        if isinstance(meta, dict):
            cls = meta.get("_tenant_class") or meta.get("tenant")
        model = self._table.model_of(cls) if cls is not None else None
        if model is None or model not in self._backends:
            return self._default_model
        return model

    def ready_info(self) -> dict:
        dims, types, _ = self._in_spec.to_strings()
        return {"out_dims": dims, "out_types": types,
                "versions": _resident_versions(),
                "models": sorted(self._backends)}

    def serve(self, rid: int, payload: bytes, reply) -> None:
        import numpy as np

        from nnstreamer_tpu.edge.wire import decode_buffer, encode_buffer
        from nnstreamer_tpu.runtime.tracing import stamp_hop

        buf, _ = decode_buffer(payload)
        if buf.pts == self._spec.crash_pts:
            os._exit(3)
        if buf.pts == self._spec.hang_pts:
            time.sleep(3600)
        model = self._route(buf.meta)
        backend = self._backends[model]
        if self._tracer.active:
            stamp_hop(buf.meta, "worker_recv", wid=self._wid,
                      model=model)
        t0 = time.perf_counter()
        out = backend.invoke(buf.tensors)
        t1 = time.perf_counter()
        self.invokes_by_model[model] = \
            self.invokes_by_model.get(model, 0) + 1
        self._residency.touch(model)
        res = buf.with_tensors(
            tuple(np.asarray(o) for o in out), pts=buf.pts)
        if self._tracer.active:
            self._tracer.record_process(f"mux:{model}", buf, t0, t1)
            stamp_hop(res.meta, "worker_done", wid=self._wid,
                      model=model)
        reply(("res", rid, encode_buffer(res)))

    def residency_stats(self) -> dict:
        st = self._residency.stats()
        st["invokes_by_model"] = dict(self.invokes_by_model)
        return st

    def close(self) -> None:
        for b in self._backends.values():
            try:
                b.close()
            except Exception:
                pass


def _register_preloads(preload) -> None:
    """Install the spec's (name, version, ref) recipes into THIS
    process's store: string refs register as lazy builders, so nothing
    heavyweight resolves until a swap actually commits that version."""
    from nnstreamer_tpu.serving.store import get_store

    store = get_store()
    for name, version, ref in preload:
        try:
            # pull the zoo seed (@0) first if there is one, so the
            # preloaded version lands as a LATER version and the
            # zero-downtime contract holds: registration never changes
            # what's being served — only a committed swap does
            try:
                store.entry(name)
            except Exception:
                pass                  # brand-new name: recipe is v1
            store.register(name, model=ref, version=version)
        except Exception:
            # idempotence over strictness: an already-registered
            # version (restart, double preload) is not a setup failure
            pass


def _handle_bind(service, state: dict, phase: str,
                 model) -> "tuple[bool, Optional[str]]":
    """Two-phase slot→model rebinding, child side (pool.rebind).

    Binding is primarily PARENT routing state (which slot is preferred
    for which model); the child's role is to vote in the two-phase
    broadcast so the flip is epoch-atomic, and — for a multiplex
    worker — to verify it can actually serve the model and warm it.
    Echo/pipeline workers accept any bind (routing is not theirs to
    refuse)."""
    if phase == "abort":
        state.pop("bind_staged", None)
        return True, None
    if phase == "prepare":
        if model is not None and isinstance(service, _MultiplexService):
            if model not in service._backends:
                return False, (f"worker has no backend for model "
                               f"{model!r}")
        state["bind_staged"] = model
        return True, None
    if phase == "commit":
        staged = state.pop("bind_staged", "\0missing")
        if staged == "\0missing" or staged != model:
            return False, (f"bind commit without matching prepare "
                           f"(staged={staged!r})")
        state["bound_model"] = model
        if model is not None and isinstance(service, _MultiplexService):
            service._residency.touch(model)   # pre-warm LRU position
        return True, None
    return False, f"unknown bind phase {phase!r}"


def _handle_swap(service, spec: WorkerSpec, state: dict, phase: str,
                 name: str, version) -> "tuple[bool, Optional[str]]":
    """Two-phase hot swap, child side. `prepare` stages (and for
    pipeline workers validates against the child's model store) without
    flipping; only `commit` makes the new version live — so the
    supervisor can abort every worker if any one prepare fails, and the
    pool epoch flips all-or-none (PR 5 semantics, one level up)."""
    if phase == "abort":
        state.pop("staged", None)
        return True, None
    if phase == "prepare":
        if spec.swap_fail_version is not None \
                and version == spec.swap_fail_version:
            return False, f"injected prepare failure for @{version}"
        if isinstance(service, (_PipelineService, _MultiplexService)):
            try:
                from nnstreamer_tpu.serving.store import get_store

                entry = get_store().entry(name)
                if version is not None and \
                        int(version) not in entry.versions:
                    return False, (f"store://{name} has no version "
                                   f"@{version} in this worker")
            except Exception as e:
                return False, str(e)
        state["staged"] = (name, version)
        return True, None
    if phase == "commit":
        staged = state.pop("staged", None)
        if staged != (name, version):
            return False, (f"commit without matching prepare "
                           f"(staged={staged!r})")
        if isinstance(service, (_PipelineService, _MultiplexService)):
            try:
                from nnstreamer_tpu.serving.store import get_store

                get_store().update(name, version)
            except Exception as e:
                return False, str(e)
        state["version"] = (name, version)
        return True, None
    return False, f"unknown swap phase {phase!r}"


def worker_main(conn, spec: WorkerSpec, wid: int = 0) -> None:
    """Child entry point (multiprocessing spawn target).

    The loop is deliberately sequential per worker — concurrency comes
    from the POOL running N of these processes, which is the whole
    point: one wedged/GIL-bound worker never slows its siblings."""
    send_lock = threading.Lock()

    # same-host shm lane: attach the supervisor's rings, or silently
    # stay on pickle — the handshake ack below tells the parent which
    shm_req_ring = shm_res_ring = None
    if spec.shm_req and spec.shm_res:
        try:
            from nnstreamer_tpu.serving.shm import ShmRing

            shm_req_ring = ShmRing.attach(spec.shm_req)
            shm_res_ring = ShmRing.attach(spec.shm_res)
        except Exception:
            if shm_req_ring is not None:
                shm_req_ring.close()
            shm_req_ring = shm_res_ring = None

    def reply(msg) -> None:
        try:
            with send_lock:
                # result payloads ride the res ring when they fit; the
                # ring write lands BEFORE the control send (and both
                # under send_lock), so ring order == pipe order and the
                # parent's reader never guesses
                if shm_res_ring is not None and msg[0] == "res":
                    seq = shm_res_ring.try_write(msg[2])
                    if seq is not None:
                        conn.send(("ress", msg[1], len(msg[2]), seq))
                        return
                conn.send(msg)
        except (OSError, ValueError, BrokenPipeError):
            os._exit(0)               # parent gone — never orphan

    tracer = None
    if spec.trace:
        from nnstreamer_tpu.runtime.tracing import Tracer

        tracer = Tracer()
        tracer.enable_shipping()

    hb = _Heartbeat(conn, send_lock, spec.hb_interval_s, tracer)
    hb.start()
    if spec.crash_after_s is not None:
        # chaos: die abruptly after t seconds (circuit-breaker tests);
        # daemon so a worker that drains cleanly first isn't held alive
        # until the fuse fires
        crash = threading.Timer(spec.crash_after_s, lambda: os._exit(3))
        crash.daemon = True
        crash.start()

    service = None
    try:
        if spec.preload:
            _register_preloads(spec.preload)
        if spec.kind == "pipeline":
            service = _PipelineService(spec, reply, tracer, wid)
        elif spec.kind == "multiplex":
            service = _MultiplexService(spec, tracer, wid)
        else:
            service = _EchoService(spec, tracer, wid)
    except BaseException as e:
        reply(("fatal", _pickle_exc(e)))
        os._exit(4)

    # t_perf lets the parent sample this worker's monotonic-clock
    # offset at handshake (pool.py "ready" handler) so shipped trace
    # timestamps align on one pool-wide timeline
    reply(("ready", dict(service.ready_info(), pid=os.getpid(),
                         wid=wid, t_perf=time.perf_counter(),
                         shm=shm_res_ring is not None)))
    swap_state: dict = {}
    try:
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                os._exit(1)           # supervisor died — exit, no orphan
            tag = msg[0]
            if tag == "req" or tag == "reqs":
                if tag == "reqs":
                    # payload rode the req ring; the control message
                    # promised (nbytes, seq) — any mismatch is a
                    # request-scoped error, recovered by redelivery
                    _, rid, nbytes, seq = msg
                    try:
                        payload = shm_req_ring.read_record(nbytes, seq)
                    except BaseException as e:
                        reply(("err", rid, _pickle_exc(e)))
                        continue
                else:
                    _, rid, payload = msg
                try:
                    service.serve(rid, payload, reply)
                except BaseException as e:
                    reply(("err", rid, _pickle_exc(e)))
            elif tag == "swap":
                _, phase, name, version = msg
                ok, err = _handle_swap(service, spec, swap_state,
                                       phase, name, version)
                reply(("swap_ack", phase, ok, err))
            elif tag == "bind":
                _, phase, model = msg
                ok, err = _handle_bind(service, swap_state, phase, model)
                reply(("bind_ack", phase, ok, err))
            elif tag == "stop":
                break
    finally:
        hb.stop()
        if service is not None:
            service.close()
        # close (never unlink — the creator owns the name) the shm lane
        for ring in (shm_req_ring, shm_res_ring):
            if ring is not None:
                ring.close()
    if tracer is not None:
        # final drain: a graceful stop must not strand the tail of the
        # trace in the child (the heartbeat cadence may not have fired
        # since the last frame)
        delta = tracer.ship_delta()
        if delta is not None:
            reply(("tr", delta))
    reply(("bye",))
    try:
        conn.close()
    except OSError:
        pass
