"""Process-wide versioned model registry + epoch-based hot swap.

The reference treats model updates as a per-filter affair (is-updatable
reload, tensor_filter_common.c:2400 reloadModel): each filter owns its
model and a reload races the invoke path. Here the registry is the unit
of truth — a ``store://`` ref names a *served model*, versions are
immutable once registered, and an update is a controlled swap:

1. ``update(name, version)`` resolves and builds the incoming version
   off the hot path;
2. every attached backend pre-warms it — compiling the same dyn_batch /
   fixed-shape buckets the outgoing version has served, through the
   same bucketed ``_bucket_jit`` machinery (backends/xla.py), and
   verifying the new version accepts them *before* anything flips;
3. the entry's ``(current, epoch)`` state flips in one atomic tuple
   assignment;
4. backends adopt at their next invoke boundary (each element has ONE
   worker thread, so an invoke either sees the old snapshot or the new
   one — never a torn version), installing the staged compilations and
   retiring the outgoing version's buckets.

Canary splits ride the same routing point: ``store://name@2:0.05``
sends a deterministic, seeded 5% of invokes to version 2 while the
remainder tracks ``current``; per-version invoke/error/latency counters
(`stats_dict`) make the comparison readable straight from
``tensor_filter`` stats and the tracer report.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.runtime.tracing import percentile

log = get_logger("serving.store")

VersionSpec = Union[int, str, None]


@dataclass(frozen=True)
class StoreRef:
    """Parsed ``store://`` model reference.

    ``version`` None means *track current* (hot-swappable); an int or
    alias pins the backend to that version (immune to swaps).
    ``canary_version``/``canary_ratio`` describe a weighted split
    against the tracked current version.
    """

    name: str
    version: VersionSpec = None
    canary_version: VersionSpec = None
    canary_ratio: float = 0.0


def parse_store_ref(ref: str) -> StoreRef:
    """``store://name[@version[:ratio]]`` → :class:`StoreRef`.

    - ``store://det``            track current (swaps apply)
    - ``store://det@latest``     same as above
    - ``store://det@3``          pinned to version 3
    - ``store://det@prod``       pinned via alias
    - ``store://det@2:0.05``     canary: 5% of invokes to version 2,
      the rest track current
    """
    if not isinstance(ref, str) or not ref.startswith("store://"):
        raise BackendError(f"not a store reference: {ref!r}")
    body = ref[len("store://"):]
    name, _, vpart = body.partition("@")
    if not name:
        raise BackendError(f"store reference {ref!r} has no model name")
    if not vpart:
        return StoreRef(name=name)
    vspec, _, ratio = vpart.partition(":")
    version: VersionSpec = vspec
    if vspec.lstrip("-").isdigit():
        version = int(vspec)
    elif vspec == "latest" or vspec == "":
        version = None
    if not ratio:
        return StoreRef(name=name, version=version)
    try:
        r = float(ratio)
    except ValueError:
        raise BackendError(
            f"bad canary ratio {ratio!r} in {ref!r}; expected a float "
            f"in (0, 1) like store://{name}@2:0.05") from None
    if not (0.0 < r < 1.0):
        raise BackendError(
            f"canary ratio {r} in {ref!r} out of range; must be in "
            f"(0, 1) exclusive (1.0 would be a full swap — use "
            f"ModelStore.update instead)")
    if version is None:
        raise BackendError(
            f"canary reference {ref!r} needs an explicit version to "
            f"canary (store://{name}@<version>:{ratio})")
    return StoreRef(name=name, canary_version=version, canary_ratio=r)


class _VersionStats:
    """Per-version serving counters (invokes/errors + proctime
    reservoir → p95). Appends come from element worker threads; the
    tiny lock keeps the counts exact for canary comparisons."""

    __slots__ = ("invokes", "errors", "_times", "_lock")

    def __init__(self):
        self.invokes = 0
        self.errors = 0
        self._times: deque = deque(maxlen=512)
        self._lock = threading.Lock()

    def record(self, dt_s: float, error: bool) -> None:
        with self._lock:
            self.invokes += 1
            if error:
                self.errors += 1
            else:
                self._times.append(dt_s)

    def as_dict(self) -> dict:
        with self._lock:
            vals = sorted(self._times)
            return {
                "invokes": self.invokes,
                "errors": self.errors,
                "p95_us": round(1e6 * percentile(vals, 95), 1),
            }


@dataclass
class _Version:
    """One immutable registered version: a bundle, or a zero-arg
    builder deferred until first resolution."""

    version: int
    source: str = ""
    builder: Optional[Callable[[], Any]] = None
    _bundle: Any = None
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def bundle(self):
        if self._bundle is not None:
            return self._bundle
        with self._lock:
            if self._bundle is None:
                self._bundle = _as_bundle(self.builder(), self.source)
            return self._bundle


def _as_bundle(model, source: str):
    """Accept a ModelBundle directly; resolve strings/callables through
    the XLA backend's model resolution (zoo://, file paths,
    pkg.module:attr, bare jax callables)."""
    from nnstreamer_tpu.backends.xla import ModelBundle, XLABackend

    if isinstance(model, ModelBundle):
        return model
    try:
        return XLABackend()._resolve(model)
    except BackendError as e:
        raise BackendError(
            f"model store could not resolve {source or model!r}: {e}"
        ) from e


class _Entry:
    """One served model name: its versions, aliases, the atomic
    ``(current_version, epoch)`` state, attached backend handles, and
    per-version stats/bucket records."""

    def __init__(self, name: str):
        self.name = name
        self.versions: Dict[int, _Version] = {}
        self.aliases: Dict[str, int] = {}
        #: single-tuple assignment = the atomic swap point: readers
        #: (backend invoke paths) load it once and see a consistent pair
        self._state: Tuple[Optional[int], int] = (None, 0)
        self.lock = threading.RLock()          # registration/swap serial
        self._handles: List[weakref.ref] = []
        self._stats: Dict[int, _VersionStats] = {}
        self._buckets: Dict[int, set] = {}
        self.swap_log: List[dict] = []

    # -- state -------------------------------------------------------------
    @property
    def state(self) -> Tuple[Optional[int], int]:
        return self._state

    @property
    def current(self) -> Optional[int]:
        return self._state[0]

    @property
    def epoch(self) -> int:
        return self._state[1]

    # -- versions ----------------------------------------------------------
    def add_version(self, version: int, *, bundle=None, builder=None,
                    source: str = "") -> None:
        with self.lock:
            existing = self.versions.get(version)
            if existing is not None:
                raise BackendError(
                    f"model store already holds {self.name!r}@{version} "
                    f"(registered from {existing.source or 'a bundle'}); "
                    f"versions are immutable — register the new weights "
                    f"under a new version and ModelStore.update() to it")
            self.versions[version] = _Version(
                version=version, source=source,
                builder=builder, _bundle=bundle)
            if self._state[0] is None:
                self._state = (version, self._state[1])

    def resolve_version(self, spec: VersionSpec) -> int:
        cur, _ = self._state
        if spec is None or spec == "latest":
            if cur is None:
                raise BackendError(
                    f"model {self.name!r} has no versions registered")
            return cur
        if isinstance(spec, str) and spec.lstrip("-").isdigit():
            spec = int(spec)
        if isinstance(spec, str):
            v = self.aliases.get(spec)
            if v is None:
                raise BackendError(
                    f"model {self.name!r} has no version alias {spec!r}; "
                    f"aliases: {sorted(self.aliases) or '(none)'}, "
                    f"versions: {sorted(self.versions)}")
            return v
        if spec not in self.versions:
            raise BackendError(
                f"model {self.name!r} has no version {spec}; registered "
                f"versions: {sorted(self.versions)}")
        return int(spec)

    def bundle(self, version: int):
        return self.versions[version].bundle()

    # -- handles (attached backends) ---------------------------------------
    def attach(self, handle) -> None:
        with self.lock:
            self._handles = [r for r in self._handles if r() is not None]
            self._handles.append(weakref.ref(handle))

    def detach(self, handle) -> None:
        with self.lock:
            self._handles = [r for r in self._handles
                             if r() is not None and r() is not handle]

    def live_handles(self) -> list:
        with self.lock:
            out = [r() for r in self._handles]
        return [h for h in out if h is not None]

    # -- per-version serving stats -----------------------------------------
    def record(self, version: int, dt_s: float, error: bool = False) -> None:
        s = self._stats.get(version)
        if s is None:
            s = self._stats.setdefault(version, _VersionStats())
        s.record(dt_s, error)

    def stats_dict(self) -> Dict[int, dict]:
        return {v: s.as_dict() for v, s in sorted(self._stats.items())}

    def note_bucket(self, version: int, bucket_key: tuple) -> None:
        """Record a served compile bucket (first time only) so swaps can
        pre-warm it and the persistent manifest can replay it on the
        next process start."""
        s = self._buckets.setdefault(version, set())
        if bucket_key in s:
            return
        s.add(bucket_key)
        from nnstreamer_tpu.serving.compile_cache import record_bucket

        record_bucket(self.name, version, bucket_key)

    def buckets(self, version: int) -> list:
        return sorted(self._buckets.get(version, ()))


class ModelStore:
    """The process-wide versioned registry ``store://`` refs resolve
    through. One instance per process (``get_store()``)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._entries: Dict[str, _Entry] = {}

    # -- registration ------------------------------------------------------
    def register(self, name: str, model: Any = None, *,
                 builder: Optional[Callable[[], Any]] = None,
                 version: Optional[int] = None,
                 source: str = "") -> int:
        """Register `model` (ModelBundle | str ref | jax callable) — or
        a lazy zero-arg `builder` — as a new immutable version of
        `name`. Auto-versions from 1 upward (version 0 is the zoo
        seed). The first registered version becomes ``current``; later
        ones serve only after an explicit :meth:`update` (zero-downtime
        contract: registration never changes what's being served)."""
        if (model is None) == (builder is None):
            raise BackendError(
                "ModelStore.register needs exactly one of model= or "
                "builder=")
        with self._lock:
            e = self._entries.setdefault(name, _Entry(name))
            with e.lock:
                if version is None:
                    version = max(e.versions, default=0) + 1
                src = source or (model if isinstance(model, str)
                                 else f"{name}@{version}")
                if builder is not None:
                    e.add_version(version, builder=builder, source=src)
                elif isinstance(model, str):
                    ref = model
                    e.add_version(version, source=src,
                                  builder=lambda: ref)
                else:
                    e.add_version(version,
                                  bundle=_as_bundle(model, src),
                                  source=src)
        log.info("registered %s@%d (%s)", name, version, src)
        return version

    def seed_zoo(self, name: str, zoo_builder: Callable) -> None:
        """Seed a zoo builtin as version ``@0`` (idempotent — reseeding
        after reset_store() is a no-op when @0 already exists)."""
        with self._lock:
            e = self._entries.setdefault(name, _Entry(name))
            with e.lock:
                if 0 in e.versions:
                    return
                e.add_version(0, builder=lambda: zoo_builder(),
                              source=f"zoo://{name}")

    def alias(self, name: str, alias: str, version: VersionSpec) -> None:
        e = self.entry(name)
        with e.lock:
            e.aliases[alias] = e.resolve_version(version)

    # -- lookup ------------------------------------------------------------
    def entry(self, name: str) -> _Entry:
        """The entry for `name`, pulling a zoo seed on miss so
        ``store://<zoo name>`` works without prior registration."""
        with self._lock:
            e = self._entries.get(name)
        if e is not None and e.versions:
            return e
        from nnstreamer_tpu.models import zoo

        zoo._load_builtins()
        b = zoo._builders.get(name)
        if b is not None:
            self.seed_zoo(name, b)
            with self._lock:
                return self._entries[name]
        raise BackendError(
            f"model store has no model named {name!r}; registered: "
            f"{self.names() or '(none)'} (zoo builtins seed "
            f"automatically as @0)")

    def names(self) -> List[str]:
        with self._lock:
            return sorted(n for n, e in self._entries.items() if e.versions)

    def describe(self, name: str) -> dict:
        e = self.entry(name)
        cur, epoch = e.state
        return {
            "name": name,
            "current": cur,
            "epoch": epoch,
            "versions": {
                v: {"source": ver.source,
                    "built": ver._bundle is not None,
                    "buckets": len(e.buckets(v))}
                for v, ver in sorted(e.versions.items())},
            "aliases": dict(e.aliases),
            "handles": len(e.live_handles()),
            "stats": e.stats_dict(),
            "swaps": list(e.swap_log),
        }

    # -- the swap controller ----------------------------------------------
    def update(self, name: str, version: VersionSpec = None, *,
               prewarm: bool = True,
               wait_s: Optional[float] = None) -> dict:
        """Hot-swap `name` to `version` (default: highest registered).

        Pre-warms the incoming version on every attached backend
        (compiling the bucket set the outgoing version served — a
        version that rejects those shapes aborts the swap here, before
        anything flips), then flips ``(current, epoch)`` atomically.
        With `wait_s`, blocks until every tracking backend has adopted
        the new epoch (the swap barrier) or the deadline passes —
        adoption happens at invoke boundaries, so the barrier only
        completes while traffic flows.
        """
        e = self.entry(name)
        with e.lock:
            if version is None:
                target = max(e.versions)
            else:
                target = e.resolve_version(version)
            old, epoch = e.state
            bundle = e.bundle(target)        # build off the hot path
            handles = e.live_handles()
            warmed = 0
            if prewarm and target != old:
                for h in handles:
                    warmed += int(h.prewarm_version(target, bundle))
            new_epoch = epoch + 1
            e._state = (target, new_epoch)   # THE flip
            report = {
                "name": name, "from_version": old, "to_version": target,
                "epoch": new_epoch, "handles": len(handles),
                "prewarm": bool(prewarm), "prewarmed_buckets": warmed,
                "ts": time.time(),
            }
            e.swap_log.append(report)
        if wait_s:
            deadline = time.monotonic() + float(wait_s)

            def lagging():
                return [h for h in e.live_handles()
                        if getattr(h, "tracks_store_epoch", False)
                        and getattr(h, "adopted_epoch", new_epoch)
                        < new_epoch]

            while lagging() and time.monotonic() < deadline:
                time.sleep(0.002)
            report["barrier_ok"] = not lagging()
        log.info("swap %s: @%s → @%s epoch=%d prewarmed=%d handles=%d",
                 name, old, target, new_epoch, warmed, len(handles))
        return report


_store: Optional[ModelStore] = None
_store_lock = threading.Lock()


def get_store() -> ModelStore:
    global _store
    with _store_lock:
        if _store is None:
            _store = ModelStore()
        return _store


def reset_store() -> ModelStore:
    """Replace the process store (tests). Zoo builtins re-seed lazily on
    the next ``store://`` resolution."""
    global _store
    with _store_lock:
        _store = ModelStore()
        return _store
