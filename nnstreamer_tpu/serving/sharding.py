"""Sharded serving: one mesh-sharded model across N chips (`shards=N`).

dp replicas (serving/placement.py) scale *traffic* — N chips, N whole
copies of the model. This subsystem scales the *model*: `shards=N`
opens ONE backend whose weights (and, for the LLM path, paged KV pool)
are partitioned across an N-chip `tp` mesh via `shard_map`, so a model
whose weights + KV exceed one chip's HBM serves from the group's
combined memory. Three layers:

**Canonical blocking — the bit-parity mechanism.** Every sharded
weight is split into a FIXED number of blocks (``FIXED_BLOCKS = 8``,
the largest supported group) along its megatron axis — wq/wk/wv and
the SwiGLU gate/up column-wise per head/feature block, wo/wd row-wise
per block, the LM head column-wise per vocab block. A group of N chips
holds 8/N contiguous blocks each; the compute graph is a loop over
*blocks*, never over *shards*: per-block matmuls have N-independent
shapes, row-parallel partial sums are `all_gather`\\ ed into the fixed
(8, …) block order and reduced by a fixed-order chain of adds instead
of a `psum` (whose reduction order would depend on N). Numerics are
therefore a function of the block count — a constant — not the shard
count, which is what makes ``shards=N`` outputs bit-identical to
``shards=1`` (the acceptance gate bench/tests check with
`np.array_equal`, not allclose).

**Generic dense path** (`ShardedBackend`): any `ModelBundle`-style
``fn(params, *inputs)`` serves sharded by storing its params through
`parallel/mesh.py`'s `shard_params` (megatron column/row rules,
`_clip_spec` replicating what doesn't divide) and reconstructing each
sharded leaf with a tiled `all_gather` inside the `shard_map` body
before running the unmodified fn — weight *storage* is partitioned
(the HBM win), the math is the original fn on bit-identical gathered
weights, so outputs are bit-identical to the unsharded backend for ANY
model. The LLM path above is the compute-partitioned specialization
for the transformer family.

**Placement composition** (`ShardedReplicaSet`): ``devices=M
shards=N`` stands up M/N shard *groups*, each group one logical
replica in the ReplicaSet routing/conservation machinery. Each group
leases its N chips from a `ChipLeaseTable` under one owner; fencing
ANY member chip fences the whole group (an SPMD program cannot run on
N-1 chips), the group's queued work re-routes to surviving groups via
the ReplicaSet reoffer path, and the conservation ledger
offered == admitted + Σrejected / admitted == replied + … stays exact.
Store hot swap generalizes unchanged: the group's one backend is one
store handle, its pre-warm compiles the N-chip SPMD executable — all
shards warm in one all-or-none step before the entry's single epoch
flip.

Long-context prefill can route through `parallel/ring_attention.py`
(`ring_prefill_min` tokens threshold): the sequence axis shards over
the same chips re-axed as ``sp`` and K/V blocks rotate by `ppermute`.
Ring attention's online softmax reassociates by design, so that path
is equivalent-math (tested allclose), not bit-exact — the parity gate
always runs the blocked path.

This module and `parallel/` are the only places allowed to construct
`shard_map` / `NamedSharding` / `PartitionSpec` (nnlint NNL012) —
sharding decisions cannot leak into random call sites.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.serving.placement import (
    ChipLeaseTable, ReplicaSet, visible_devices)

log = get_logger("serving.sharding")

#: canonical block count: numerics depend on this constant, never on
#: the shard count, so any N dividing it serves bit-identical outputs
FIXED_BLOCKS = 8

#: shard counts the blocked layout supports (divisors of FIXED_BLOCKS)
SUPPORTED_SHARDS = (1, 2, 4, 8)


def _tp_mesh(devices):
    """A 1-axis ("tp",) mesh over exactly these devices."""
    from jax.sharding import Mesh

    return Mesh(np.array(list(devices)), ("tp",))


def _sp_mesh(devices):
    """The same chips re-axed as ("sp",) for ring-attention prefill."""
    from jax.sharding import Mesh

    return Mesh(np.array(list(devices)), ("sp",))


def shard_devices(indices: Sequence[int]) -> list:
    """Device objects for a shard group's chip ordinals (routes through
    the placement subsystem's blessed enumeration, NNL009)."""
    devs = visible_devices()
    for i in indices:
        if not 0 <= int(i) < len(devs):
            raise BackendError(
                f"shard group wants device {i} but only {len(devs)} "
                f"visible; run under "
                f"XLA_FLAGS=--xla_force_host_platform_device_count=N")
    return [devs[int(i)] for i in indices]


def validate_shards(n: int) -> int:
    n = int(n)
    if n not in SUPPORTED_SHARDS:
        raise BackendError(
            f"shards={n}: supported counts are {SUPPORTED_SHARDS} "
            f"(divisors of the canonical block count {FIXED_BLOCKS})")
    return n


# ---------------------------------------------------------------------------
# Generic dense path: sharded weight storage, gather-on-use compute
# ---------------------------------------------------------------------------

def dense_shard_rules():
    """Megatron column/row rules for generic dense params, layered over
    `parallel/mesh.default_param_rules` (conv patterns) with 2-D matmul
    weights column-split (`w1`-style names shard the output axis, `w2`/
    `wo`/`wd` the input axis). `_clip_spec` replicates anything the
    mesh doesn't divide — sharding never changes which model serves."""
    from jax.sharding import PartitionSpec as P

    from nnstreamer_tpu.parallel.mesh import default_param_rules

    return (
        ("w1", P(None, "tp")),
        ("wi", P(None, "tp")),
        ("wqkv", P(None, "tp")),
        ("w2", P("tp", None)),
        ("wo", P("tp", None)),
        ("wd", P("tp", None)),
    ) + tuple(default_param_rules())


def _gather_spec(x, spec):
    """all_gather a local leaf back to its global value, tiled along the
    (single) sharded axis; replicated leaves pass through."""
    import jax

    axes = [i for i, a in enumerate(spec) if a is not None]
    if not axes:
        return x
    return jax.lax.all_gather(x, "tp", axis=axes[0], tiled=True)


class ShardedBackend:
    """One model served by one N-chip SPMD program (the dense path).

    Holds params sharded across the group's mesh (`shard_params` +
    megatron rules); each invoke runs a `shard_map` whose body gathers
    the sharded leaves and applies the *unmodified* model fn — outputs
    are bit-identical to the single-chip backend by construction, and
    each chip stores only its 1/N slice of the split weights.

    Store integration mirrors the XLA backend's handle protocol:
    `prewarm_version` compiles the incoming version's N-chip executable
    for every served input signature BEFORE the store's epoch flip (one
    compile covers all shards — the all-or-none pre-warm is inherent to
    SPMD), `maybe_adopt` flips to the prepared version at the next
    invoke, and a flip after pre-warm costs zero recompiles.
    """

    def __init__(self, model, device_indices: Sequence[int], *,
                 name: str = "sharded"):
        self.name = name
        self.device_indices = tuple(int(i) for i in device_indices)
        self.shards = validate_shards(len(self.device_indices))
        self.mesh = _tp_mesh(shard_devices(self.device_indices))
        self.compile_count = 0
        self.invokes = 0
        self.invoke_failures = 0
        self.adopted_epoch = -1
        self.swap_count = 0
        self._lock = threading.Lock()
        #: (version, shape-sig…) → jitted N-chip executable
        self._jits: Dict[tuple, Any] = {}
        #: version → {placed, specs, fn, host_pre}
        self._vers: Dict[Any, dict] = {}
        self._entry = None
        self._pinned = None
        self._version: Any = None
        self._bind(model)

    # -- model binding ------------------------------------------------------
    def _bind(self, model) -> None:
        if isinstance(model, str) and model.startswith("store://"):
            from nnstreamer_tpu.serving.store import (
                get_store, parse_store_ref)

            ref = parse_store_ref(model)
            self._entry = get_store().entry(ref.name)
            if ref.version is not None:
                self._pinned = self._entry.resolve_version(ref.version)
                self._version = self._pinned
            else:
                cur, epoch = self._entry.state
                self._version, self.adopted_epoch = cur, epoch
            if self._version is None:
                raise BackendError(
                    f"sharded backend: store model {ref.name!r} has no "
                    f"versions registered")
            self._vers[self._version] = self._place(
                self._entry.bundle(self._version))
            self._entry.attach(self)
            return
        # anything else (zoo://, ModelBundle, callables, file paths)
        # resolves through the XLA backend's blessed model resolution
        from nnstreamer_tpu.backends.xla import XLABackend

        self._version = None
        self._vers[None] = self._place(XLABackend()._resolve(model))

    def _place(self, bundle) -> dict:
        """Shard a version's params across the group mesh."""
        from nnstreamer_tpu.parallel.mesh import param_specs, shard_params

        rules = dense_shard_rules()
        params = bundle.params
        return {
            "placed": shard_params(params, self.mesh, rules),
            "specs": param_specs(params, self.mesh, rules),
            "fn": bundle.fn,
            "host_pre": getattr(bundle, "host_pre", None),
        }

    @property
    def tracks_store_epoch(self) -> bool:
        return self._entry is not None and self._pinned is None

    # -- store handle protocol ---------------------------------------------
    def maybe_adopt(self) -> None:
        if not self.tracks_store_epoch:
            return
        cur, epoch = self._entry.state
        if epoch == self.adopted_epoch:
            return
        with self._lock:
            if cur not in self._vers:        # flip without pre-warm
                self._vers[cur] = self._place(self._entry.bundle(cur))
            for v in [v for v in self._vers
                      if v not in (cur, self._pinned)]:
                del self._vers[v]
            for k in [k for k in self._jits
                      if k[0] not in (cur, self._pinned)]:
                del self._jits[k]
            self._version, self.adopted_epoch = cur, epoch
            self.swap_count += 1
        log.info("sharded %s adopted %s@%s epoch=%d", self.name,
                 self._entry.name, cur, epoch)

    def prewarm_version(self, version, bundle) -> int:
        """Swap-controller hook: shard the incoming version's params and
        compile its N-chip executable for every input signature this
        group has served — one SPMD compile warms every shard, so the
        store's epoch flip is all-or-none across the whole group by
        construction (any failure raises here, before the flip)."""
        with self._lock:
            self._vers[version] = self._place(bundle)
            served = sorted({k[1:] for k in self._jits})
        compiled = 0
        for sig in served:
            _, fresh = self._get_jit(sig, version)
            if fresh:
                # a real dummy invocation populates the dispatch cache
                # so the first post-flip invoke is a hit, not a compile
                dummy = tuple(np.zeros(s, d) for s, d in sig)
                self._run(dummy, version)
                compiled += 1
        return compiled

    # -- execution ----------------------------------------------------------
    def _sig(self, inputs: tuple) -> tuple:
        return tuple((tuple(np.shape(a)), np.asarray(a).dtype.str)
                     for a in inputs)

    def _get_jit(self, sig: tuple, version) -> Tuple[Any, bool]:
        import jax
        from jax.sharding import PartitionSpec as P

        from nnstreamer_tpu.parallel._compat import shard_map

        key = (version,) + tuple(sig)
        with self._lock:
            jitted = self._jits.get(key)
        if jitted is not None:
            return jitted, False
        ver = self._vers[version]
        specs, fn = ver["specs"], ver["fn"]
        narg = len(sig)

        def body(params, *inputs):
            full = jax.tree_util.tree_map(_gather_spec, params, specs)
            out = fn(full, *inputs)
            return tuple(out) if isinstance(out, (tuple, list)) else (out,)

        smapped = shard_map(
            body, mesh=self.mesh,
            in_specs=(specs,) + (P(),) * narg,
            out_specs=P(), check_vma=False)
        jitted = jax.jit(smapped)
        with self._lock:
            self._jits[key] = jitted
            self.compile_count += 1
        return jitted, True

    def _run(self, inputs: tuple, version):
        # inputs here are post-host_pre: sigs (and prewarm dummies built
        # from them) always describe what the device fn actually sees
        jitted, _ = self._get_jit(self._sig(inputs), version)
        return jitted(self._vers[version]["placed"], *inputs)

    def invoke(self, inputs: tuple) -> tuple:
        self.maybe_adopt()
        try:
            pre = self._vers[self._version]["host_pre"]
            if pre is not None:
                inputs = pre(tuple(inputs))
            out = self._run(tuple(inputs), self._version)
        except BackendError:
            self.invoke_failures += 1
            raise
        self.invokes += 1
        return tuple(np.asarray(o) for o in out)

    def invoke_batched(self, inputs: tuple, n: int, keepdims) -> tuple:
        # the group serves the stacked batch as one SPMD invocation —
        # batching semantics (stack axis, keepdims) are the caller's
        return self.invoke(inputs)

    # -- lifecycle ----------------------------------------------------------
    def warm_start(self) -> None:
        return None

    def close(self) -> None:
        if self._entry is not None:
            try:
                self._entry.detach(self)
            except Exception:
                pass
        with self._lock:
            self._jits.clear()
            self._vers.clear()

    def stats(self) -> dict:
        return {
            "devices": list(self.device_indices),
            "shards": self.shards,
            "invokes": self.invokes,
            "compile_count": self.compile_count,
            "adopted_epoch": self.adopted_epoch,
            "swap_count": self.swap_count,
        }


# ---------------------------------------------------------------------------
# Blocked transformer math (the paged-LLM TP path)
# ---------------------------------------------------------------------------

def blocked_transformer_params(params, *, n_heads: int):
    """Re-pack transformer params (models/transformer.init_params
    layout) into the canonical blocked layout.

    Per block b of FIXED_BLOCKS: wq/wk/wv hold head-block b's
    projection columns, wg/wu the SwiGLU gate/up feature block, wo/wd
    the matching row block, head the vocab column block. Every blocked
    array carries the block axis leading — `(8, …)` — which is the
    axis `shard_llm_params` puts on the ``tp`` mesh axis. Norm vectors
    and the embedding stay whole (replicated).
    """
    import jax.numpy as jnp

    B = FIXED_BLOCKS
    d = int(params["embed"].shape[1])
    vocab = int(params["head"].shape[1])
    hd = d // n_heads
    kv_dim = (int(params["blocks"][0]["wqkv"].shape[1]) - d) // 2
    n_kv = kv_dim // hd
    d_ff = int(params["blocks"][0]["wd"].shape[0])
    for nm, v in (("n_heads", n_heads), ("n_kv_heads", n_kv),
                  ("d_ff", d_ff), ("vocab", vocab)):
        if v % B:
            raise BackendError(
                f"shards=N needs {nm}={v} divisible by the canonical "
                f"block count {B} (models/transformer.init_params "
                f"geometry)")

    def cols(w):
        # (d, out) → (B, d, out/B) column blocks
        return jnp.asarray(w).reshape(w.shape[0], B, -1).transpose(1, 0, 2)

    def rows(w):
        # (in, d) → (B, in/B, d) row blocks
        return jnp.asarray(w).reshape(B, -1, w.shape[1])

    if "wqkv_scale" in params["blocks"][0]:
        raise BackendError(
            "sharded serving is float-only: W8A8-quantized store "
            "versions cannot re-block (per-column scales would split); "
            "serve quantized models unsharded")
    blocks = []
    for blk in params["blocks"]:
        wqkv = jnp.asarray(blk["wqkv"])
        wq, wk, wv = (wqkv[:, :d], wqkv[:, d:d + kv_dim],
                      wqkv[:, d + kv_dim:])
        wi = jnp.asarray(blk["wi"])
        wg, wu = wi[:, :d_ff], wi[:, d_ff:]
        blocks.append({
            "ln1": jnp.asarray(blk["ln1"]),
            "wq": cols(wq), "wk": cols(wk), "wv": cols(wv),
            "wo": rows(blk["wo"]),
            "ln2": jnp.asarray(blk["ln2"]),
            "wg": cols(wg), "wu": cols(wu),
            "wd": rows(blk["wd"]),
        })
    return {
        "embed": jnp.asarray(params["embed"]),
        "blocks": blocks,
        "ln_f": jnp.asarray(params["ln_f"]),
        "head": cols(jnp.asarray(params["head"])),
    }


def llm_shard_rules():
    """Blocked-layout rules: the leading block axis shards over tp."""
    from jax.sharding import PartitionSpec as P

    blocked = P("tp", None, None)
    return (
        ("wq", blocked), ("wk", blocked), ("wv", blocked),
        ("wg", blocked), ("wu", blocked),
        ("wo", blocked), ("wd", blocked),
        ("head", blocked),
        ("", P()),
    )


def shard_llm_params(params, mesh, *, n_heads: int):
    """Blocked re-pack + placement: returns (device pytree, spec
    pytree) for use as shard_map in_specs / jit arguments."""
    from nnstreamer_tpu.parallel.mesh import param_specs, shard_params

    blocked = blocked_transformer_params(params, n_heads=n_heads)
    rules = llm_shard_rules()
    return (shard_params(blocked, mesh, rules),
            param_specs(blocked, mesh, rules))


def kv_pool_specs():
    """PartitionSpec for the paged pools: the kv-head axis of
    (L, num_blocks, block_size, n_kv, hd) shards over tp, next to the
    head-blocked projections that read and write it."""
    from jax.sharding import PartitionSpec as P

    return P(None, None, None, "tp", None)


def kv_pool_placer(mesh):
    """Placement hook for `PagedKVCache(placer=…)`: device_put the
    pools with the head-axis sharding (spec construction stays here —
    NNL012)."""
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, kv_pool_specs())

    def place(pool):
        return jax.device_put(pool, sharding)

    return place


def _combine_rows(parts, axis_name: str = "tp"):
    """Row-parallel combine with N-independent numerics: stack the
    local blocks' partial sums, all_gather into global (8, …) block
    order, reduce by a fixed-order chain of adds. A `psum` here would
    tie the reduction order to the shard count and break bit-parity."""
    import jax
    import jax.numpy as jnp

    part = jnp.stack(parts)                               # (8/N, …)
    allp = jax.lax.all_gather(part, axis_name, tiled=False)
    allp = allp.reshape((FIXED_BLOCKS,) + part.shape[1:])
    acc = allp[0]
    for i in range(1, FIXED_BLOCKS):
        acc = acc + allp[i]
    return acc


def _concat_cols(parts, axis_name: str = "tp"):
    """Column-parallel combine: gather the local blocks and concatenate
    along the feature axis in global block order (exact — pure data
    movement)."""
    import jax
    import jax.numpy as jnp

    part = jnp.stack(parts)                               # (8/N, …, f/8)
    allp = jax.lax.all_gather(part, axis_name, tiled=False)
    allp = allp.reshape((FIXED_BLOCKS,) + part.shape[1:])
    return jnp.concatenate([allp[i] for i in range(FIXED_BLOCKS)], axis=-1)


def _blocked_mlp(blk, x, dtype):
    """SwiGLU with per-block gate/up/down — block b's activation slice
    never touches another block's columns, so the only cross-shard op
    is the final fixed-order row combine."""
    import jax

    nloc = blk["wg"].shape[0]
    parts = []
    for j in range(nloc):
        gate = x @ blk["wg"][j].astype(dtype)
        up = x @ blk["wu"][j].astype(dtype)
        parts.append((jax.nn.silu(gate) * up) @ blk["wd"][j].astype(dtype))
    return _combine_rows(parts)


def sharded_paged_decode_step(params, cur, tables, pos, k_pool, v_pool,
                              *, n_heads=4, dtype=None):
    """Blocked-TP twin of `llm/paged_model.paged_decode_step`, written
    against LOCAL shards (runs inside shard_map; `make_llm_jits` wires
    the specs). Per local head-block: project q/k/v, rope, scatter this
    step's K/V into the LOCAL pool slice, attend through the block
    tables, partial-project through wo — then one fixed-order row
    combine per layer. Attention is per-head math, so head blocks never
    communicate; the pool never leaves its shard."""
    import jax
    import jax.numpy as jnp

    dtype = dtype or jnp.float32
    b = cur.shape[0]
    _, _, block_size, n_kv_loc, hd = k_pool.shape
    max_blocks = tables.shape[1]
    kv_len = max_blocks * block_size
    rows = jnp.arange(b)
    write_blk = tables[rows, pos // block_size]
    write_off = pos % block_size
    nloc = params["blocks"][0]["wq"].shape[0]      # local head blocks
    kv_per_blk = n_kv_loc // nloc
    x = params["embed"][cur][:, None, :].astype(dtype)
    mask = (jnp.arange(kv_len)[None, None, None, :] <=
            pos[:, None, None, None])
    from nnstreamer_tpu.llm.paged_model import _rope_rows
    from nnstreamer_tpu.models.transformer import rmsnorm

    for li, blk in enumerate(params["blocks"]):
        h = rmsnorm(x, blk["ln1"].astype(dtype))
        hpb = blk["wq"].shape[2] // hd            # q heads per block
        parts = []
        for j in range(nloc):
            q = (h @ blk["wq"][j].astype(dtype)).reshape(b, 1, hpb, hd)
            k = (h @ blk["wk"][j].astype(dtype)).reshape(
                b, 1, kv_per_blk, hd)
            v = (h @ blk["wv"][j].astype(dtype)).reshape(
                b, 1, kv_per_blk, hd)
            q, k = _rope_rows(q, pos), _rope_rows(k, pos)
            kvs = slice(j * kv_per_blk, (j + 1) * kv_per_blk)
            k_pool = k_pool.at[li, write_blk, write_off, kvs].set(
                k[:, 0].astype(k_pool.dtype))
            v_pool = v_pool.at[li, write_blk, write_off, kvs].set(
                v[:, 0].astype(v_pool.dtype))
            kc = k_pool[li][:, :, kvs][tables].reshape(
                b, kv_len, kv_per_blk, hd)
            vc = v_pool[li][:, :, kvs][tables].reshape(
                b, kv_len, kv_per_blk, hd)
            kcx = jnp.repeat(kc, hpb // kv_per_blk,
                             axis=2).astype(jnp.float32)
            s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                           kcx) * hd ** -0.5
            s = jnp.where(mask, s, -1e30)
            pattn = jax.nn.softmax(s, axis=-1)
            vcx = jnp.repeat(vc, hpb // kv_per_blk,
                             axis=2).astype(jnp.float32)
            attn = jnp.einsum("bhqk,bkhd->bqhd", pattn, vcx).astype(dtype)
            parts.append(attn.reshape(b, 1, -1) @ blk["wo"][j].astype(dtype))
        x = x + _combine_rows(parts)
        h = rmsnorm(x, blk["ln2"].astype(dtype))
        x = x + _blocked_mlp(blk, h, dtype)
    x = rmsnorm(x, params["ln_f"].astype(dtype))
    nhb = params["head"].shape[0]
    logits = _concat_cols(
        [x[:, 0] @ params["head"][j].astype(dtype) for j in range(nhb)])
    return logits.astype(jnp.float32), k_pool, v_pool


def sharded_paged_prefill(params, ids, blk_idx, blk_off, k_pool, v_pool,
                          last_idx, *, n_heads=4, dtype=None):
    """Blocked-TP twin of `paged_prefill`: full-sequence causal forward
    + per-shard KV scatter, per local head-block. Same canonical
    blocking as the decode step, so ``shards=N`` prefill logits (and
    the KV every later decode reads) are bit-identical to ``shards=1``.
    Returns (last-token logits (vocab,), k_pool, v_pool)."""
    import jax
    import jax.numpy as jnp

    dtype = dtype or jnp.float32
    s_len = ids.shape[1]
    _, _, _, n_kv_loc, hd = k_pool.shape
    nloc = params["blocks"][0]["wq"].shape[0]
    kv_per_blk = n_kv_loc // nloc
    pos = jnp.arange(s_len)
    causal = (jnp.arange(s_len)[None, :] <=
              jnp.arange(s_len)[:, None])[None, None, :, :]
    x = params["embed"][ids].astype(dtype)                # (1, S, D)
    from nnstreamer_tpu.models.transformer import rmsnorm, rope

    for li, blk in enumerate(params["blocks"]):
        h = rmsnorm(x, blk["ln1"].astype(dtype))
        hpb = blk["wq"].shape[2] // hd
        parts = []
        for j in range(nloc):
            q = (h @ blk["wq"][j].astype(dtype)).reshape(
                1, s_len, hpb, hd)
            k = (h @ blk["wk"][j].astype(dtype)).reshape(
                1, s_len, kv_per_blk, hd)
            v = (h @ blk["wv"][j].astype(dtype)).reshape(
                1, s_len, kv_per_blk, hd)
            q, k = rope(q, pos), rope(k, pos)
            kvs = slice(j * kv_per_blk, (j + 1) * kv_per_blk)
            k_pool = k_pool.at[li, blk_idx, blk_off, kvs].set(
                k[0].astype(k_pool.dtype))
            v_pool = v_pool.at[li, blk_idx, blk_off, kvs].set(
                v[0].astype(v_pool.dtype))
            kcx = jnp.repeat(k, hpb // kv_per_blk,
                             axis=2).astype(jnp.float32)
            sc = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                            kcx) * hd ** -0.5
            sc = jnp.where(causal, sc, -1e30)
            pattn = jax.nn.softmax(sc, axis=-1)
            vcx = jnp.repeat(v, hpb // kv_per_blk,
                             axis=2).astype(jnp.float32)
            attn = jnp.einsum("bhqk,bkhd->bqhd", pattn, vcx).astype(dtype)
            parts.append(
                attn.reshape(1, s_len, -1) @ blk["wo"][j].astype(dtype))
        x = x + _combine_rows(parts)
        h = rmsnorm(x, blk["ln2"].astype(dtype))
        x = x + _blocked_mlp(blk, h, dtype)
    x = rmsnorm(x, params["ln_f"].astype(dtype))
    nhb = params["head"].shape[0]
    logits = _concat_cols(
        [x[0] @ params["head"][j].astype(dtype) for j in range(nhb)])
    return (logits.astype(jnp.float32)[last_idx], k_pool, v_pool)


def make_llm_fns(mesh, param_spec_tree, mesh_devices=None):
    """Unjitted N-chip callables for the sharded paged family, keyed by
    kind — what `PagedLLMExecutor` jits per (namespace, kind, bucket)
    under its ``("tp", N, …)`` namespace, preserving its per-bucket
    compile accounting. Signatures mirror `llm/paged_model.py`
    (params, …, k_pool, v_pool → (logits, k_pool, v_pool)); the pools
    stay head-sharded in and out (donated by the executor's jit).

    "ring" is the long-context prefill twin: `ring_prefill` attention
    (sequence-parallel over the same chips) + the standard pool
    scatter. It takes RAW (unblocked, replicated) params — see
    `replicate_params` — and is allclose-, not bit-, equivalent."""
    from jax.sharding import PartitionSpec as P

    from nnstreamer_tpu.parallel._compat import shard_map

    pool = kv_pool_specs()

    def prefill(params, ids, blk_idx, blk_off, k_pool, v_pool,
                last_idx, n_heads=4, dtype=None):
        body = shard_map(
            lambda p, i, bi, bo, kp, vp, la: sharded_paged_prefill(
                p, i, bi, bo, kp, vp, la, n_heads=n_heads, dtype=dtype),
            mesh=mesh,
            in_specs=(param_spec_tree, P(), P(), P(), pool, pool, P()),
            out_specs=(P(), pool, pool), check_vma=False)
        return body(params, ids, blk_idx, blk_off, k_pool, v_pool,
                    last_idx)

    def decode(params, cur, tables, pos, k_pool, v_pool,
               n_heads=4, dtype=None):
        body = shard_map(
            lambda p, c, t, q, kp, vp: sharded_paged_decode_step(
                p, c, t, q, kp, vp, n_heads=n_heads, dtype=dtype),
            mesh=mesh,
            in_specs=(param_spec_tree, P(), P(), P(), pool, pool),
            out_specs=(P(), pool, pool), check_vma=False)
        return body(params, cur, tables, pos, k_pool, v_pool)

    devs = (list(mesh_devices) if mesh_devices is not None
            else list(mesh.devices.flat))

    def ring(params, ids, blk_idx, blk_off, k_pool, v_pool,
             last_idx, n_heads=4, dtype=None):
        logits, ks, vs = ring_prefill(params, ids, devs,
                                      n_heads=n_heads, dtype=dtype)
        # standard paged_prefill scatter; the head-sharded pool writes
        # partition under GSPMD (replicated ks/vs → local head slices)
        k_pool = k_pool.at[:, blk_idx, blk_off].set(
            ks[:, 0].astype(k_pool.dtype))
        v_pool = v_pool.at[:, blk_idx, blk_off].set(
            vs[:, 0].astype(v_pool.dtype))
        return logits[0, last_idx], k_pool, v_pool

    return {"prefill": prefill, "decode": decode, "ring": ring}


def make_llm_jits(mesh, param_spec_tree):
    """Jitted convenience wrappers over `make_llm_fns` (tests/bench) —
    same static/donate discipline as the executor's per-bucket jits:
    pools donate (write-in-place on device), n_heads/dtype static."""
    import jax

    fns = make_llm_fns(mesh, param_spec_tree)
    return {
        "prefill": jax.jit(fns["prefill"],
                           static_argnames=("n_heads", "dtype"),
                           donate_argnums=(4, 5)),
        "decode": jax.jit(fns["decode"],
                          static_argnames=("n_heads", "dtype"),
                          donate_argnums=(4, 5)),
    }


def replicate_params(params, mesh):
    """device_put a raw params pytree fully replicated across the group
    mesh (the ring-prefill path serves the unblocked weights; spec
    construction stays here — NNL012)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sharding), params)


def ring_prefill(params, ids, mesh_devices, *, n_heads=4, dtype=None):
    """Long-context prefill attention via `parallel/ring_attention.py`:
    the same chips re-axed as ("sp",), sequence sharded, K/V rotating
    by ppermute. Returns (logits (1,S,vocab) f32, ks, vs) with ks/vs
    (L, 1, S, n_kv, hd) — `paged_prefill`'s KV layout, for scatter into
    the (sharded) pools. Online-softmax math: equivalent to the blocked
    path within float tolerance, never bit-exact — callers gate it on a
    length threshold and the parity tests pin the blocked path."""
    import jax
    import jax.numpy as jnp

    from nnstreamer_tpu.models.transformer import (
        _expand_kv, _qkv, _mlp, rmsnorm, rope)
    from nnstreamer_tpu.parallel.ring_attention import ring_attention

    dtype = dtype or jnp.float32
    mesh = _sp_mesh(mesh_devices)
    b, s = ids.shape
    if s % max(1, len(mesh_devices)):
        raise BackendError(
            f"ring prefill needs the bucketed prompt length ({s}) "
            f"divisible by the shard count ({len(mesh_devices)})")
    x = params["embed"][ids].astype(dtype)
    pos = jnp.arange(s)
    ks, vs = [], []
    for blk in params["blocks"]:
        h = rmsnorm(x, blk["ln1"].astype(dtype))
        q, k, v = _qkv(blk, h, n_heads, dtype)
        q, k = rope(q, pos), rope(k, pos)
        ks.append(k)
        vs.append(v)
        attn = ring_attention(q, _expand_kv(k, n_heads),
                              _expand_kv(v, n_heads), mesh=mesh,
                              axis="sp", causal=True)
        x = x + attn.reshape(b, s, -1) @ blk["wo"].astype(dtype)
        h = rmsnorm(x, blk["ln2"].astype(dtype))
        x = x + _mlp(blk, h, dtype)
    x = rmsnorm(x, params["ln_f"].astype(dtype))
    logits = (x @ params["head"].astype(dtype)).astype(jnp.float32)
    return logits, jnp.stack(ks), jnp.stack(vs)


# ---------------------------------------------------------------------------
# Shard groups: placement + routing + fencing
# ---------------------------------------------------------------------------

class ShardedReplicaSet(ReplicaSet):
    """G shard groups of N chips each behind the ReplicaSet front door.

    Each "replica" is one `ShardedBackend` — an N-chip SPMD program —
    so routing, backpressure, the reoffer path and the conservation
    ledger are inherited unchanged; what changes is the failure unit:
    `fence_device(chip)` fences the chip's whole GROUP (SPMD cannot run
    on N-1 chips), its lease rows flip to fenced in the group's
    `ChipLeaseTable`, and the stranded work re-routes to surviving
    groups exactly like a fenced dp replica's."""

    def __init__(self, backends, group_devices: List[Tuple[int, ...]],
                 leases: Optional[ChipLeaseTable] = None, **kw):
        self.group_devices = [tuple(g) for g in group_devices]
        self.leases = leases
        super().__init__(backends, list(range(len(backends))), **kw)

    @classmethod
    def open_sharded(cls, model, *, shards: int, groups: int = 0,
                     leases: Optional[ChipLeaseTable] = None,
                     queue_cap: int = 64, name: str = "sharded",
                     tracer=None) -> "ShardedReplicaSet":
        """Stand up `groups` shard groups of `shards` chips (0 = as
        many as the visible device count fits, at least one). Chips are
        leased per group from `leases` (one owner per group, so a group
        fence is one ledger fence) — a fresh table over the visible
        devices when the caller does not share one."""
        shards = validate_shards(shards)
        ndev = len(visible_devices())
        if groups <= 0:
            groups = max(1, ndev // shards)
        if groups * shards > ndev:
            raise BackendError(
                f"shards={shards} x {groups} groups needs "
                f"{groups * shards} devices, {ndev} visible; run under "
                f"XLA_FLAGS=--xla_force_host_platform_device_count=N")
        if leases is None:
            leases = ChipLeaseTable(range(ndev))
        store_name = ""
        if isinstance(model, str) and model.startswith("store://"):
            store_name = model[len("store://"):].split("@", 1)[0]
        backends, group_devs = [], []
        try:
            for g in range(groups):
                chips = leases.lease(f"{name}/g{g}", shards)
                b = ShardedBackend(model, chips, name=f"{name}/g{g}")
                backends.append(b)
                group_devs.append(chips)
        except Exception:
            for g, b in enumerate(backends):
                try:
                    b.close()
                except Exception:
                    pass
                leases.release(f"{name}/g{g}")
            raise
        return cls(backends, group_devs, leases, queue_cap=queue_cap,
                   bucket=1, name=name, tracer=tracer,
                   store_name=store_name)

    # -- group fencing ------------------------------------------------------
    def group_of(self, chip: int) -> Optional[int]:
        for g, devs in enumerate(self.group_devices):
            if int(chip) in devs:
                return g
        return None

    def fence_device(self, chip: int, cause: str = "fenced") -> bool:
        """A member chip died: fence its whole shard group — the lease
        rows AND the routing replica — so conservation flows through
        the inherited reoffer path."""
        g = self.group_of(chip)
        if g is None:
            return False
        if self.leases is not None:
            self.leases.fence(f"{self.name}/g{g}")
        return self.fence(g, f"member chip {chip} {cause}")

    # -- stats --------------------------------------------------------------
    def stats(self) -> dict:
        out = super().stats()
        # rows stay under "replicas" — filter.extra_stats and the metric
        # scrape read that key; sharded-ness is extra fields, not a new
        # schema
        for g, row in enumerate(out["replicas"]):
            row["group"] = g
            row["devices"] = list(self.group_devices[g])
            row["shards"] = len(self.group_devices[g])
        out["group_size"] = (len(self.group_devices[0])
                             if self.group_devices else 0)
        if self.leases is not None:
            out["leases"] = self.leases.snapshot()["counts"]
        return out
