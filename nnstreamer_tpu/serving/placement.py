"""Device placement: data-parallel replicas + profiled model segmentation.

The subsystem that wires `parallel/` into the serving path (ROADMAP
"the next frontier is horizontal"). Two placement modes:

**Data-parallel replicas** (`ReplicaSet`): `tensor_filter devices=N`
stands up N per-chip model replicas — each one a full backend instance
pinned to its own device, fed by a per-chip bounded queue running the
`parallel/dispatch.BatchCore` batching discipline (linger window,
overlapped D2H readback, count-before-resolve conservation). Routing is
least-outstanding with a round-robin tiebreak; a fenced replica's
queued work is re-routed to survivors, so Σ replica invokes == filter
replied holds exactly through a chip loss. Hot swap is store-integrated:
every replica backend attaches to the model's `_Entry` as a swap
handle, so one `ModelStore.update()` is the two-phase broadcast —
prepare pre-warms the new version on every replica (any failure aborts
before anything flips, same contract as `pool.rebind`), commit is the
entry's single `_state` assignment, and all replicas adopt the same
epoch at their next invoke with zero post-flip recompiles.

**Profiled model segmentation** (`segment_plan` / `apply_plan`):
consumes the tracer's per-element proctime profile to choose cut points
(balanced contiguous partition — profiled cuts beat naive equal splits,
arXiv 2503.01025), places each PR-8 `compose_segment` unit on its own
device (the plan pins each stage's filters to one device via the
`accelerator` prop; `graph/optimize.fuse_segments` then refuses to
absorb across a planned cut), and reports per-stage/bubble time.
Handoffs between stages are explicit `device_put`s: the next stage's
backend stages incoming arrays onto its own device (counted by its
`staging_transfers`).

**Chip leases** (`ChipLeaseTable`): the worker-pool supervisor's view
of "worker `wid` owns chips i..j". A crashed worker's chips are fenced
at reap time and re-leased to the slot's replacement process at
restart, so capacity accounting (tenancy's ScalingController weighs a
K-chip slot as K capacity slots) never counts a dead chip.

This module (plus `parallel/`) is the ONLY place allowed to pick
explicit devices — nnlint NNL009 flags `jax.devices()[i]` anywhere
else, so placement decisions cannot leak into random call sites.

Everything here runs under CPU emulation
(`XLA_FLAGS=--xla_force_host_platform_device_count=8`), which is how
tier-1 exercises real multi-device placement without a chip.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from nnstreamer_tpu.core.errors import BackendError, StreamError
from nnstreamer_tpu.core.log import get_logger

log = get_logger("serving.placement")


# -- device enumeration (the subsystem's single blessed call site) -----------

def visible_devices() -> list:
    """Every addressable accelerator device, in jax enumeration order.
    All placement decisions route through here (NNL009)."""
    import jax

    return list(jax.devices())


def device_of(index: int):
    """The device with ordinal `index`; typed error past the end."""
    devs = visible_devices()
    if not 0 <= index < len(devs):
        raise BackendError(
            f"device index {index} out of range: {len(devs)} device(s) "
            f"visible ({devs[0].platform if devs else 'none'})")
    return devs[index]


def accelerator_for(index: int) -> str:
    """`accelerator=` property string pinning a backend to one device
    (e.g. ``cpu:3`` / ``tpu:1``) — how the plan reaches `_pick_device`."""
    return f"{device_of(index).platform}:{index}"


# -- data-parallel replicas ---------------------------------------------------

class _Replica:
    """One per-chip model replica: a backend pinned to its device plus
    the bounded BatchCore queue that feeds it."""

    def __init__(self, index: int, backend, core, platform: str):
        self.index = index            # device ordinal
        self.backend = backend
        self.core = core
        self.platform = platform
        self.fenced = False

    @property
    def outstanding(self) -> int:
        return self.core.outstanding


class ReplicaSet:
    """N per-chip replicas behind one submit()/invoke() front door.

    Construction: `ReplicaSet.open(framework, props, count)` opens one
    backend per device with `accelerator=<platform>:<i>` (replica i on
    device i); `configure` replays any head-side backend setup (fuse,
    set_input_info) on each replica so every chip serves the exact
    single-device program — bit-parity by construction.

    Routing: least outstanding work first, round-robin among ties; a
    replica whose bounded queue is full is skipped, and when every
    replica is full submit() raises a typed StreamError (backpressure,
    never unbounded buffering). A payload stranded by a fence is
    re-routed to a surviving replica (`reoffers` counts them), so the
    conservation ledger Σ replica invokes == frames replied holds
    exactly through a chip loss.
    """

    def __init__(self, backends: Sequence[Any], device_indices: Sequence[int],
                 *, queue_cap: int = 64, bucket: int = 4,
                 max_delay_ms: float = 0.0, name: str = "replicas",
                 tracer=None, store_name: str = ""):
        if not backends:
            raise BackendError("ReplicaSet needs at least one backend")
        from nnstreamer_tpu.parallel.dispatch import BatchCore

        self.name = name
        self.tracer = tracer
        self.store_name = store_name
        self._lock = threading.Lock()
        self._rr = 0
        self.routed = 0
        self.reoffers = 0
        self.rejected = 0
        self.fences = 0
        self.max_redeliver = 1
        devs = visible_devices()
        self._replicas: List[_Replica] = []
        for b, di in zip(backends, device_indices):
            core = BatchCore(
                self._make_run(len(self._replicas), di),
                buckets=[max(1, int(bucket))],
                max_delay_s=max_delay_ms / 1e3,
                capacity=int(queue_cap), raw=True,
                name=f"{name}-dev{di}")
            self._replicas.append(
                _Replica(di, b, core,
                         devs[di].platform if di < len(devs) else "cpu"))

    @classmethod
    def open(cls, framework: str, props: Dict[str, Any], count: int, *,
             configure: Optional[Callable[[Any], None]] = None,
             queue_cap: int = 64, bucket: int = 4,
             max_delay_ms: float = 0.0, name: str = "replicas",
             tracer=None) -> "ReplicaSet":
        """Stand up `count` per-device backends of `framework`, replica
        i pinned to device i. Backends opened so far are closed again
        if any later one fails — all replicas or none."""
        from nnstreamer_tpu.backends.base import get_backend

        devs = visible_devices()
        if count > len(devs):
            raise BackendError(
                f"devices={count} requested but only {len(devs)} "
                f"device(s) visible; run under "
                f"XLA_FLAGS=--xla_force_host_platform_device_count=N "
                f"for CPU emulation")
        model = props.get("model")
        store_name = ""
        if isinstance(model, str) and model.startswith("store://"):
            store_name = model[len("store://"):].split("@", 1)[0]
        backends = []
        try:
            for i in range(count):
                b = get_backend(framework)
                p = dict(props)
                p["accelerator"] = accelerator_for(i)
                b.open(p)
                if configure is not None:
                    configure(b)
                backends.append(b)
        except Exception:
            for b in backends:
                try:
                    b.close()
                except Exception:
                    pass
            raise
        return cls(backends, list(range(count)), queue_cap=queue_cap,
                   bucket=bucket, max_delay_ms=max_delay_ms, name=name,
                   tracer=tracer, store_name=store_name)

    # -- execution ---------------------------------------------------------
    def _make_run(self, ridx: int, dev_index: int):
        def run(items: List[tuple], n: int) -> List[tuple]:
            r = self._replicas[ridx]
            out: List[tuple] = []
            for payload in items:
                kind = payload[0]
                t0 = time.perf_counter()
                if kind == "invoke":
                    res = r.backend.invoke(payload[1])
                elif kind == "batched":
                    res = r.backend.invoke_batched(
                        payload[1], payload[2], payload[3])
                else:
                    raise StreamError(
                        f"unknown replica payload kind {kind!r}")
                t1 = time.perf_counter()
                tr = self.tracer
                if tr is not None and getattr(tr, "active", False):
                    tr.device_span(dev_index, "invoke", t0, t1,
                                   element=self.name,
                                   frames=payload[2]
                                   if kind == "batched" else 1)
                out.append(tuple(res) if isinstance(res, (tuple, list))
                           else (res,))
            return out

        return run

    # -- routing -----------------------------------------------------------
    def _pick(self, exclude: Tuple[int, ...] = ()) -> Optional[_Replica]:
        """Least-outstanding live replica; round-robin breaks ties so
        an idle set still spreads work across every chip."""
        with self._lock:
            live = [r for r in self._replicas
                    if not r.fenced and r.index not in exclude]
            if not live:
                return None
            start = self._rr % len(live)
            self._rr += 1
            order = live[start:] + live[:start]
            return min(order, key=lambda r: r.outstanding)

    def _route(self, payload, outer: Future, attempts: int,
               exclude: Tuple[int, ...] = ()) -> None:
        tried: List[int] = list(exclude)
        while True:
            r = self._pick(tuple(tried))
            if r is None:
                with self._lock:
                    self.rejected += 1
                outer.set_exception(StreamError(
                    f"{self.name}: no live replica accepted the frame "
                    f"(fenced/full: {sorted(tried)})"))
                return
            try:
                inner = r.core.submit(payload)
            except StreamError:
                tried.append(r.index)   # full or fenced mid-pick
                continue
            with self._lock:
                self.routed += 1

            def _done(fut, r=r, payload=payload, attempts=attempts):
                exc = fut.exception()
                if exc is None:
                    if not outer.done():
                        outer.set_result(fut.result())
                    return
                # a fence strands queued payloads — re-route them to a
                # survivor (the frame never ran, retrying is safe);
                # genuine model errors propagate untouched
                if r.fenced and attempts < self.max_redeliver:
                    with self._lock:
                        self.reoffers += 1
                    self._route(payload, outer, attempts + 1,
                                exclude=(r.index,))
                    return
                if not outer.done():
                    outer.set_exception(exc)

            inner.add_done_callback(_done)
            return

    def submit(self, inputs: tuple) -> Future:
        """Route one invocation (tuple of input tensors); the future
        resolves to the output tensor tuple (host arrays)."""
        outer: Future = Future()
        self._route(("invoke", tuple(inputs)), outer, 0)
        return outer

    def submit_batched(self, inputs: tuple, n: int, keepdims) -> Future:
        outer: Future = Future()
        self._route(("batched", tuple(inputs), int(n), keepdims), outer, 0)
        return outer

    def invoke(self, inputs: tuple, timeout: Optional[float] = 60.0):
        return self.submit(inputs).result(timeout)

    def invoke_batched(self, inputs: tuple, n: int, keepdims,
                       timeout: Optional[float] = 60.0):
        return self.submit_batched(inputs, n, keepdims).result(timeout)

    # -- chaos / supervision -----------------------------------------------
    def fence(self, index: int, cause: str = "fenced") -> bool:
        """Take replica `index` out of service: stop routing to it,
        fail its queued work immediately (re-routed by the outer
        futures), let anything already on device finish."""
        with self._lock:
            r = next((x for x in self._replicas if x.index == index), None)
            if r is None or r.fenced:
                return False
            r.fenced = True
            self.fences += 1
        r.core.abort(f"replica dev{index} {cause}")
        log.warning("%s: replica dev%d fenced (%s)", self.name, index,
                    cause)
        return True

    def live_replicas(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas if not r.fenced)

    # -- store hot swap ----------------------------------------------------
    def swap(self, version=None, wait_s: Optional[float] = None) -> dict:
        """Two-phase, epoch-atomic hot swap across every replica, by
        delegating to the store's handle protocol: prepare pre-warms
        the target version on each attached replica backend (any
        failure raises BEFORE the flip — nothing moved, same
        all-or-none contract as `pool.rebind`); commit is the entry's
        single `_state` assignment, after which every replica adopts
        the same epoch at its next invoke boundary. Pre-warm staged the
        exact jits, so the flip costs zero recompiles."""
        if not self.store_name:
            raise BackendError(
                f"{self.name}: not store-backed (model was not a "
                f"store:// ref); register the model in the ModelStore "
                f"to hot swap replicas")
        from nnstreamer_tpu.serving.store import get_store

        return get_store().update(self.store_name, version,
                                  prewarm=True, wait_s=wait_s)

    def adopted_epochs(self) -> List[int]:
        return [getattr(r.backend, "adopted_epoch", -1)
                for r in self._replicas]

    def compile_counts(self) -> List[int]:
        return [int(getattr(r.backend, "compile_count", 0) or 0)
                for r in self._replicas]

    # -- lifecycle / stats -------------------------------------------------
    def warm_start(self, tracer=None, trace_name: str = "") -> None:
        for r in self._replicas:
            if tracer is not None:
                r.backend.tracer = tracer
                r.backend.trace_name = (
                    f"{trace_name or self.name}/dev{r.index}")
            r.backend.warm_start()
        if tracer is not None:
            self.tracer = tracer

    def close(self) -> None:
        for r in self._replicas:
            r.core.shutdown()
        for r in self._replicas:
            try:
                r.backend.close()
            except Exception:
                pass

    def stats(self) -> dict:
        rows = []
        with self._lock:
            reps = list(self._replicas)
            totals = {"routed": self.routed, "reoffers": self.reoffers,
                      "rejected": self.rejected, "fences": self.fences}
        for r in reps:
            cs = r.core.stats()
            rows.append({
                "device": r.index,
                "platform": r.platform,
                "invokes": cs["frames"],
                "batches": cs["batches"],
                "errors": cs["errors"],
                "queue_depth": cs["outstanding"],
                "up": not r.fenced,
                "state": "fenced" if r.fenced else "ready",
                "compile_count": int(
                    getattr(r.backend, "compile_count", 0) or 0),
                "adopted_epoch": getattr(r.backend, "adopted_epoch", -1),
            })
        out = {"replicas": rows, "devices": len(rows),
               "live": sum(1 for x in rows if x["up"])}
        out.update(totals)
        return out


# -- chip leases (worker-pool supervision) -----------------------------------

class ChipLeaseTable:
    """Which process owns which chips — the supervisor's fencing ledger.

    States per chip: ``free`` (unowned), ``leased`` (owned by a live
    worker), ``fenced`` (its owner died; the chip is out of service
    until the replacement process re-leases it). `lease()` prefers the
    owner's own fenced chips, so a restarted slot gets its chips back
    — the "worker owns chips i..j" invariant survives the crash."""

    def __init__(self, chips: Sequence[int]):
        self._lock = threading.Lock()
        self._chips: Dict[int, dict] = {
            int(c): {"owner": None, "state": "free"}
            for c in chips}
        self.fences_total = 0
        self.releases_total = 0

    def lease(self, owner, want: Optional[int] = None) -> Tuple[int, ...]:
        """Lease `want` chips to `owner` (None = all of its fenced
        chips, i.e. a re-lease after restart). Own fenced chips come
        back first; free chips top up the rest. Typed error when the
        table cannot satisfy the request — silently under-leasing would
        corrupt the scaler's capacity math."""
        with self._lock:
            got: List[int] = []
            for c, row in sorted(self._chips.items()):
                if row["state"] == "fenced" and row["owner"] == owner:
                    got.append(c)
            if want is None:
                want = len(got)
            for c, row in sorted(self._chips.items()):
                if len(got) >= want:
                    break
                if row["state"] == "free":
                    got.append(c)
            if len(got) < want:
                raise BackendError(
                    f"chip lease for {owner!r}: wanted {want}, only "
                    f"{len(got)} available "
                    f"({self._counts_locked()})")
            got = got[:want]
            for c in got:
                self._chips[c] = {"owner": owner, "state": "leased"}
            return tuple(sorted(got))

    def fence(self, owner) -> Tuple[int, ...]:
        """The owner died: its leased chips go out of service, still
        associated with the owner so the restart re-leases them."""
        with self._lock:
            fenced = []
            for c, row in self._chips.items():
                if row["owner"] == owner and row["state"] == "leased":
                    row["state"] = "fenced"
                    fenced.append(c)
            self.fences_total += len(fenced)
            return tuple(sorted(fenced))

    def release(self, owner) -> Tuple[int, ...]:
        """Give the owner's chips (leased or fenced) back to the free
        pool — a slot disabled by the restart circuit surrenders its
        capacity instead of pinning dead chips forever."""
        with self._lock:
            freed = []
            for c, row in self._chips.items():
                if row["owner"] == owner:
                    self._chips[c] = {"owner": None, "state": "free"}
                    freed.append(c)
            self.releases_total += len(freed)
            return tuple(sorted(freed))

    def chips_of(self, owner) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(c for c, row in self._chips.items()
                                if row["owner"] == owner))

    def _counts_locked(self) -> dict:
        counts = {"free": 0, "leased": 0, "fenced": 0}
        for row in self._chips.values():
            counts[row["state"]] += 1
        return counts

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "chips": {c: dict(row)
                          for c, row in sorted(self._chips.items())},
                "counts": self._counts_locked(),
                "fences_total": self.fences_total,
                "releases_total": self.releases_total,
            }


# -- profiled model segmentation ---------------------------------------------

@dataclass
class SegmentPlan:
    """Where to cut a filter chain and which device runs each piece."""

    stages: List[List[str]]        # element names, dataflow order
    devices: List[int]             # first device ordinal per stage
    stage_times_s: List[float]     # profiled per-stage proctime sum
    bubble_fraction: float         # steady-state device idle share
    total_s: float                 # profiled single-device total
    source: str = "profile"
    tp: List[int] = field(default_factory=list)  # shards per stage (1 = none)

    def tp_of(self, stage: int) -> int:
        return self.tp[stage] if self.tp else 1

    def chips_total(self) -> int:
        return sum(self.tp) if self.tp else len(self.stages)

    def stage_of(self) -> Dict[str, int]:
        return {name: i for i, group in enumerate(self.stages)
                for name in group}

    def report(self) -> dict:
        """Per-stage/bubble summary (feeds the metrics plane)."""
        return {
            "stages": [
                {"stage": i, "device": self.devices[i],
                 "elements": list(self.stages[i]),
                 "time_s": self.stage_times_s[i],
                 "tp": self.tp_of(i)}
                for i in range(len(self.stages))],
            "bubble_fraction": self.bubble_fraction,
            "bottleneck_s": max(self.stage_times_s, default=0.0),
            "total_s": self.total_s,
            "chips_total": self.chips_total(),
            "source": self.source,
        }

    def measured_report(self, tracer) -> dict:
        """Like report(), but with stage times re-read from the live
        tracer profile of each stage's surviving head element — the
        planned-vs-measured comparison that tells you whether the cut
        points still fit the traffic."""
        hists = tracer.hists() if getattr(tracer, "active", False) else {}
        times = []
        for group in self.stages:
            h = hists.get(group[0]) if group else None
            times.append(h["sum"] / h["count"]
                         if h and h["count"] else 0.0)
        mx = max(times, default=0.0)
        rep = self.report()
        for i, row in enumerate(rep["stages"]):
            row["measured_s"] = times[i]
        rep["measured_bubble_fraction"] = (
            1.0 - (sum(times) / (len(times) * mx)) if mx > 0 else 0.0)
        return rep


def _bubble(stage_times: List[float]) -> float:
    """Steady-state idle share of a synchronous pipeline: every cycle
    takes the bottleneck stage's time, so each other stage idles for
    (max - its own time) of it."""
    mx = max(stage_times, default=0.0)
    if mx <= 0 or len(stage_times) <= 1:
        return 0.0
    return 1.0 - sum(stage_times) / (len(stage_times) * mx)


def segment_plan(costs: Sequence[Tuple[str, float]],
                 ndev: int, *, source: str = "profile") -> SegmentPlan:
    """Optimal contiguous partition of a profiled chain over up to
    `ndev` devices, minimizing the bottleneck stage (classic linear
    partition DP, O(n²k)) — the profiled-cut-point pass of arXiv
    2503.01025. `costs` is [(element_name, seconds)] in dataflow order;
    zero-cost elements (never profiled) ride along with their
    neighbours. Stage s is placed on device s."""
    names = [n for n, _ in costs]
    ts = [max(0.0, float(t)) for _, t in costs]
    n = len(ts)
    if n == 0:
        raise BackendError("segment_plan: empty cost profile")
    k = max(1, min(int(ndev), n))
    # prefix[i] = sum of ts[:i]
    prefix = [0.0]
    for t in ts:
        prefix.append(prefix[-1] + t)
    INF = float("inf")
    # best[j][i] = minimal bottleneck splitting first i elements into j
    best = [[INF] * (n + 1) for _ in range(k + 1)]
    cut = [[0] * (n + 1) for _ in range(k + 1)]
    best[0][0] = 0.0
    for j in range(1, k + 1):
        for i in range(j, n + 1):
            for m in range(j - 1, i):
                cand = max(best[j - 1][m], prefix[i] - prefix[m])
                if cand < best[j][i]:
                    best[j][i] = cand
                    cut[j][i] = m
    # fewer stages can tie the bottleneck (e.g. one dominant element):
    # prefer the smallest stage count that achieves it — extra cuts buy
    # nothing but handoffs
    kbest = min(range(1, k + 1), key=lambda j: (best[j][n], j))
    bounds: List[int] = []
    i, j = n, kbest
    while j > 0:
        bounds.append(i)
        i = cut[j][i]
        j -= 1
    bounds.reverse()
    stages, times = [], []
    lo = 0
    for hi in bounds:
        stages.append(names[lo:hi])
        times.append(prefix[hi] - prefix[lo])
        lo = hi
    return SegmentPlan(stages=stages,
                       devices=list(range(len(stages))),
                       stage_times_s=times,
                       bubble_fraction=_bubble(times),
                       total_s=prefix[n], source=source)


def _tp_speedup(t: int, eff: float) -> float:
    """Modeled speedup of giving one stage `t` tensor-parallel shards:
    each doubling buys 2·eff (eff < 1 pays for the all-gather/combine
    collectives), so speedup(t) = t · eff^log2(t). speedup(1) == 1."""
    return float(t) * (eff ** max(0, t.bit_length() - 1))


def segment_plan_tp(costs: Sequence[Tuple[str, float]], ndev: int, *,
                    tp_efficiency: float = 0.7,
                    source: str = "profile") -> SegmentPlan:
    """TP-vs-segmentation mix: spend a `ndev`-chip budget on pipeline
    cuts AND tensor-parallel shard groups, minimizing the modeled
    bottleneck. For every candidate stage count j the inner linear
    partition DP (same recurrence as `segment_plan`) yields the best
    j-way cut; the j-1 leftover chips are then spent greedily, always
    doubling the TP width of the current bottleneck stage (widths stay
    in `serving.sharding.SUPPORTED_SHARDS`, one shard group per stage).
    The j whose mixed plan has the lowest bottleneck wins; ties prefer
    fewer stages, then fewer chips — a cut or a shard that buys nothing
    is not taken. `stage_times_s` holds the modeled post-TP times, so
    `bubble_fraction` reflects the mixed plan; `devices[i]` is the
    first chip ordinal of stage i's contiguous tp[i]-chip group."""
    from nnstreamer_tpu.serving.sharding import SUPPORTED_SHARDS

    names = [n for n, _ in costs]
    ts = [max(0.0, float(t)) for _, t in costs]
    n = len(ts)
    if n == 0:
        raise BackendError("segment_plan_tp: empty cost profile")
    if not 0.0 < tp_efficiency <= 1.0:
        raise BackendError(
            f"segment_plan_tp: tp_efficiency must be in (0, 1], "
            f"got {tp_efficiency}")
    ndev = max(1, int(ndev))
    k = min(ndev, n)
    prefix = [0.0]
    for t in ts:
        prefix.append(prefix[-1] + t)
    INF = float("inf")
    best = [[INF] * (n + 1) for _ in range(k + 1)]
    cut = [[0] * (n + 1) for _ in range(k + 1)]
    best[0][0] = 0.0
    for j in range(1, k + 1):
        for i in range(j, n + 1):
            for m in range(j - 1, i):
                cand = max(best[j - 1][m], prefix[i] - prefix[m])
                if cand < best[j][i]:
                    best[j][i] = cand
                    cut[j][i] = m

    def _partition(j: int) -> List[float]:
        bounds: List[int] = []
        i = n
        jj = j
        while jj > 0:
            bounds.append(i)
            i = cut[jj][i]
            jj -= 1
        bounds.reverse()
        return bounds

    top_tp = max(s for s in SUPPORTED_SHARDS)
    champion = None  # (bottleneck, j, chips, bounds, tps)
    for j in range(1, k + 1):
        bounds = _partition(j)
        raw = []
        lo = 0
        for hi in bounds:
            raw.append(prefix[hi] - prefix[lo])
            lo = hi
        tps = [1] * j
        spare = ndev - j
        # double the bottleneck's TP while a doubling fits the budget
        # and actually lowers the modeled bottleneck
        while True:
            eff = [raw[s] / _tp_speedup(tps[s], tp_efficiency)
                   for s in range(j)]
            b = max(range(j), key=lambda s: eff[s])
            grow = tps[b]  # doubling costs tps[b] more chips
            if (tps[b] * 2 > top_tp or grow > spare
                    or _tp_speedup(tps[b] * 2, tp_efficiency)
                    <= _tp_speedup(tps[b], tp_efficiency)):
                break
            tps[b] *= 2
            spare -= grow
        eff = [raw[s] / _tp_speedup(tps[s], tp_efficiency)
               for s in range(j)]
        key = (max(eff), j, sum(tps))
        if champion is None or key < champion[0]:
            champion = (key, bounds, tps, eff)
    _, bounds, tps, eff = champion
    stages = []
    lo = 0
    for hi in bounds:
        stages.append(names[lo:hi])
        lo = hi
    devices, off = [], 0
    for t in tps:
        devices.append(off)
        off += t
    return SegmentPlan(stages=stages, devices=devices,
                       stage_times_s=eff, bubble_fraction=_bubble(eff),
                       total_s=prefix[n], source=source, tp=tps)


def plan_from_tracer(tracer, names: Sequence[str], ndev: int,
                     tp_efficiency: Optional[float] = None) -> SegmentPlan:
    """Build a plan from the tracer's per-element proctime histograms
    (`Tracer.hists()`): each element's cost is its observed mean
    process() time. Elements with no profile yet cost zero (they ride
    along with profiled neighbours). Pass `tp_efficiency` to let the
    planner trade pipeline cuts against tensor-parallel shard groups
    (`segment_plan_tp`); None keeps the pure-segmentation DP."""
    hists = tracer.hists() if getattr(tracer, "active", False) else {}
    costs = []
    for nm in names:
        h = hists.get(nm)
        costs.append((nm, h["sum"] / h["count"]
                      if h and h["count"] else 0.0))
    if tp_efficiency is not None:
        return segment_plan_tp(costs, ndev, tp_efficiency=tp_efficiency,
                               source="tracer")
    return segment_plan(costs, ndev, source="tracer")


def apply_plan(pipe, plan: SegmentPlan) -> int:
    """Pin each planned stage's filters to its device (sets the
    `accelerator` prop — must run BEFORE negotiation) and record the
    plan on the pipeline so `fuse_segments` splices plan-aware: members
    fuse within a stage, never across a cut. Stages the planner gave a
    TP group (`plan.tp[i] > 1`) get the `shards` prop instead of a
    device pin — the sharded backend leases its own chip group, so a
    single-chip `accelerator` pin would fight the mesh. Returns the
    number of elements pinned."""
    pinned = 0
    for si, (group, dev) in enumerate(zip(plan.stages, plan.devices)):
        tp = plan.tp_of(si)
        accel = accelerator_for(dev)
        for name in group:
            e = pipe.elements.get(name)
            if e is None:
                log.warning("apply_plan: element %r not in pipeline "
                            "(already fused?)", name)
                continue
            if tp > 1 and ("shards" in e.PROPS or "shards" in e.props):
                e.props["shards"] = tp
                pinned += 1
            elif "accelerator" in e.PROPS or "accelerator" in e.props:
                e.props["accelerator"] = accel
                pinned += 1
    pipe.segment_plan = plan
    return pinned
