"""Multi-host serving mesh: router tier + host agents over the query wire.

PR-10's supervisor proves every invariant we need on ONE host — crash
isolation, conservation-exact redelivery, graceful drain. This module
is the horizontal generalization (ROADMAP item 3, the reference's
"among-device AI" layer, arXiv 2101.06371): a `MeshRouter` fronts N
*remote* worker hosts, each an ordinary query server (a PR-10
`WorkerPool`, an `EchoServer`, any HELLO/DATA/RESULT/BUSY speaker)
bridged in by a `HostAgent`.

Control plane (edge/protocol.py types 10-14), riding the SAME TCP
connection as the data plane — deliberately, so a network partition
severs both at once and one liveness mechanism covers both:

- ``T_REGISTER``: the agent joins, advertising capacity, caps, zone,
  and resident ``store://`` versions. The ack carries the router's
  lease duration and epoch.
- ``T_LEASE``: heartbeat-renewed expiry. A *silent* host — not just a
  closed connection — is detected when its lease runs out, then
  **fenced**: its in-flight frames are re-offered to surviving hosts
  (``max_redeliver`` bound) or shed as ``BUSY(host_lost)``. Renewals
  carry the host's local admission counters, giving the router a
  mesh-wide conservation view (metrics per-host labels).
- ``T_SWAP``/``T_SWAP_ACK``: two-phase model swap broadcast with
  all-or-none epoch semantics; a host that acks prepare but misses
  commit is fenced, not left inconsistent (PR-10 semantics across
  machines).

Routing extends least-outstanding with locality (model residency, then
zone match, then load normalized by advertised capacity) and
typed-BUSY-aware retry: a host's BUSY for an admitted frame re-routes
it to a *different* host before the client ever sees the rejection.

Conservation is the same two invariants PR 9/10 enforce, now summed
across hosts: ``offered == admitted + rejected`` and ``admitted ==
replied + shed + depth + inflight`` hold at the router, and every
router reply maps to exactly one host reply (`stats()["hosts"]`).

Correlation: the router rewrites each frame's pts to a router-unique
rid before forwarding and restores the original on reply, so the
`HostAgent` stays a stateless byte forwarder and a host-local BUSY
(which carries only pts) is unambiguous mesh-wide. Parent-side hops
(dispatch with the host name, reoffer) are merged into the reply's
trace context exactly like the pool does — a cross-host redelivered
frame keeps ONE trace_id whose timeline shows both hosts.

Tested by traffic/netchaos.py (deterministic delay/drop/duplicate/
blackhole/slow-close proxy) and `run_against_mesh` (traffic/loadgen.py):
blackhole one host mid-flood → zero lost, conserved, recovery within
the lease budget. See docs/robustness.md for the failure-model matrix.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.edge import protocol as P
from nnstreamer_tpu.edge.query import QueryServer
from nnstreamer_tpu.edge.wire import decode_buffer, encode_buffer, peek_pts
from nnstreamer_tpu.runtime.tracing import get_trace_ctx
from nnstreamer_tpu.tensor.info import TensorsSpec

log = get_logger("serving.mesh")

READY = "READY"
FENCED = "FENCED"

#: meta key note — the router never stores a rid in meta: the pts
#: rewrite IS the correlation (see module docstring), so a pool-backed
#: host's own RID_META cannot collide with the mesh layer.


class _MeshRequest:
    """One admitted frame in flight somewhere in the mesh. Mirrors
    pool._Request: carries the re-encoded payload (pts=rid) so a
    re-offer after a host fence needs no surviving TensorBuffer."""

    __slots__ = ("rid", "client_id", "pts", "payload", "model",
                 "attempts", "busy_hosts", "t_sent", "traced", "hops")

    def __init__(self, rid: int, client_id, pts, payload: bytes,
                 model: Optional[str] = None, traced: bool = False):
        self.rid = rid
        self.client_id = client_id
        self.pts = pts
        self.payload = payload
        self.model = model
        self.attempts = 0             # deliveries so far
        self.busy_hosts: set = set()  # hosts that BUSYed this frame
        self.t_sent = 0.0
        self.traced = traced
        # parent-side hop records (dispatch/reoffer) merged into the
        # reply's trace context — the payload is already-encoded bytes
        # here, and a fenced host's own stamps are unreachable; the
        # router's dispatch record carries the host name instead
        self.hops: List[dict] = []

    def hop(self, name: str, **extra) -> None:
        if self.traced:
            rec = {"hop": name, "t": time.perf_counter(),
                   "pid": os.getpid()}
            rec.update(extra)
            self.hops.append(rec)


class _Host:
    """One registered worker host as the router sees it."""

    def __init__(self, name: str, conn: P.Connection, ad: dict,
                 window: int):
        self.name = name
        self.conn = conn
        self.capacity_rps = float(ad.get("capacity_rps") or 0.0)
        self.zone = str(ad.get("zone") or "")
        self.versions: Dict[str, list] = dict(ad.get("versions") or {})
        self.window = window
        self.state = READY
        self.outstanding: Dict[int, _MeshRequest] = {}
        now = time.monotonic()
        self.registered_t = now
        self.lease_deadline = now     # set by the router on register
        self.fence_cause: Optional[str] = None
        self.replied = 0
        self.busies = 0
        self.remote: Dict[str, Any] = {}   # lease-carried counters


class MeshRouter:
    """Router tier fronting N registered hosts (module docstring).

    The client plane is a plain `QueryServer` — same HELLO/DATA wire,
    same bounded admission — whose transport this router owns so the
    mesh control types (REGISTER/LEASE/SWAP) share the port.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 sid: int = 0,
                 dims: str = "", types: str = "",
                 max_pending: int = 64, max_inflight: int = 0,
                 shed_policy: str = "reject-newest",
                 lease_s: float = 2.0,
                 max_redeliver: int = 1,
                 busy_retry: int = 2,
                 per_host_window: int = 32,
                 send_timeout_s: float = 5.0,
                 frame_deadline_s: float = 30.0,
                 zone: str = "",
                 tracer=None,
                 tenants=None,
                 name: str = "mesh"):
        self.name = name
        self.zone = zone
        self.lease_s = float(lease_s)
        self.max_redeliver = max(0, max_redeliver)
        self.busy_retry = max(0, busy_retry)
        self.per_host_window = max(1, per_host_window)
        self.send_timeout_s = send_timeout_s
        self.frame_deadline_s = frame_deadline_s
        self.qs = QueryServer.get(sid)
        self.sid = sid
        if dims:
            self.qs.in_spec = TensorsSpec.from_strings(dims, types)
        self.qs.frames.configure(max_pending=max_pending,
                                 max_inflight=max_inflight,
                                 shed_policy=shed_policy)
        if tenants is not None:
            self.set_tenants(tenants)
        if tracer is not None:
            self.qs.tracer = tracer
        self._lock = threading.RLock()
        self._hosts: Dict[str, _Host] = {}
        self._conn_hosts: Dict[int, _Host] = {}
        self._pending: Deque[_MeshRequest] = deque()
        self._dispatch_evt = threading.Event()
        self._stop_evt = threading.Event()
        self._closed = False
        self._close_lock = threading.Lock()
        self._next_rid = 0
        self._swap_acks = None
        self.epoch = 0                # bumps on every committed swap
        self.reoffered = 0
        self.busy_reroutes = 0
        self.stale_results = 0
        #: (monotonic t, host name, kind, detail) — fence/register
        #: timeline; `run_against_mesh` derives detection latency here
        self.events: List[tuple] = []
        # the mesh control types share the query wire: this router owns
        # the transport and lends it to the QueryServer client plane
        self.server = P.MsgServer(host, port,
                                  on_message=self._on_message,
                                  on_disconnect=self._on_disconnect)
        self.qs.server = self.server
        self.qs.started.set()
        self._router = threading.Thread(
            target=self._route_loop, name=f"{name}-router", daemon=True)
        self._router.start()
        self._supervisor = threading.Thread(
            target=self._lease_loop, name=f"{name}-leases", daemon=True)
        self._supervisor.start()

    @property
    def port(self) -> int:
        return self.server.port

    # -- message plane -----------------------------------------------------
    def _on_message(self, conn: P.Connection, mtype: int,
                    payload: bytes) -> None:
        if mtype == P.T_REGISTER:
            self._on_register(conn, payload)
            return
        with self._lock:
            host = self._conn_hosts.get(conn.client_id)
        if host is not None:
            if mtype == P.T_LEASE:
                self._on_lease(host, payload)
            elif mtype == P.T_RESULT:
                self._on_host_result(host, payload)
            elif mtype == P.T_BUSY:
                self._on_host_busy(host, payload)
            elif mtype == P.T_SWAP_ACK:
                self._on_swap_ack(host, payload)
            return
        # client plane: HELLO handshake + DATA admission
        self.qs._on_message(conn, mtype, payload)

    def _on_disconnect(self, conn: P.Connection) -> None:
        with self._lock:
            host = self._conn_hosts.get(conn.client_id)
        if host is not None:
            self._fence(host, "conn_lost")

    # -- registration + leases ---------------------------------------------
    def _on_register(self, conn: P.Connection, payload: bytes) -> None:
        def nak(err: str) -> None:
            try:
                conn.send(P.T_REGISTER_ACK,
                          json.dumps({"ok": False, "error": err}).encode(),
                          timeout=self.send_timeout_s)
            except OSError:
                pass

        try:
            ad = json.loads(payload.decode())
            hname = str(ad["name"])
        except (ValueError, KeyError) as e:
            nak(f"bad register ad: {e}")
            return
        host_in = None
        if ad.get("dims"):
            try:
                host_in = TensorsSpec.from_strings(
                    ad["dims"], ad.get("types", ""))
            except ValueError as e:
                nak(f"bad caps in register ad: {e}")
                return
        with self._lock:
            if self.qs.in_spec is not None and host_in is not None and \
                    not self.qs.in_spec.is_compatible(host_in):
                pass_caps = False
            else:
                pass_caps = True
        if not pass_caps:
            nak("incompatible caps: host serves a different stream "
                "than this mesh routes")
            return
        with self._lock:
            old = self._hosts.get(hname)
        if old is not None and old.state == READY and old.conn is not conn:
            # a re-registration replaces the old incarnation: fence it
            # first so its in-flight frames are re-offered, not leaked
            self._fence(old, "re_registered")
        host = _Host(hname, conn, ad, self.per_host_window)
        host.lease_deadline = time.monotonic() + self.lease_s
        if old is not None:
            # per-host counters are monotone across incarnations: a
            # rejoining host keeps its totals, so the cross-host
            # conservation sum (Σ replied == router replied) survives
            # a fence + rejoin cycle
            host.replied = old.replied
            host.busies = old.busies
        with self._lock:
            if self.qs.in_spec is None and host_in is not None:
                self.qs.in_spec = host_in
            if self.qs.out_spec is None and ad.get("out_dims"):
                try:
                    self.qs.out_spec = TensorsSpec.from_strings(
                        ad["out_dims"], ad.get("out_types", ""))
                except ValueError:
                    pass
            self._hosts[hname] = host
            self._conn_hosts[conn.client_id] = host
        self.events.append((time.monotonic(), hname, "register", ""))
        log.info("mesh %s: host %s registered (capacity %.1f rps, "
                 "zone %r, %d model(s))", self.name, hname,
                 host.capacity_rps, host.zone, len(host.versions))
        try:
            conn.send(P.T_REGISTER_ACK, json.dumps({
                "ok": True, "name": hname, "lease_s": self.lease_s,
                "epoch": self.epoch}).encode(),
                timeout=self.send_timeout_s)
        except OSError:
            self._fence(host, "register_ack_failed")
            return
        self._dispatch_evt.set()

    def _on_lease(self, host: _Host, payload: bytes) -> None:
        try:
            body = json.loads(payload.decode()) if payload else {}
        except ValueError:
            body = {}
        with self._lock:
            if host.state != READY:
                ok = False
            else:
                ok = True
                host.lease_deadline = time.monotonic() + self.lease_s
                counters = body.get("counters")
                if isinstance(counters, dict):
                    host.remote = counters
        try:
            host.conn.send(P.T_LEASE, json.dumps(
                {"ok": ok, "epoch": self.epoch}).encode(),
                timeout=self.send_timeout_s)
        except OSError:
            self._fence(host, "lease_ack_failed")

    # -- host replies ------------------------------------------------------
    def _on_host_result(self, host: _Host, payload: bytes) -> None:
        rid = peek_pts(payload)
        if rid is None:
            log.warning("mesh %s: host %s returned an uncorrelatable "
                        "frame", self.name, host.name)
            return
        with self._lock:
            req = host.outstanding.pop(rid, None)
        if req is None:
            # already re-offered after a fence / shed at close — the
            # admission accounting closed this request elsewhere
            with self._lock:
                self.stale_results += 1
            return
        host.replied += 1
        try:
            buf, _ = decode_buffer(payload)
        except ValueError as e:
            log.warning("mesh %s: host %s returned a corrupt frame for "
                        "pts=%s: %s", self.name, host.name, req.pts, e)
            self.qs.frames.note_failed("host_error")
            self.qs.send_busy(req.client_id, req.pts, "host_error")
            return
        if req.hops:
            # merge the router-side hops (dispatch/reoffer) into the
            # reply's trace context, in time order: one timeline per
            # trace_id even across a cross-host redelivery
            ctx = get_trace_ctx(buf.meta)
            if ctx is not None:
                ctx["hops"].extend(req.hops)
                ctx["hops"].sort(
                    key=lambda h: h.get("t", 0.0)
                    if isinstance(h, dict) else 0.0)
        self.qs.reply(int(req.client_id),
                      buf.with_tensors(buf.tensors, pts=req.pts))
        self._dispatch_evt.set()

    def _on_host_busy(self, host: _Host, payload: bytes) -> None:
        """A host refused an admitted frame (its local admission bound,
        or its agent's forward failed). Retry on a DIFFERENT host while
        one exists; only then surface the rejection to the client."""
        try:
            body = json.loads(payload.decode())
            rid = int(body["pts"])
        except (ValueError, KeyError, TypeError):
            log.warning("mesh %s: uncorrelatable BUSY from host %s",
                        self.name, host.name)
            return
        cause = str(body.get("cause") or "busy")
        with self._lock:
            req = host.outstanding.pop(rid, None)
            if req is None:
                return
            host.busies += 1
            req.busy_hosts.add(host.name)
            alternative = any(
                h.state == READY and h.name not in req.busy_hosts
                for h in self._hosts.values())
            retry = alternative and \
                len(req.busy_hosts) <= self.busy_retry and \
                not self._stop_evt.is_set()
            if retry:
                self.busy_reroutes += 1
                self._pending.appendleft(req)
        if retry:
            req.hop("reoffer", host=host.name, cause=f"host_busy:{cause}",
                    attempt=req.attempts)
            self._dispatch_evt.set()
            return
        self.qs.frames.note_failed("host_busy")
        self.qs.send_busy(req.client_id, req.pts, f"host_busy:{cause}")
        self._dispatch_evt.set()

    def _on_swap_ack(self, host: _Host, payload: bytes) -> None:
        try:
            body = json.loads(payload.decode())
        except ValueError:
            return
        with self._lock:
            acks = self._swap_acks
        if acks is not None:
            acks.put((host.name, body.get("phase"),
                      bool(body.get("ok")), body.get("error")))

    # -- routing -----------------------------------------------------------
    def _route_loop(self) -> None:
        import queue as _queue

        while not self._stop_evt.is_set():
            req = None
            with self._lock:
                if self._pending:
                    req = self._pending.popleft()
            if req is None:
                try:
                    buf = self.qs.frames.get(timeout=0.05)
                except _queue.Empty:
                    continue
                if buf is None:       # teardown sentinel
                    continue
                req = self._admit(buf)
            if not self._dispatch(req):
                with self._lock:
                    self._pending.appendleft(req)
                self._dispatch_evt.wait(0.05)
                self._dispatch_evt.clear()

    def _admit(self, buf) -> _MeshRequest:
        with self._lock:
            self._next_rid += 1
            rid = self._next_rid
        client_id = buf.meta.pop("client_id", None)
        model = buf.meta.get("model")
        # pts := rid before encoding — the correlation id every host
        # echoes back (results and BUSYs both), restored on reply
        wire = encode_buffer(buf.with_tensors(buf.tensors, pts=rid))
        return _MeshRequest(
            rid, client_id, buf.pts, wire,
            model=model if isinstance(model, str) else None,
            traced=get_trace_ctx(buf.meta) is not None)

    def _host_key(self, h: _Host, req: _MeshRequest):
        """Routing preference: model residency, then zone locality,
        then least-outstanding normalized by advertised capacity."""
        resident = 0 if (req.model and req.model in h.versions) else 1
        local = 0 if (self.zone and h.zone == self.zone) else 1
        weight = h.capacity_rps if h.capacity_rps > 0 else 1.0
        return (resident, local, len(h.outstanding) / weight, h.name)

    def _dispatch(self, req: _MeshRequest) -> bool:
        with self._lock:
            ready = [h for h in self._hosts.values()
                     if h.state == READY
                     and len(h.outstanding) < h.window]
            candidates = [h for h in ready
                          if h.name not in req.busy_hosts]
            if not candidates:
                # every roomy host already BUSYed this frame: retrying
                # one beats stalling the router forever
                candidates = ready
            if not candidates:
                return False
            host = min(candidates, key=lambda h: self._host_key(h, req))
            req.attempts += 1
            req.t_sent = time.monotonic()
            host.outstanding[req.rid] = req
        req.hop("dispatch", host=host.name, attempt=req.attempts)
        try:
            host.conn.send(P.T_DATA, req.payload,
                           timeout=self.send_timeout_s)
        except OSError:
            # host gone between pick and send: undo, fence, re-offer
            # through the normal path
            with self._lock:
                host.outstanding.pop(req.rid, None)
                req.attempts -= 1
            if req.hops:
                req.hops.pop()
            self._fence(host, "send_failed")
            return False
        return True

    # -- liveness ----------------------------------------------------------
    def _lease_loop(self) -> None:
        poll = max(0.02, min(0.25, self.lease_s / 4.0))
        while not self._stop_evt.wait(poll):
            now = time.monotonic()
            with self._lock:
                hosts = list(self._hosts.values())
            for h in hosts:
                with self._lock:
                    if h.state != READY:
                        continue
                    expired = now > h.lease_deadline
                    oldest = min((r.t_sent
                                  for r in h.outstanding.values()),
                                 default=None)
                if expired:
                    self._fence(h, "lease_expired")
                elif oldest is not None and \
                        now - oldest > self.frame_deadline_s:
                    # a renewing lease with wedged frames: the host is
                    # alive but not serving — fence it anyway (remote
                    # sibling of the pool's frame-deadline kill)
                    self._fence(h, "frame_deadline")

    def _fence(self, host: _Host, cause: str) -> None:
        """Cut a host out of the mesh and settle its in-flight frames:
        re-offer (≤ max_redeliver, while another host could serve) or
        shed as BUSY(host_lost). Conservation holds exactly through the
        fence — nothing ends neither-replied-nor-rejected."""
        with self._lock:
            if host.state != READY:
                return
            host.state = FENCED
            host.fence_cause = cause
            orphans = list(host.outstanding.values())
            host.outstanding.clear()
            self._conn_hosts.pop(host.conn.client_id, None)
            live_possible = any(h.state == READY
                                for h in self._hosts.values())
        self.events.append((time.monotonic(), host.name, "fence", cause))
        log.warning("mesh %s: fencing host %s (%s), %d frame(s) "
                    "in flight", self.name, host.name, cause,
                    len(orphans))
        try:
            host.conn.close()
        except OSError:
            pass
        for req in orphans:
            if req.attempts <= self.max_redeliver and live_possible \
                    and not self._stop_evt.is_set():
                # re-offer: still `inflight` in admission accounting —
                # nothing changes until it is replied or shed
                req.hop("reoffer", host=host.name, cause=cause,
                        attempt=req.attempts)
                with self._lock:
                    self._pending.appendleft(req)
                self.reoffered += 1
            else:
                self.qs.frames.note_failed("host_lost")
                self.qs.send_busy(req.client_id, req.pts, "host_lost")
        self._dispatch_evt.set()

    # -- swap --------------------------------------------------------------
    def swap(self, name: str, version=None,
             timeout_s: float = 30.0) -> dict:
        """Two-phase model swap across every ready host. All-or-none:
        any prepare failure aborts everywhere and the mesh epoch does
        not move; a host that acked prepare but failed commit is FENCED
        (its frames re-offered) rather than left serving a version its
        siblings do not."""
        import queue as _queue

        with self._lock:
            targets = [h for h in self._hosts.values()
                       if h.state == READY]
            if not targets:
                return {"ok": False, "error": "no ready hosts",
                        "epoch": self.epoch}
            acks: "_queue.Queue" = _queue.Queue()
            self._swap_acks = acks

        def phase(ph: str, hosts) -> Dict[str, tuple]:
            got: Dict[str, tuple] = {}
            body = json.dumps({"phase": ph, "model": name,
                               "version": version,
                               "epoch": self.epoch}).encode()
            for h in hosts:
                try:
                    h.conn.send(P.T_SWAP, body,
                                timeout=self.send_timeout_s)
                except OSError:
                    got[h.name] = (False, "host died mid-swap")
            deadline = time.monotonic() + timeout_s
            while len(got) < len(hosts):
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                try:
                    hname, ph_got, ok, err = acks.get(timeout=remain)
                except _queue.Empty:
                    break
                if ph_got == ph:
                    got[hname] = (ok, err)
            for h in hosts:
                got.setdefault(h.name, (False, f"no {ph} ack"))
            return got

        try:
            prep = phase("prepare", targets)
            report = {"name": name, "version": version,
                      "hosts": {h: {"prepare_ok": ok, "error": err}
                                for h, (ok, err) in prep.items()}}
            if not all(ok for ok, _ in prep.values()):
                phase("abort", targets)
                report["ok"] = False
                report["epoch"] = self.epoch
                return report
            comm = phase("commit", targets)
            for h, (ok, err) in comm.items():
                report["hosts"][h]["commit_ok"] = ok
                if err:
                    report["hosts"][h]["error"] = err
            report["ok"] = all(ok for ok, _ in comm.values())
            if report["ok"]:
                with self._lock:
                    self.epoch += 1
                    for h in targets:
                        vs = h.versions.setdefault(name, [])
                        if version is not None and version not in vs:
                            vs.append(version)
                report["epoch"] = self.epoch
            else:
                report["epoch"] = self.epoch
                for h in targets:
                    if not comm.get(h.name, (True, None))[0]:
                        self._fence(h, "swap_commit_failed")
            return report
        finally:
            with self._lock:
                self._swap_acks = None

    # -- introspection -----------------------------------------------------
    def wait_hosts(self, n: int, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.ready_hosts() >= n:
                return True
            time.sleep(0.01)
        return False

    def ready_hosts(self) -> int:
        with self._lock:
            return sum(1 for h in self._hosts.values()
                       if h.state == READY)

    def depth_probe(self) -> int:
        return self.qs.frames.depth

    def set_tenants(self, table) -> None:
        """Install (or clear, with None) a weighted-fair `TenantTable`
        on the router's admission queue — the mesh twin of
        `PooledQueryServer(tenants=...)`. The class resolved at offer
        rides the frame's meta through the host round-trip (workers
        echo meta), so the reply settles against the right class and
        the per-class conservation books close across hosts."""
        self.qs.frames.set_tenants(table)

    def admission_counters(self) -> dict:
        return self.qs.frames.counters()

    def stats(self) -> dict:
        now = time.monotonic()
        with self._lock:
            hosts = [{
                "host": h.name,
                "state": h.state,
                "zone": h.zone,
                "capacity_rps": h.capacity_rps,
                "outstanding": len(h.outstanding),
                "replied": h.replied,
                "busies": h.busies,
                "lease_age_ms": round(1e3 * max(
                    0.0, now - (h.lease_deadline - self.lease_s)), 1),
                "fence_cause": h.fence_cause,
                "versions": dict(h.versions),
                "remote": dict(h.remote),
            } for h in self._hosts.values()]
            mesh = {
                "hosts": len(self._hosts),
                "ready": sum(1 for h in self._hosts.values()
                             if h.state == READY),
                "fenced": sum(1 for h in self._hosts.values()
                              if h.state == FENCED),
                "epoch": self.epoch,
                "reoffered": self.reoffered,
                "busy_reroutes": self.busy_reroutes,
                "stale_results": self.stale_results,
                "pending": len(self._pending),
                "lease_s": self.lease_s,
            }
        return {"mesh": mesh, "hosts": hosts,
                "admission": self.qs.frames.counters()}

    # -- drain / close -----------------------------------------------------
    def close(self) -> None:
        """Graceful drain, mirroring WorkerPool.close: stop admitting,
        BUSY the undispatched, settle in-flight against live hosts
        within a short budget, shed the rest, then transport down."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        for v in self.qs.frames.shed_remaining("shutdown"):
            if v is not None:
                self.qs.send_busy(v.meta.get("client_id"), v.pts,
                                  "shutdown")
        self._stop_evt.set()
        self._dispatch_evt.set()
        if self._router is not None:
            self._router.join(timeout=5)
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
        with self._lock:
            undispatched = list(self._pending)
            self._pending.clear()
        for req in undispatched:
            self.qs.frames.note_failed("shutdown")
            self.qs.send_busy(req.client_id, req.pts, "shutdown")
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with self._lock:
                if not any(h.outstanding for h in self._hosts.values()):
                    break
            time.sleep(0.02)
        abandoned: List[_MeshRequest] = []
        with self._lock:
            for h in self._hosts.values():
                abandoned.extend(h.outstanding.values())
                h.outstanding.clear()
        for req in abandoned:
            self.qs.frames.note_failed("shutdown")
            self.qs.send_busy(req.client_id, req.pts, "shutdown")
        self.qs.stop()   # also closes self.server (shared transport)


class HostAgent:
    """Bridges one local query server into a mesh: dials the router,
    REGISTERs, keeps the lease alive, and forwards frames byte-for-byte
    (the router's pts=rid rewrite keeps this layer stateless). The
    registration connection IS the data channel — a partition severs
    both, so lease expiry is the single liveness truth.
    """

    def __init__(self, router_host: str, router_port: int, *,
                 name: str,
                 local_port: int,
                 local_host: str = "127.0.0.1",
                 dims: str, types: str,
                 capacity_rps: float = 0.0,
                 zone: str = "",
                 versions: Optional[Dict[str, list]] = None,
                 counters_fn: Optional[Callable[[], dict]] = None,
                 on_swap: Optional[Callable] = None,
                 connect_timeout_s: Optional[float] = None,
                 reconnect: bool = True,
                 reconnect_backoff_s: float = 0.2,
                 reconnect_backoff_max_s: float = 2.0):
        self.name = name
        self.router_host, self.router_port = router_host, router_port
        self.local_host, self.local_port = local_host, local_port
        self.dims, self.types = dims, types
        self.capacity_rps = capacity_rps
        self.zone = zone
        self.versions = dict(versions or {})
        self.counters_fn = counters_fn
        self.on_swap = on_swap
        self.connect_timeout_s = connect_timeout_s
        self.reconnect = reconnect
        self.reconnect_backoff_s = reconnect_backoff_s
        self.reconnect_backoff_max_s = reconnect_backoff_max_s
        self.lease_s = 2.0            # overwritten by REGISTER_ACK
        self.out_dims = ""
        self.out_types = ""
        self.registered = threading.Event()
        self._hello_ok = threading.Event()
        self._stop_evt = threading.Event()
        self._lock = threading.Lock()
        self._local: Optional[P.MsgClient] = None
        self._router: Optional[P.MsgClient] = None
        self._lease_thread: Optional[threading.Thread] = None
        self._reconnector: Optional[threading.Thread] = None
        self.forwarded = 0
        self.forward_failures = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self, timeout_s: float = 10.0) -> "HostAgent":
        self._connect()
        if not self.registered.wait(timeout_s):
            self.stop()
            raise StreamError(
                f"host agent {self.name}: no REGISTER_ACK from "
                f"{self.router_host}:{self.router_port} within "
                f"{timeout_s}s")
        self._lease_thread = threading.Thread(
            target=self._lease_loop, name=f"mesh-agent-{self.name}",
            daemon=True)
        self._lease_thread.start()
        return self

    def _connect(self) -> None:
        """Dial local backend then router, HELLO + REGISTER. Raises on
        hard failure (caller or reconnect loop handles retry)."""
        local = P.MsgClient(
            self.local_host, self.local_port,
            on_message=self._on_local,
            on_close=self._schedule_reconnect,
            connect_timeout=self.connect_timeout_s)
        self._hello_ok.clear()
        local.send(P.T_HELLO, json.dumps(
            {"dims": self.dims, "types": self.types}).encode())
        if not self._hello_ok.wait(5.0):
            local.close()
            raise StreamError(
                f"host agent {self.name}: local server "
                f"{self.local_host}:{self.local_port} rejected HELLO")
        router = P.MsgClient(
            self.router_host, self.router_port,
            on_message=self._on_router,
            on_close=self._schedule_reconnect,
            connect_timeout=self.connect_timeout_s)
        with self._lock:
            old_local, self._local = self._local, local
            old_router, self._router = self._router, router
        for old in (old_local, old_router):
            if old is not None and old.alive:
                old.close()
        self._send_register()

    def _send_register(self) -> None:
        self.registered.clear()
        ad = {"name": self.name, "capacity_rps": self.capacity_rps,
              "dims": self.dims, "types": self.types,
              "out_dims": self.out_dims, "out_types": self.out_types,
              "zone": self.zone, "versions": self.versions}
        self._router.send(P.T_REGISTER, json.dumps(ad).encode())

    def _schedule_reconnect(self) -> None:
        """Either leg dropped: tear down and (optionally) rejoin. The
        router side fences us on its own — this loop is how a healed
        partition turns back into a READY host."""
        if self._stop_evt.is_set() or not self.reconnect:
            return
        with self._lock:
            if self._reconnector is not None and \
                    self._reconnector.is_alive():
                return
            self._reconnector = threading.Thread(
                target=self._reconnect_loop,
                name=f"mesh-agent-{self.name}-rejoin", daemon=True)
            self._reconnector.start()

    def _reconnect_loop(self) -> None:
        backoff = self.reconnect_backoff_s
        while not self._stop_evt.is_set():
            time.sleep(backoff)
            backoff = min(backoff * 2, self.reconnect_backoff_max_s)
            try:
                self._connect()
                return
            except StreamError as e:
                log.info("host agent %s: rejoin attempt failed: %s",
                         self.name, e)

    # -- router-side messages ----------------------------------------------
    def _on_router(self, mtype: int, payload: bytes) -> None:
        if mtype == P.T_DATA:
            with self._lock:
                local = self._local
            try:
                if local is None:
                    raise StreamError("no local backend")
                local.send(P.T_DATA, payload)
                self.forwarded += 1
            except StreamError:
                self.forward_failures += 1
                self._busy_router(peek_pts(payload),
                                  "host_forward_failed")
        elif mtype == P.T_REGISTER_ACK:
            try:
                body = json.loads(payload.decode())
            except ValueError:
                return
            if body.get("ok"):
                self.lease_s = float(body.get("lease_s") or self.lease_s)
                self.registered.set()
            else:
                log.error("host agent %s: registration refused: %s",
                          self.name, body.get("error"))
        elif mtype == P.T_LEASE:
            try:
                body = json.loads(payload.decode())
            except ValueError:
                return
            if not body.get("ok"):
                # the router no longer knows us (fenced while the TCP
                # connection survived): re-register on this connection
                try:
                    self._send_register()
                except StreamError:
                    pass
        elif mtype == P.T_SWAP:
            self._handle_swap(payload)

    def _busy_router(self, rid: Optional[int], cause: str) -> None:
        with self._lock:
            router = self._router
        if router is None or rid is None:
            return
        try:
            router.send(P.T_BUSY, json.dumps(
                {"pts": rid, "cause": cause, "queue_depth": 0,
                 "retry_after_ms": 250.0}).encode())
        except StreamError:
            pass

    def _handle_swap(self, payload: bytes) -> None:
        try:
            body = json.loads(payload.decode())
            phase = body["phase"]
        except (ValueError, KeyError):
            return
        model, version = body.get("model"), body.get("version")
        ok, err = True, None
        if self.on_swap is not None:
            try:
                res = self.on_swap(phase, model, version)
                if isinstance(res, tuple):
                    ok, err = bool(res[0]), res[1]
                else:
                    ok = bool(res)
            except Exception as e:        # noqa: BLE001 — ack the error
                ok, err = False, f"{type(e).__name__}: {e}"
        if ok and phase == "commit" and version is not None:
            self.versions.setdefault(str(model), [])
            if version not in self.versions[str(model)]:
                self.versions[str(model)].append(version)
        with self._lock:
            router = self._router
        if router is None:
            return
        try:
            router.send(P.T_SWAP_ACK, json.dumps(
                {"phase": phase, "ok": ok, "error": err,
                 "name": self.name}).encode())
        except StreamError:
            pass

    # -- local-side messages -----------------------------------------------
    def _on_local(self, mtype: int, payload: bytes) -> None:
        if mtype in (P.T_RESULT, P.T_BUSY):
            with self._lock:
                router = self._router
            if router is None:
                return
            try:
                router.send(mtype, payload)
            except StreamError:
                pass                  # router gone: reconnect loop owns it
        elif mtype == P.T_HELLO_ACK:
            try:
                body = json.loads(payload.decode())
                self.out_dims = body.get("dims", "")
                self.out_types = body.get("types", "")
            except ValueError:
                pass
            self._hello_ok.set()
        elif mtype == P.T_HELLO_NAK:
            log.error("host agent %s: local server refused HELLO: %s",
                      self.name, payload.decode(errors="replace"))

    # -- lease loop --------------------------------------------------------
    def _lease_loop(self) -> None:
        """Renew at 3x the expiry rate — two consecutive losses still
        leave slack before the router fences us."""
        while not self._stop_evt.wait(max(0.05, self.lease_s / 3.0)):
            with self._lock:
                router = self._router
            if router is None or not router.alive:
                continue              # reconnect loop owns recovery
            body: Dict[str, Any] = {"name": self.name}
            if self.counters_fn is not None:
                try:
                    body["counters"] = self.counters_fn()
                except Exception:     # noqa: BLE001 — lease must not die
                    pass
            try:
                router.send(P.T_LEASE, json.dumps(body).encode())
            except StreamError:
                continue              # on_close schedules the rejoin

    def stop(self) -> None:
        self._stop_evt.set()
        if self._lease_thread is not None:
            self._lease_thread.join(timeout=2)
        with self._lock:
            local, self._local = self._local, None
            router, self._router = self._router, None
        for c in (router, local):
            if c is not None:
                c.close()


def pool_join(pqs, router_host: str, router_port: int, *,
              name: str, zone: str = "", **kw) -> HostAgent:
    """Join a `PooledQueryServer` to a mesh: the `serve --join` path.
    Wires the agent's ad (caps, capacity, resident versions), its lease
    counters, and a two-phase swap handler that defers the real work to
    the pool's own prepare/commit broadcast at mesh commit time — a
    commit failure then fences this host, which is exactly the
    "prepared but inconsistent" contract."""
    def on_swap(phase, model, version):
        if phase != "commit":
            return True               # validation happens pool-side
        rep = pqs.swap(model, version)
        return bool(rep.get("ok")), rep.get("error")

    spec = pqs.pool.spec
    cap = pqs.capacity_rps
    return HostAgent(
        router_host, router_port,
        name=name,
        local_port=pqs.port,
        dims=spec.dims, types=spec.types,
        capacity_rps=0.0 if cap == float("inf") else cap,
        zone=zone,
        versions=pqs.pool.resident_versions(),
        counters_fn=pqs.admission_counters,
        on_swap=on_swap,
        **kw).start()
