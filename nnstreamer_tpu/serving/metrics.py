"""Pull-based metrics plane: typed series → Prometheus text → HTTP.

The third leg of the observability tentpole (docs/observability.md):
`metrics_snapshot()` flattens everything the runtime already counts —
tracer histograms/counters (runtime/tracing.py), admission conservation
counters (traffic/admission.py), pool supervision stats
(serving/pool.py), and any extra numeric gauges the caller owns — into
typed counter/gauge/histogram series; `render_prometheus()` turns them
into the text exposition format; `MetricsServer` serves them over a
tiny stdlib HTTP endpoint (``GET /metrics``); `top_view()` scrapes any
such endpoint and renders a live terminal table (`python -m
nnstreamer_tpu top`).

Monotonicity contract (pinned by tests/test_metrics.py): every series
typed ``counter`` here is backed by a cumulative source — admission
totals, pool lifetime counters, the tracer's delta-merged child
counters and fixed-bound cumulative histograms — so two consecutive
scrapes under load NEVER see a counter or histogram bucket decrease.
Anything windowed (ring length, queue depth, percentiles) is typed
``gauge``.

The HTTP handler is deliberately dependency-free (http.server from the
stdlib) and runs entirely host-side: it reads counters under their own
locks and never touches device state, so it sits outside the
device-adjacent sync rules nnlint enforces (NNL002 scope note in
analysis/rules.py).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from nnstreamer_tpu.core.log import get_logger

log = get_logger("serving.metrics")

#: one exposition series: type is counter | gauge | histogram; samples
#: are (labels, value) pairs — value is a float for counter/gauge and a
#: {"bounds", "counts", "sum", "count"} dict (tracing._Hist.snapshot
#: layout, per-bucket counts) for histogram
Series = Dict[str, Any]

_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _series(name: str, typ: str, help_: str,
            samples: List[Tuple[Dict[str, str], Any]]) -> Series:
    return {"name": name, "type": typ, "help": help_, "samples": samples}


def metrics_snapshot(tracer=None, admission: Optional[dict] = None,
                     pool: Optional[dict] = None,
                     mesh: Optional[dict] = None,
                     replicas: Optional[Dict[str, dict]] = None,
                     segments: Optional[Dict[str, dict]] = None,
                     autotune: Optional[dict] = None,
                     llm: Optional[Dict[str, dict]] = None,
                     devprof: Optional[dict] = None,
                     extra: Optional[Dict[str, float]] = None,
                     namespace: str = "nns") -> List[Series]:
    """Flatten runtime state into typed series.

    tracer     — a runtime.tracing.Tracer (ignored when None/inactive)
    admission  — AdmissionQueue.counters() snapshot
    replicas   — {filter: ReplicaSet.stats()} (serving/placement.py):
                 per-chip invoke/error counters + queue-depth/up gauges
                 labelled by device; Σ nns_replica_invokes_total over
                 devices == that filter's invoke count — the replica
                 conservation check, verifiable from one scrape.
                 ShardedReplicaSet stats (rows carrying "group") emit
                 the nns_shard_* family on top: per-group invoke/up/
                 adopted-epoch series plus the shard width and the
                 chip-lease ledger, with the same Σ-over-groups ==
                 filter-invokes conservation contract
    segments   — {plan: SegmentPlan.report()}: per-stage profiled time
                 (labelled stage/device) + the plan's bubble fraction
    pool       — WorkerPool.stats() snapshot
    mesh       — MeshRouter.stats() snapshot: per-host labelled series
                 (the `host` label) + mesh-wide gauges; the router's
                 own admission counters ride the `admission` arg, so
                 Σ nns_host_replied_total == nns_admission_replied_total
                 is checkable from one scrape
    autotune   — AutoTuner.stats() snapshot (serving/autotune.py):
                 cumulative decision counters labelled knob/outcome
                 plus current-knob and SLO gauges, so every applied
                 decision is visible as an nns_autotune_* series
    llm        — {element: TensorLLM.extra_stats()} (or bare
                 LLMEngine.stats()): per-kernel attention invoke
                 counters labelled {element, kernel}, the fallback
                 counter, token/finished totals and the selected-kernel
                 info gauge — one scrape proves which attention path
                 served
    devprof    — DeviceProfiler.stats() (runtime/devprof.py): the
                 device performance plane.  Cost-registry rows become
                 nns_jit_* (flops / bytes accessed / compile seconds
                 per {filter, bucket}); invoke reservoirs become
                 nns_invoke_* (MFU, achieved TFLOP/s, cumulative
                 sampled seconds — Σ nns_invoke_seconds_total is
                 reconcilable against the tracer's proctime histograms
                 from the same scrape); the HBM ledger becomes
                 nns_device_hbm_* labelled {device, kind} with a
                 headroom gauge per device
    extra      — arbitrary numeric gauges {name: value} the caller owns
                 (backend cache sizes, build info, …)
    """
    ns = namespace
    out: List[Series] = []

    if admission:
        for key, help_ in (("offered", "requests seen at the door"),
                           ("admitted", "requests admitted"),
                           ("replied", "requests answered with RESULT")):
            out.append(_series(f"{ns}_admission_{key}_total", "counter",
                               f"admission: {help_}",
                               [({}, float(admission[key]))]))
        out.append(_series(
            f"{ns}_admission_rejected_total", "counter",
            "at-the-door refusals by cause (BUSY, never queued)",
            [({"cause": c}, float(v))
             for c, v in sorted(admission["rejected"].items())] or
            [({"cause": "none"}, 0.0)]))
        out.append(_series(
            f"{ns}_admission_shed_total", "counter",
            "post-admission sheds by cause (BUSY after queueing)",
            [({"cause": c}, float(v))
             for c, v in sorted(admission["shed"].items())] or
            [({"cause": "none"}, 0.0)]))
        out.append(_series(f"{ns}_admission_depth", "gauge",
                           "requests queued right now",
                           [({}, float(admission["depth"]))]))
        out.append(_series(f"{ns}_admission_inflight", "gauge",
                           "requests dequeued but not yet replied",
                           [({}, float(admission["inflight"]))]))
        out.append(_series(f"{ns}_admission_depth_peak", "gauge",
                           "admission queue high-water mark",
                           [({}, float(admission["depth_peak"]))]))
        classes = admission.get("classes")
        if classes:
            # per-tenant conservation ledger: same shape as the global
            # admission counters, labelled by tenant class. Summing any
            # family over the tenant label reproduces the global series
            # — the per-class invariant is checkable from one scrape.
            # Label cardinality is bounded by admission-time tenant
            # name validation (serving/tenancy.validate_tenant_name).
            for key, help_ in (
                    ("offered", "requests seen at the door"),
                    ("admitted", "requests admitted"),
                    ("replied", "requests answered with RESULT")):
                out.append(_series(
                    f"{ns}_tenant_{key}_total", "counter",
                    f"per-tenant admission: {help_}",
                    [({"tenant": t}, float(c[key]))
                     for t, c in sorted(classes.items())]))
            out.append(_series(
                f"{ns}_tenant_rejected_total", "counter",
                "per-tenant at-the-door refusals by cause",
                [({"tenant": t, "cause": cause}, float(v))
                 for t, c in sorted(classes.items())
                 for cause, v in sorted(c["rejected"].items())] or
                [({"tenant": "none", "cause": "none"}, 0.0)]))
            out.append(_series(
                f"{ns}_tenant_shed_total", "counter",
                "per-tenant post-admission sheds by cause",
                [({"tenant": t, "cause": cause}, float(v))
                 for t, c in sorted(classes.items())
                 for cause, v in sorted(c["shed"].items())] or
                [({"tenant": "none", "cause": "none"}, 0.0)]))
            out.append(_series(
                f"{ns}_tenant_depth", "gauge",
                "per-tenant requests queued right now",
                [({"tenant": t}, float(c["depth"]))
                 for t, c in sorted(classes.items())]))
            out.append(_series(
                f"{ns}_tenant_inflight", "gauge",
                "per-tenant requests dequeued but not yet replied",
                [({"tenant": t}, float(c["inflight"]))
                 for t, c in sorted(classes.items())]))
            out.append(_series(
                f"{ns}_tenant_weight", "gauge",
                "per-tenant WFQ weight (scheduling share)",
                [({"tenant": t}, float(c["weight"]))
                 for t, c in sorted(classes.items())]))

    if pool:
        p = pool.get("pool", {})
        for key, help_ in (("restarts", "worker restarts"),
                           ("kills", "supervisor kills (hang/deadline)"),
                           ("reoffered", "frames redelivered after a "
                                         "worker death")):
            out.append(_series(f"{ns}_pool_{key}_total", "counter",
                               f"pool: {help_}",
                               [({}, float(p.get(key, 0)))]))
        # same-host shared-memory transport (serving/shm.py): frames/
        # bytes moved over the rings + hops that fell back to pickle.
        # shm_frames + shm_fallbacks == dispatches + replies attempted,
        # so the lane split is checkable from one scrape.
        for key, help_ in (
                ("shm_frames", "payload hops served over the "
                               "shared-memory ring lane"),
                ("shm_bytes", "payload bytes moved over the "
                              "shared-memory rings"),
                ("shm_fallbacks", "hops that fell back to the pickle "
                                  "pipe lane (ring full / shm "
                                  "unavailable)")):
            out.append(_series(f"{ns}_{key}_total", "counter",
                               f"pool shm transport: {help_}",
                               [({}, float(p.get(key, 0)))]))
        for key, help_ in (("live", "live workers"),
                           ("ready", "ready workers"),
                           ("pending", "router backlog"),
                           ("degraded", "slots disabled by the circuit"),
                           ("epoch", "model swap epoch")):
            out.append(_series(f"{ns}_pool_{key}", "gauge",
                               f"pool: {help_}",
                               [({}, float(p.get(key, 0)))]))
        workers = pool.get("workers", [])
        if workers:
            out.append(_series(
                f"{ns}_worker_replied_total", "counter",
                "per-worker goodput (frames answered)",
                [({"wid": str(w["wid"])}, float(w["replied"]))
                 for w in workers]))
            out.append(_series(
                f"{ns}_worker_restarts_total", "counter",
                "per-worker slot restarts",
                [({"wid": str(w["wid"])}, float(w["restarts"]))
                 for w in workers]))
            out.append(_series(
                f"{ns}_worker_inflight", "gauge",
                "frames dispatched to the worker, unanswered",
                [({"wid": str(w["wid"])}, float(w["inflight"]))
                 for w in workers]))
            out.append(_series(
                f"{ns}_worker_up", "gauge",
                "1 when the slot is ready, else 0 (state label says "
                "why)",
                [({"wid": str(w["wid"]), "state": w["state"]},
                  1.0 if w["state"] == "ready" else 0.0)
                 for w in workers]))

    if replicas:
        flat = [(f, r) for f, st in sorted(replicas.items())
                for r in st.get("replicas", [])]
        if flat:
            out.append(_series(
                f"{ns}_replica_invokes_total", "counter",
                "per-chip replica invokes; summed over devices this "
                "equals the owning filter's invoke count — the replica "
                "conservation check",
                [({"filter": f, "device": str(r["device"])},
                  float(r["invokes"])) for f, r in flat]))
            out.append(_series(
                f"{ns}_replica_errors_total", "counter",
                "per-chip replica invoke failures",
                [({"filter": f, "device": str(r["device"])},
                  float(r["errors"])) for f, r in flat]))
            out.append(_series(
                f"{ns}_replica_queue_depth", "gauge",
                "frames queued on the chip's bounded queue right now",
                [({"filter": f, "device": str(r["device"])},
                  float(r["queue_depth"])) for f, r in flat]))
            out.append(_series(
                f"{ns}_replica_up", "gauge",
                "1 when the replica serves, 0 when fenced (state label "
                "says which)",
                [({"filter": f, "device": str(r["device"]),
                   "state": r["state"]}, 1.0 if r["up"] else 0.0)
                 for f, r in flat]))
        out.append(_series(
            f"{ns}_replica_reoffers_total", "counter",
            "frames re-routed to a surviving replica after a fence",
            [({"filter": f}, float(st.get("reoffers", 0)))
             for f, st in sorted(replicas.items())]))
        # sharded serving: rows carrying a "group" key come from a
        # ShardedReplicaSet (serving/sharding.py) — one row per shard
        # GROUP, i.e. N chips acting as one tensor-parallel backend.
        # Σ nns_shard_group_invokes_total over groups equals the owning
        # filter's invoke count, so tensor-parallel conservation is the
        # same one-scrape check the per-chip replica family gives.
        sh = [(f, r) for f, st in sorted(replicas.items())
              for r in st.get("replicas", []) if "group" in r]
        if sh:
            out.append(_series(
                f"{ns}_shard_group_invokes_total", "counter",
                "per-shard-group invokes; summed over groups this "
                "equals the owning filter's invoke count — the "
                "tensor-parallel conservation check",
                [({"filter": f, "group": str(r["group"]),
                   "devices": ",".join(str(d) for d in r["devices"])},
                  float(r["invokes"])) for f, r in sh]))
            out.append(_series(
                f"{ns}_shard_group_up", "gauge",
                "1 when every member chip of the group serves; fencing "
                "ONE member fences the whole group (state label says "
                "which)",
                [({"filter": f, "group": str(r["group"]),
                   "state": r["state"]}, 1.0 if r["up"] else 0.0)
                 for f, r in sh]))
            out.append(_series(
                f"{ns}_shard_group_adopted_epoch", "gauge",
                "store swap epoch this group last adopted; all groups "
                "of a filter reporting one value proves the hot swap "
                "was epoch-atomic across the shard set",
                [({"filter": f, "group": str(r["group"])},
                  float(r.get("adopted_epoch", 0))) for f, r in sh]))
            out.append(_series(
                f"{ns}_shard_group_size", "gauge",
                "chips per shard group (the tensor-parallel width)",
                [({"filter": f}, float(st["group_size"]))
                 for f, st in sorted(replicas.items())
                 if "group_size" in st]))
            out.append(_series(
                f"{ns}_shard_leased_chips", "gauge",
                "chip-lease ledger of the sharded filter, by state",
                [({"filter": f, "state": state}, float(v))
                 for f, st in sorted(replicas.items())
                 for state, v in sorted(st.get("leases", {}).items())]))

    if segments:
        stage_rows = [(pl, row) for pl, rep in sorted(segments.items())
                      for row in rep.get("stages", [])]
        if stage_rows:
            out.append(_series(
                f"{ns}_segment_stage_seconds", "gauge",
                "profiled per-stage proctime of the placement plan",
                [({"plan": pl, "stage": str(row["stage"]),
                   "device": str(row["device"])}, float(row["time_s"]))
                 for pl, row in stage_rows]))
        out.append(_series(
            f"{ns}_segment_bubble_fraction", "gauge",
            "steady-state device idle share of the segmented pipeline "
            "(0 = perfectly balanced stages)",
            [({"plan": pl}, float(rep.get("bubble_fraction", 0.0)))
             for pl, rep in sorted(segments.items())]))

    if mesh:
        m = mesh.get("mesh", {})
        for key, help_ in (("reoffered", "frames redelivered after a "
                                         "host fence"),
                           ("busy_reroutes", "frames retried on a "
                                             "different host after BUSY"),
                           ("stale_results", "host results for already-"
                                             "settled requests")):
            out.append(_series(f"{ns}_mesh_{key}_total", "counter",
                               f"mesh: {help_}",
                               [({}, float(m.get(key, 0)))]))
        for key, help_ in (("hosts", "registered hosts"),
                           ("ready", "hosts holding a live lease"),
                           ("fenced", "hosts cut out of the mesh"),
                           ("pending", "router backlog"),
                           ("epoch", "mesh swap epoch")):
            out.append(_series(f"{ns}_mesh_{key}", "gauge",
                               f"mesh: {help_}",
                               [({}, float(m.get(key, 0)))]))
        hosts = mesh.get("hosts", [])
        if hosts:
            out.append(_series(
                f"{ns}_host_replied_total", "counter",
                "per-host goodput (frames answered); summed over hosts "
                "this equals nns_admission_replied_total — the "
                "cross-host conservation check",
                [({"host": str(h["host"])}, float(h["replied"]))
                 for h in hosts]))
            out.append(_series(
                f"{ns}_host_busies_total", "counter",
                "per-host typed BUSY refusals seen by the router",
                [({"host": str(h["host"])}, float(h["busies"]))
                 for h in hosts]))
            out.append(_series(
                f"{ns}_host_outstanding", "gauge",
                "frames dispatched to the host, unanswered",
                [({"host": str(h["host"])}, float(h["outstanding"]))
                 for h in hosts]))
            out.append(_series(
                f"{ns}_host_lease_age_ms", "gauge",
                "ms since the host's last lease renewal",
                [({"host": str(h["host"])}, float(h["lease_age_ms"]))
                 for h in hosts]))
            out.append(_series(
                f"{ns}_host_up", "gauge",
                "1 when the host holds a live lease, else 0 (state "
                "label says why)",
                [({"host": str(h["host"]), "state": h["state"]},
                  1.0 if h["state"] == "READY" else 0.0)
                 for h in hosts]))
            # lease renewals carry each host's LOCAL admission
            # counters: the remote half of the conservation ledger
            remote = [(h, h.get("remote") or {}) for h in hosts]
            if any(r for _, r in remote):
                for key in ("offered", "admitted", "replied"):
                    out.append(_series(
                        f"{ns}_host_local_{key}_total", "counter",
                        f"host-local admission {key} (lease-carried)",
                        [({"host": str(h["host"])}, float(r[key]))
                         for h, r in remote if key in r]))

    if tracer is not None and getattr(tracer, "active", False):
        hists = tracer.hists()
        if hists:
            out.append(_series(
                f"{ns}_element_proctime_seconds", "histogram",
                "per-element process() latency (w<wid>/ prefix = "
                "merged from that worker process)",
                [({"element": name}, h)
                 for name, h in sorted(hists.items())]))
        cw = tracer.compiled_windows() \
            if hasattr(tracer, "compiled_windows") else {}
        if cw:
            out.append(_series(
                f"{ns}_loop_entries_total", "counter",
                "compiled steady-state windows entered per element "
                "(scheduler bypass, runtime/compiled_loop.py)",
                [({"element": n}, float(c["windows"]))
                 for n, c in sorted(cw.items())]))
            out.append(_series(
                f"{ns}_compiled_steps_total", "counter",
                "frames served through a compiled window per element",
                [({"element": n}, float(c["frames"]))
                 for n, c in sorted(cw.items())]))
        bails = tracer.loop_bails() \
            if hasattr(tracer, "loop_bails") else {}
        if bails:
            out.append(_series(
                f"{ns}_loop_bails_total", "counter",
                "armed compiled windows that fell back to per-frame "
                "mode, by element and cause",
                [({"element": n, "cause": c}, float(v))
                 for n, causes in sorted(bails.items())
                 for c, v in sorted(causes.items())]))
        forced = tracer.forced_syncs()
        if forced:
            out.append(_series(
                f"{ns}_forced_syncs_total", "counter",
                "semantic host syncs per element (runtime/sync.py)",
                [({"element": n}, float(v))
                 for n, v in sorted(forced.items())]))
        sheds = tracer.shed_counts()
        if sheds:
            out.append(_series(
                f"{ns}_trace_sheds_total", "counter",
                "sheds/rejections as seen by the tracer, per server "
                "and cause",
                [({"server": srv, "cause": c}, float(v))
                 for srv, causes in sorted(sheds.items())
                 for c, v in sorted(causes.items())]))
        out.append(_series(
            f"{ns}_trace_events_total", "counter",
            "trace events recorded pool-wide (monotone; ring length "
            "is bounded)", [({}, float(tracer.total_events))]))
        out.append(_series(
            f"{ns}_trace_events_dropped_total", "counter",
            "trace events lost to ring wrap, children included",
            [({}, float(tracer.events_dropped))]))
        s = tracer.summary()
        out.append(_series(
            f"{ns}_trace_requests_total", "counter",
            "completed request timelines recorded",
            [({}, float(s.get("requests", 0)))]))
        queues = tracer.queue_gauges()
        if queues:
            out.append(_series(
                f"{ns}_queue_depth_peak", "gauge",
                "per-queue high-water mark",
                [({"queue": n}, float(g.get("peak", 0)))
                 for n, g in sorted(queues.items())]))
        tenants = tracer.tenant_summary() \
            if hasattr(tracer, "tenant_summary") else {}
        if tenants:
            out.append(_series(
                f"{ns}_tenant_p99_ms", "gauge",
                "per-tenant server-side p99 latency over the request "
                "window (admit → reply)",
                [({"tenant": t}, float(r["p99_ms"]))
                 for t, r in sorted(tenants.items())]))
            out.append(_series(
                f"{ns}_tenant_p50_ms", "gauge",
                "per-tenant server-side median latency over the "
                "request window",
                [({"tenant": t}, float(r["p50_ms"]))
                 for t, r in sorted(tenants.items())]))
            out.append(_series(
                f"{ns}_tenant_rate_hz", "gauge",
                "per-tenant completion rate over the request window",
                [({"tenant": t}, float(r["rate_hz"]))
                 for t, r in sorted(tenants.items())]))

    if autotune:
        decisions = autotune.get("decisions", {})
        out.append(_series(
            f"{ns}_autotune_decisions_total", "counter",
            "autotuner decisions by knob and outcome (applied / "
            "dry_run / proposed / hysteresis / cooldown / error)",
            [({"knob": k, "outcome": o}, float(n))
             for k, d in sorted(decisions.items())
             for o, n in sorted(d.items())] or
            [({"knob": "none", "outcome": "none"}, 0.0)]))
        out.append(_series(
            f"{ns}_autotune_applied_total", "counter",
            "autotuner decisions actually actuated",
            [({}, float(autotune.get("applied_total", 0)))]))
        out.append(_series(
            f"{ns}_autotune_audit_dropped_total", "counter",
            "audit-ring entries aged out by wrap (totals above stay "
            "exact)",
            [({}, float(autotune.get("audit_dropped", 0)))]))
        out.append(_series(
            f"{ns}_autotune_knob", "gauge",
            "current value of each controlled knob",
            [({"knob": k}, float(v))
             for k, v in sorted(autotune.get("knobs", {}).items())] or
            [({"knob": "none"}, 0.0)]))
        out.append(_series(
            f"{ns}_autotune_dry_run", "gauge",
            "1 when the controller only records decisions, 0 when it "
            "actuates",
            [({}, 1.0 if autotune.get("dry_run") else 0.0)]))
        slo = autotune.get("slo", {})
        out.append(_series(
            f"{ns}_autotune_slo_p99_budget_ms", "gauge",
            "declared p99 latency budget the controller defends",
            [({}, float(slo.get("p99_budget_ms", 0.0)))]))
        out.append(_series(
            f"{ns}_autotune_slo_goodput_floor_rps", "gauge",
            "declared goodput floor (0 = none)",
            [({}, float(slo.get("goodput_floor_rps", 0.0)))]))

    if llm:
        # element → (engine-level stats, executor-level stats); accept
        # either a TensorLLM.extra_stats() merge (executor nested) or a
        # bare executor stats dict
        rows = [(el, st, st.get("executor", st))
                for el, st in sorted(llm.items())]
        out.append(_series(
            f"{ns}_llm_kernel_invokes_total", "counter",
            "paged-attention executions by kernel (pallas = flash "
            "paged kernels, xla = the bit-reference) — one scrape "
            "proves which path served",
            [({"element": el, "kernel": k}, float(v))
             for el, _, ex in rows
             for k, v in sorted(ex.get("kernel_invokes", {}).items())]
            or [({"element": "none", "kernel": "none"}, 0.0)]))
        out.append(_series(
            f"{ns}_llm_kernel_fallback_total", "counter",
            "requested Pallas paths served on XLA instead (kernel "
            "unavailable or failed to build — counted, never an error)",
            [({"element": el}, float(ex.get("kernel_fallback", 0)))
             for el, _, ex in rows]))
        out.append(_series(
            f"{ns}_llm_paged_kernel_info", "gauge",
            "1 for the attention kernel currently selected",
            [({"element": el,
               "kernel": str(ex.get("paged_kernel", "xla"))}, 1.0)
             for el, _, ex in rows]))
        out.append(_series(
            f"{ns}_llm_tokens_total", "counter",
            "tokens generated",
            [({"element": el}, float(st.get("tokens_out", 0)))
             for el, st, _ in rows]))
        out.append(_series(
            f"{ns}_llm_finished_total", "counter",
            "requests finished",
            [({"element": el}, float(st.get("finished", 0)))
             for el, st, _ in rows]))
        out.append(_series(
            f"{ns}_llm_chunk_prefills_total", "counter",
            "prompt chunks run through the chunked-prefill bucket",
            [({"element": el}, float(ex.get("chunk_prefills", 0)))
             for el, _, ex in rows]))
        out.append(_series(
            f"{ns}_llm_prefilling", "gauge",
            "requests mid chunked-prefill right now",
            [({"element": el}, float(st.get("prefilling", 0)))
             for el, st, _ in rows]))

    if devprof:
        jit = devprof.get("jit", [])
        inv = devprof.get("invoke", [])
        out.append(_series(
            f"{ns}_jit_flops", "gauge",
            "XLA cost-model FLOPs of the compiled program (a property "
            "of the (filter, bucket) program, not a rate)",
            [({"filter": r["filter"], "bucket": r["bucket"]},
              float(r["flops"])) for r in jit]
            or [({"filter": "none", "bucket": "none"}, 0.0)]))
        out.append(_series(
            f"{ns}_jit_bytes_accessed", "gauge",
            "XLA cost-model bytes accessed of the compiled program",
            [({"filter": r["filter"], "bucket": r["bucket"]},
              float(r["bytes_accessed"])) for r in jit]))
        out.append(_series(
            f"{ns}_jit_roofline_info", "gauge",
            "1 for the bucket's roofline verdict (compute / memory / "
            "unknown) vs the chip's ridge point",
            [({"filter": r["filter"], "bucket": r["bucket"],
               "bound": r["roofline"]}, 1.0) for r in jit]))
        out.append(_series(
            f"{ns}_compile_seconds_total", "counter",
            "cumulative compile wall-seconds per {filter, bucket}",
            [({"filter": r["filter"], "bucket": r["bucket"]},
              float(r["compile_s"])) for r in jit]
            or [({"filter": "none", "bucket": "none"}, 0.0)]))
        out.append(_series(
            f"{ns}_compiles_total", "counter",
            "compile events (fresh executables) per {filter, bucket}",
            [({"filter": r["filter"], "bucket": r["bucket"]},
              float(r["compiles"])) for r in jit]))
        out.append(_series(
            f"{ns}_invoke_mfu", "gauge",
            "model FLOPs utilization: achieved TFLOP/s over the "
            "declared per-chip peak (0 where no peak is declared — "
            "CPU emulation; see nns_invoke_mfu_calibrated)",
            [({"filter": r["filter"], "bucket": r["bucket"],
               "device": r["device"]}, float(r["mfu"])) for r in inv]
            or [({"filter": "none", "bucket": "none",
                  "device": "none"}, 0.0)]))
        out.append(_series(
            f"{ns}_invoke_mfu_calibrated", "gauge",
            "achieved TFLOP/s over the best achieved so far — the "
            "measured calibration denominator where no declared peak "
            "exists",
            [({"filter": r["filter"], "bucket": r["bucket"],
               "device": r["device"]}, float(r["mfu_calibrated"]))
             for r in inv]))
        out.append(_series(
            f"{ns}_invoke_tflops", "gauge",
            "achieved TFLOP/s (cost-model flops / median sampled "
            "device seconds)",
            [({"filter": r["filter"], "bucket": r["bucket"],
               "device": r["device"]}, float(r["achieved_tflops"]))
             for r in inv]))
        out.append(_series(
            f"{ns}_invoke_seconds_total", "counter",
            "cumulative sampled device-seconds per {filter, bucket} — "
            "reconcilable against the proctime histograms' sum from "
            "the same scrape",
            [({"filter": r["filter"], "bucket": r["bucket"]},
              float(r["seconds_total"])) for r in inv]
            or [({"filter": "none", "bucket": "none"}, 0.0)]))
        out.append(_series(
            f"{ns}_invoke_samples_total", "counter",
            "device-time samples taken per {filter, bucket}",
            [({"filter": r["filter"], "bucket": r["bucket"]},
              float(r["samples_total"])) for r in inv]))
        out.append(_series(
            f"{ns}_device_hbm_bytes", "gauge",
            "device memory ledger: memory_stats() rows per {device, "
            "kind} plus model:<label> attribution rows",
            [({"device": r["device"], "kind": r["kind"]},
              float(r["bytes"])) for r in devprof.get("hbm", [])]
            or [({"device": "none", "kind": "none"}, 0.0)]))
        out.append(_series(
            f"{ns}_device_hbm_headroom", "gauge",
            "fraction of the device's memory limit in use",
            [({"device": r["device"]}, float(r["frac"]))
             for r in devprof.get("headroom", [])]))
        out.append(_series(
            f"{ns}_device_peak_tflops", "gauge",
            "declared per-chip bf16 peak TFLOP/s applied as the MFU "
            "denominator (0 = none declared)",
            [({"device_kind": str(devprof.get("device_kind", "none"))},
              float(devprof.get("peak_tflops", 0.0)))]))
        out.append(_series(
            f"{ns}_device_calibration_tflops", "gauge",
            "best achieved TFLOP/s observed (the measured calibration "
            "peak on platforms with no declared peak)",
            [({}, float(devprof.get("calibration_tflops", 0.0)))]))

    if extra:
        for name, value in sorted(extra.items()):
            try:
                v = float(value)
            except (TypeError, ValueError):
                continue
            out.append(_series(f"{ns}_{name}", "gauge",
                               "caller-supplied gauge", [({}, v)]))
    return out


# -- text exposition ---------------------------------------------------------

def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote,
    newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def render_prometheus(series: List[Series]) -> str:
    """Serialize series to the text exposition format (one # HELP and
    # TYPE line per family; histograms expand to cumulative le-buckets
    + _sum + _count)."""
    lines: List[str] = []
    for s in series:
        name, typ = s["name"], s["type"]
        help_ = s.get("help", "").replace("\\", "\\\\") \
            .replace("\n", "\\n")
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {typ}")
        for labels, value in s["samples"]:
            if typ == "histogram":
                bounds = value["bounds"]
                counts = value["counts"]
                cum = 0
                for b, c in zip(bounds, counts):
                    cum += c
                    bl = dict(labels, le=_fmt(b))
                    lines.append(
                        f"{name}_bucket{_labels_str(bl)} {cum}")
                cum += counts[len(bounds)] if len(counts) > len(bounds) \
                    else 0
                bl = dict(labels, le="+Inf")
                lines.append(f"{name}_bucket{_labels_str(bl)} {cum}")
                lines.append(f"{name}_sum{_labels_str(labels)} "
                             f"{repr(float(value['sum']))}")
                lines.append(f"{name}_count{_labels_str(labels)} "
                             f"{int(value['count'])}")
            else:
                lines.append(
                    f"{name}{_labels_str(labels)} {_fmt(value)}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> Dict[str, dict]:
    """Minimal exposition parser (tests + `top`): returns
    {family: {"type", "help", "samples": {sample_line_name+labels:
    value}}}. Handles escaped label values; not a full PromQL lexer —
    exactly the subset render_prometheus emits."""
    out: Dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            fam, _, help_ = rest.partition(" ")
            out.setdefault(fam, {"samples": {}})["help"] = help_
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            fam, _, typ = rest.partition(" ")
            out.setdefault(fam, {"samples": {}})["type"] = typ
        elif line.startswith("#"):
            continue
        else:
            key, _, val = line.rpartition(" ")
            base = key.split("{", 1)[0]
            fam = base
            for suffix in ("_bucket", "_sum", "_count"):
                if base.endswith(suffix) and \
                        base[:-len(suffix)] in out:
                    fam = base[:-len(suffix)]
                    break
            v = float("inf") if val == "+Inf" else float(val)
            out.setdefault(fam, {"samples": {}})["samples"][key] = v
    return out


# -- HTTP endpoint -----------------------------------------------------------

class MetricsServer:
    """Stdlib HTTP exposition endpoint.

    ``collect`` returns the current series list (called per scrape, on
    the HTTP thread — it must only read counters under their own
    locks). Routes: ``/metrics`` (text exposition), ``/healthz``
    (JSON), ``/`` (pointer). Serving uses ThreadingHTTPServer so a
    slow scraper cannot wedge a concurrent /healthz probe.
    """

    def __init__(self, collect: Callable[[], List[Series]],
                 host: str = "127.0.0.1", port: int = 0,
                 health: Optional[Callable[[], dict]] = None):
        import http.server

        self._collect = collect
        self._health = health
        self.scrapes = 0
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):           # noqa: N802 (stdlib contract)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    try:
                        body = render_prometheus(
                            outer._collect()).encode()
                    except Exception as e:   # a scrape must not 500 the
                        log.warning("metrics collect failed: %s", e)
                        self.send_error(503, "collect failed")
                        return
                    outer.scrapes += 1
                    self._ok(body, _CONTENT_TYPE)
                elif path == "/healthz":
                    info = {"ok": True, "scrapes": outer.scrapes}
                    if outer._health is not None:
                        try:
                            info.update(outer._health())
                        except Exception as e:
                            info = {"ok": False, "error": str(e)}
                    self._ok(json.dumps(info).encode(),
                             "application/json")
                elif path == "/":
                    self._ok(b"nnstreamer_tpu metrics: GET /metrics\n",
                             "text/plain")
                else:
                    self.send_error(404)

            def _ok(self, body: bytes, ctype: str) -> None:
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass                      # scrape spam stays off stderr

        self._httpd = http.server.ThreadingHTTPServer(
            (host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-http",
            daemon=True)
        self._thread.start()
        log.info("metrics endpoint on http://%s:%d/metrics",
                 host, self.port)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def scrape(url: str, timeout_s: float = 5.0) -> str:
    """GET one exposition document (stdlib urllib; localhost scrapes)."""
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return r.read().decode()


# -- terminal top view --------------------------------------------------------

#: families the top view rates/ranks first, in display order
_TOP_KEY_FAMILIES = (
    "nns_admission_offered_total", "nns_admission_admitted_total",
    "nns_admission_replied_total", "nns_admission_rejected_total",
    "nns_admission_shed_total",
    # per-tenant rows: replied rate = goodput, shed/rejected rate =
    # shed rate, p99 gauge = SLO position (all labelled by tenant)
    "nns_tenant_replied_total", "nns_tenant_rejected_total",
    "nns_tenant_shed_total", "nns_tenant_p99_ms",
    "nns_worker_replied_total",
    # per-chip rows (serving/placement.py): invoke rate = per-device
    # goodput, queue depth = where the backpressure is, up = fences
    "nns_replica_invokes_total", "nns_replica_queue_depth",
    "nns_replica_up",
    # shard-group rows (serving/sharding.py): per-group goodput, the
    # group fence state, and the adopted swap epoch — one value across
    # groups means the flip was atomic
    "nns_shard_group_invokes_total", "nns_shard_group_up",
    "nns_shard_group_adopted_epoch",
    # autotuner rows: decision rate by knob/outcome + where every
    # controlled knob sits right now
    "nns_autotune_decisions_total", "nns_autotune_knob",
    # LLM serving rows: token rate = generation goodput, kernel invoke
    # rate = which attention path is hot, prefilling = admission wave
    "nns_llm_tokens_total", "nns_llm_kernel_invokes_total",
    "nns_llm_prefilling",
    # device performance plane (runtime/devprof.py): MFU and HBM
    # headroom answer "how close to the hardware" at a glance
    "nns_invoke_mfu", "nns_invoke_seconds_total",
    "nns_device_hbm_headroom", "nns_compile_seconds_total",
    "nns_pool_restarts_total", "nns_trace_events_total",
)


def top_table(prev: Dict[str, dict], cur: Dict[str, dict],
              dt_s: float) -> List[str]:
    """Render one refresh of the top view from two parsed scrapes:
    counters as rates over the interval, gauges as current values."""
    lines = [f"{'series':<56} {'value':>14} {'rate/s':>10}"]
    lines.append("-" * 82)

    def rows(order):
        for fam in order:
            info = cur.get(fam)
            if info is None:
                continue
            typ = info.get("type", "gauge")
            for key, v in sorted(info["samples"].items()):
                if key.endswith("_sum") or "_bucket{" in key or \
                        key.endswith("_count"):
                    continue
                rate = ""
                if typ == "counter" and fam in prev:
                    pv = prev[fam]["samples"].get(key)
                    if pv is not None and dt_s > 0:
                        rate = f"{max(0.0, (v - pv) / dt_s):.1f}"
                disp = key if len(key) <= 56 else key[:53] + "..."
                lines.append(f"{disp:<56} {v:>14.10g} {rate:>10}")

    rows([f for f in _TOP_KEY_FAMILIES if f in cur])
    rows(sorted(f for f in cur
                if f not in _TOP_KEY_FAMILIES
                and cur[f].get("type") != "histogram"))
    return lines


def top_view(url: str, interval_s: float = 1.0,
             iterations: int = 0, out=None) -> None:
    """Live terminal view over any exposition endpoint: scrape, diff,
    redraw. iterations=0 runs until interrupted."""
    import sys

    out = out or sys.stdout
    prev: Dict[str, dict] = {}
    prev_t = time.monotonic()
    n = 0
    while True:
        try:
            cur = parse_prometheus(scrape(url))
        except OSError as e:
            out.write(f"scrape {url} failed: {e}\n")
            return
        now = time.monotonic()
        lines = top_table(prev, cur, now - prev_t)
        out.write("\x1b[2J\x1b[H" if out.isatty() else "")
        out.write(f"nnstreamer_tpu top — {url} "
                  f"(interval {interval_s:.1f}s)\n")
        out.write("\n".join(lines) + "\n")
        out.flush()
        prev, prev_t = cur, now
        n += 1
        if iterations and n >= iterations:
            return
        time.sleep(interval_s)
