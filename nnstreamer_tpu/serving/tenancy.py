"""Multi-tenant serving: tenant classes, model residency, replica scaling.

One pool, M models, N tenants. Three cooperating pieces turn the
single-tenant serving stack into a shared one:

- **TenantTable** — the declarative config: each tenant class has a
  name, a WFQ weight, an optional ``store://`` model binding, and an
  optional per-class latency budget (``deadline_ms``) and queue bound
  (``max_pending``). `AdmissionQueue.set_tenants` consumes it to grow a
  weighted-fair front (traffic/admission.py); the pool consumes it to
  route frames tenant→model.

- **ModelResidency** — the worker-side pressure valve. A multiplex
  worker keeps several store models resident, each with its own
  bucketed-jit cache; under a configurable bound (max resident models
  with live compiles, or max resident bytes) the *least-recently-used*
  cold model's compiled buckets are released. Eviction is a counted
  event, never an error: the next invoke for that model recompiles.

- **ScalingController** — traffic-driven replica scaling. A daemon
  thread samples per-tenant arrival rates from the tracer, converts
  them to per-model demand, and re-binds pool slots to models through
  `WorkerPool.rebind` — which reuses the swap broadcast's two-phase
  prepare/commit, so a rebind is epoch-atomic: every slot flips in the
  same pool epoch or none does.

Tenant names double as Prometheus label values, so they are validated
at the edge: ``[a-zA-Z0-9_-]{1,64}`` (`validate_tenant_name`). Requests
with a malformed ``meta["tenant"]`` are refused with cause
``bad_tenant`` and attributed to the pseudo-class `INVALID_CLASS`
(spelled with a ``!``, outside the tenant charset, so it can never
collide with a real tenant) — per-class counters still sum exactly to
the global conservation invariants.
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

log = logging.getLogger("nnstreamer_tpu.tenancy")

#: TensorBuffer.meta key clients set to claim a tenant class
TENANT_META = "tenant"

#: TensorBuffer.meta key the admission queue stamps with the *resolved*
#: class name (after default-class fallback), so downstream accounting
#: (pool dispatch, query-server reply) attributes completions to the
#: same class the offer was counted under even if the table changes.
CLASS_META = "_tenant_class"

#: pseudo-class charging refusals of malformed tenant names; '!' is
#: outside the tenant charset so no real tenant can collide with it
INVALID_CLASS = "!invalid"

#: the class requests without a tenant claim fall into
DEFAULT_CLASS = "default"

_NAME_RE = re.compile(r"\A[a-zA-Z0-9_-]{1,64}\Z")


def validate_tenant_name(name: Any) -> bool:
    """True iff `name` is a str matching ``[a-zA-Z0-9_-]{1,64}``.

    This bounds Prometheus label cardinality (the charset excludes
    every character `serving/metrics.py` escapes) and keeps hostile
    input out of the label path entirely."""
    return isinstance(name, str) and _NAME_RE.match(name) is not None


@dataclass(frozen=True)
class TenantClass:
    """One tenant's contract: scheduling weight, model binding, SLO."""

    name: str
    weight: float = 1.0
    model: Optional[str] = None        # store:// model name, or None
    deadline_ms: Optional[float] = None
    max_pending: Optional[int] = None  # per-class queue bound override

    def __post_init__(self):
        if not validate_tenant_name(self.name):
            raise ValueError(
                f"tenant name {self.name!r} is invalid: must match "
                f"[a-zA-Z0-9_-]{{1,64}}")
        if not (self.weight > 0 and self.weight == self.weight):
            raise ValueError(
                f"tenant {self.name!r}: weight must be finite and > 0, "
                f"got {self.weight}")
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ValueError(
                f"tenant {self.name!r}: deadline_ms must be > 0, "
                f"got {self.deadline_ms}")
        if self.max_pending is not None and self.max_pending < 1:
            raise ValueError(
                f"tenant {self.name!r}: max_pending must be >= 1, "
                f"got {self.max_pending}")


class TenantTable:
    """Immutable name→TenantClass mapping with a default class.

    Requests that carry no ``meta["tenant"]`` resolve to the default
    class (created implicitly with weight 1.0 if the table doesn't
    declare one). Unknown-but-valid tenant names also fall back to the
    default class — a tenant the operator never declared gets best-
    effort service, not an error."""

    def __init__(self, classes: List[TenantClass],
                 default: str = DEFAULT_CLASS):
        if not classes:
            raise ValueError("TenantTable needs at least one class")
        self._classes: Dict[str, TenantClass] = {}
        for c in classes:
            if c.name in self._classes:
                raise ValueError(f"duplicate tenant class {c.name!r}")
            self._classes[c.name] = c
        if default not in self._classes:
            self._classes[default] = TenantClass(name=default)
        self.default = default

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TenantTable":
        """Parse the ``--tenants FILE`` JSON shape::

            {"default": "free",
             "tenants": [{"name": "acme", "weight": 3.0,
                          "model": "mobilenet_v2", "deadline_ms": 50,
                          "max_pending": 32}, ...]}

        ``tenants`` may also be a name→spec mapping."""
        raw = d.get("tenants", d)
        if isinstance(raw, dict):
            entries = [dict(spec, name=name) for name, spec in raw.items()]
        else:
            entries = [dict(e) for e in raw]
        classes = []
        for e in entries:
            classes.append(TenantClass(
                name=e["name"],
                weight=float(e.get("weight", 1.0)),
                model=e.get("model"),
                deadline_ms=(float(e["deadline_ms"])
                             if e.get("deadline_ms") is not None else None),
                max_pending=(int(e["max_pending"])
                             if e.get("max_pending") is not None else None),
            ))
        return cls(classes, default=d.get("default", DEFAULT_CLASS)
                   if isinstance(d.get("tenants"), (list, dict))
                   else DEFAULT_CLASS)

    @classmethod
    def from_json(cls, path: str) -> "TenantTable":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    def class_of(self, tenant: Optional[str]) -> TenantClass:
        """Resolve a (validated) tenant name to its class; None or an
        undeclared name falls back to the default class."""
        if tenant is not None and tenant in self._classes:
            return self._classes[tenant]
        return self._classes[self.default]

    def model_of(self, tenant: Optional[str]) -> Optional[str]:
        return self.class_of(tenant).model

    def names(self) -> List[str]:
        return list(self._classes)

    def classes(self) -> List[TenantClass]:
        return list(self._classes.values())

    def models(self) -> List[str]:
        """Distinct bound model names, declaration order."""
        seen: Dict[str, None] = {}
        for c in self._classes.values():
            if c.model is not None:
                seen.setdefault(c.model, None)
        return list(seen)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "default": self.default,
            "tenants": [
                {"name": c.name, "weight": c.weight, "model": c.model,
                 "deadline_ms": c.deadline_ms, "max_pending": c.max_pending}
                for c in self._classes.values()
            ],
        }


class ModelResidency:
    """LRU pressure bound over resident models' compiled state.

    Tracks which models have live bucketed-jit compiles and how much
    device memory their params hold. When the bound is exceeded
    (``max_models`` with compiles, or ``max_bytes`` of resident params),
    the least-recently-*invoked* model beyond the bound has its
    compiled buckets released via ``backend.release_compiled()``.

    Eviction is bookkeeping, not failure: the evicted model stays
    registered and its next invoke recompiles (an XLA cache miss). The
    ``jit_evictions`` counter is the only externally visible effect —
    results are bitwise unchanged.
    """

    def __init__(self, max_models: int = 0, max_bytes: int = 0):
        # 0 = unbounded (that axis imposes no pressure)
        self.max_models = int(max_models)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._lru: "OrderedDict[str, Any]" = OrderedDict()  # name → backend
        self.jit_evictions = 0          # models whose compiles were dropped
        self.entries_evicted = 0        # individual jit entries dropped

    def register(self, name: str, backend: Any) -> None:
        with self._lock:
            self._lru[name] = backend
            self._lru.move_to_end(name)

    def touch(self, name: str) -> List[str]:
        """Mark `name` most-recently-used, then enforce the bound.
        Returns the names evicted this call (usually empty)."""
        with self._lock:
            if name in self._lru:
                self._lru.move_to_end(name)
            return self._evict_locked(keep=name)

    def _evict_locked(self, keep: str) -> List[str]:
        evicted: List[str] = []
        # Pressure by count: models (≠ keep) holding live compiles
        if self.max_models > 0:
            while True:
                warm = [n for n, b in self._lru.items()
                        if self._cache_size(b) > 0]
                if len(warm) <= self.max_models:
                    break
                victim = next((n for n in warm if n != keep), None)
                if victim is None:
                    break
                evicted.append(victim)
                self._release(victim)
        # Pressure by bytes: resident param bytes across models
        if self.max_bytes > 0:
            while self._resident_bytes() > self.max_bytes:
                victim = next(
                    (n for n, b in self._lru.items()
                     if n != keep and self._cache_size(b) > 0), None)
                if victim is None:
                    break
                evicted.append(victim)
                self._release(victim)
        return evicted

    def _release(self, name: str) -> None:
        backend = self._lru[name]
        dropped = backend.release_compiled()
        self.jit_evictions += 1
        self.entries_evicted += int(dropped)
        self._lru.move_to_end(name, last=False)   # coldest position
        log.info("residency: evicted %s (%d compiled entries released)",
                 name, dropped)

    @staticmethod
    def _cache_size(backend: Any) -> int:
        try:
            return int(backend.jit_cache_size())
        except Exception:
            return 0

    def _resident_bytes(self) -> int:
        total = 0
        for b in self._lru.values():
            try:
                total += int(b.resident_bytes())
            except Exception:
                pass
        return total

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "resident": list(self._lru),
                "warm": [n for n, b in self._lru.items()
                         if self._cache_size(b) > 0],
                "jit_evictions": self.jit_evictions,
                "entries_evicted": self.entries_evicted,
                "resident_bytes": self._resident_bytes(),
                "max_models": self.max_models,
                "max_bytes": self.max_bytes,
            }


class ScalingController:
    """Traffic-driven slot→model rebinding.

    Every ``interval_s`` the controller reads `tracer.tenant_summary()`
    (per-tenant completion rates over the tracer's request window),
    folds tenant rates into per-model demand via the TenantTable, and
    computes a proportional slot allocation: each bound model gets
    ``max(min_slots, round(slots * share))`` with leftovers going to
    the hottest models. If the allocation differs from the current
    binding it calls ``pool.rebind(mapping)`` — a two-phase broadcast,
    so every slot re-binds in the same pool epoch or none does.

    Rates of exactly zero everywhere (cold start, idle) keep the
    current binding: scaling reacts to traffic, it never thrashes an
    idle pool. Failed rebinds are counted and retried on the next tick.
    """

    def __init__(self, pool: Any, table: TenantTable, tracer: Any,
                 interval_s: float = 1.0, min_slots: int = 1,
                 now: Callable[[], float] = time.monotonic):
        self.pool = pool
        self.table = table
        self.tracer = tracer
        self.interval_s = float(interval_s)
        self.min_slots = int(min_slots)
        self._now = now
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # counters / introspection (under _lock)
        self.decisions = 0       # ticks that computed a plan
        self.rebinds = 0         # plans that changed the binding
        self.rebind_failures = 0
        self.last_plan: Dict[str, int] = {}
        self.last_rates: Dict[str, float] = {}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ScalingController":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="tenancy-scaler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                log.exception("scaling tick failed")

    # -- one decision ------------------------------------------------------
    def tick(self) -> Optional[Dict[str, int]]:
        """One scaling decision; returns the applied plan or None if
        the binding was left alone. Callable directly from tests."""
        demand = self._model_demand()
        with self._lock:
            self.decisions += 1
            self.last_rates = dict(demand)
        models = self.table.models()
        if not models or not any(demand.get(m, 0.0) > 0 for m in models):
            return None
        plan = self._allocate(models, demand)
        current = self._current_binding()
        if current == plan:
            return None
        ok = self._apply(plan)
        with self._lock:
            if ok:
                self.rebinds += 1
                self.last_plan = dict(plan)
            else:
                self.rebind_failures += 1
        return plan if ok else None

    def _model_demand(self) -> Dict[str, float]:
        """Per-model demand = sum of its tenants' observed rates."""
        try:
            per_tenant = self.tracer.tenant_summary()
        except Exception:
            per_tenant = {}
        demand: Dict[str, float] = {}
        for tenant, row in per_tenant.items():
            model = self.table.model_of(tenant)
            if model is None:
                continue
            demand[model] = demand.get(model, 0.0) + float(
                row.get("rate_hz", 0.0))
        return demand

    def _slot_weights(self) -> Dict[int, int]:
        """{wid: capacity weight} — chip count for chip-leased pools
        (serving/placement.py: a K-chip worker serves K replicas'
        traffic), 1 everywhere else. Pool doubles without the surface
        weigh every slot 1."""
        sw = getattr(self.pool, "slot_weights", None)
        if callable(sw):
            try:
                return {int(k): max(1, int(v)) for k, v in sw().items()}
            except Exception:
                pass
        return {}

    def _allocate(self, models: List[str],
                  demand: Dict[str, float]) -> Dict[str, int]:
        """Proportional share with a per-model floor, largest-remainder
        for the leftovers. Deterministic: ties break by model order.
        The budget is CAPACITY slots (chip-weighted), not processes —
        a 2-worker × 4-chip pool allocates 8 units."""
        slots = max(int(getattr(self.pool, "capacity_slots", 0)
                        or self.pool.size), 1)
        total = sum(max(demand.get(m, 0.0), 0.0) for m in models)
        floors = {m: self.min_slots for m in models}
        base = sum(floors.values())
        spare = max(0, slots - base)
        if total <= 0.0 or spare == 0:
            return floors
        exact = {m: spare * max(demand.get(m, 0.0), 0.0) / total
                 for m in models}
        plan = {m: floors[m] + int(exact[m]) for m in models}
        left = slots - sum(plan.values())
        by_frac = sorted(models, key=lambda m: exact[m] - int(exact[m]),
                         reverse=True)
        for m in by_frac:
            if left <= 0:
                break
            plan[m] += 1
            left -= 1
        return plan

    def _current_binding(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        w = self._slot_weights()
        try:
            for sid, model in self.pool.bindings().items():
                if model is not None:
                    counts[model] = counts.get(model, 0) + w.get(sid, 1)
        except Exception:
            pass
        return counts

    def _apply(self, plan: Dict[str, int]) -> bool:
        """Expand a {model: n_slots} plan to {slot_id: model} and push
        it through the pool's two-phase rebind. Slots currently bound
        to a model keep it where the plan allows (minimal churn)."""
        try:
            current = dict(self.pool.bindings())
        except Exception:
            return False
        w = self._slot_weights()
        want = dict(plan)
        mapping: Dict[int, Optional[str]] = {}
        unassigned: List[int] = []
        for sid in sorted(current):
            cur = current[sid]
            wt = w.get(sid, 1)
            # keep the slot only when the plan still owes its model the
            # slot's FULL weight — a K-chip slot consumes K plan units
            if cur is not None and want.get(cur, 0) >= wt:
                mapping[sid] = cur
                want[cur] -= wt
            else:
                unassigned.append(sid)
        for sid in unassigned:
            wt = w.get(sid, 1)
            owed = sorted(((m, n) for m, n in want.items() if n > 0),
                          key=lambda kv: (-kv[1], kv[0]))
            if owed:
                m = owed[0][0]
                mapping[sid] = m
                want[m] -= wt
            else:
                mapping[sid] = None
        try:
            rep = self.pool.rebind(mapping)
        except Exception:
            log.exception("rebind failed")
            return False
        return bool(rep.get("ok"))

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "decisions": self.decisions,
                "rebinds": self.rebinds,
                "rebind_failures": self.rebind_failures,
                "last_plan": dict(self.last_plan),
                "last_rates": dict(self.last_rates),
                "interval_s": self.interval_s,
                "min_slots": self.min_slots,
            }
