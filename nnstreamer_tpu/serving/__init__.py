"""Serving subsystem: versioned model store + zero-downtime hot swap.

Models become live, versioned pipeline citizens (docs/serving.md):

- ``store://name[@version][:canary_ratio]`` refs resolve through the
  process-wide :class:`ModelStore` instead of binding a model once at
  negotiation; zoo builtins seed the store at version ``@0`` so
  ``zoo://`` and ``store://`` interoperate.
- ``store.update(name, version)`` is an epoch-based hot swap: the
  incoming version is pre-warmed off the hot path (same dyn_batch
  buckets the outgoing version served), the epoch flips atomically, and
  attached backends adopt the new version at their next invoke boundary
  — in-flight invokes finish on the old version, new buffers take the
  new one, and the old version's compiled buckets are retired.
- A persistent compile cache (``[serving]`` config group) plus a
  store-level bucket manifest lets restarted processes start warm.
"""
from nnstreamer_tpu.serving.store import (  # noqa: F401
    ModelStore,
    StoreRef,
    get_store,
    parse_store_ref,
    reset_store,
)
