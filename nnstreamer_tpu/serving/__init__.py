"""Serving subsystem: versioned model store + zero-downtime hot swap.

Models become live, versioned pipeline citizens (docs/serving.md):

- ``store://name[@version][:canary_ratio]`` refs resolve through the
  process-wide :class:`ModelStore` instead of binding a model once at
  negotiation; zoo builtins seed the store at version ``@0`` so
  ``zoo://`` and ``store://`` interoperate.
- ``store.update(name, version)`` is an epoch-based hot swap: the
  incoming version is pre-warmed off the hot path (same dyn_batch
  buckets the outgoing version served), the epoch flips atomically, and
  attached backends adopt the new version at their next invoke boundary
  — in-flight invokes finish on the old version, new buffers take the
  new one, and the old version's compiled buckets are retired.
- A persistent compile cache (``[serving]`` config group) plus a
  store-level bucket manifest lets restarted processes start warm.
- A supervised multi-process worker pool (pool.py / worker.py) runs N
  pipeline copies in child processes behind one query server: crash
  isolation, heartbeat + frame-deadline liveness, backoff restart with
  a restart-budget circuit, conservation-exact `worker_lost`
  accounting, and graceful drain (docs/robustness.md).
"""
from nnstreamer_tpu.serving.store import (  # noqa: F401
    ModelStore,
    StoreRef,
    get_store,
    parse_store_ref,
    reset_store,
)


def __getattr__(name):
    # pool/worker are lazy: importing the store must not pull in the
    # multiprocessing machinery (children import this package too)
    if name in ("WorkerPool", "PooledQueryServer", "proc_alive"):
        from nnstreamer_tpu.serving import pool as _pool

        return getattr(_pool, name)
    if name == "WorkerSpec":
        from nnstreamer_tpu.serving.worker import WorkerSpec

        return WorkerSpec
    raise AttributeError(name)
