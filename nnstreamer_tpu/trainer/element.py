"""tensor_trainer element — streaming training steps.

Sink pad 0 carries (x, label) multi-tensor frames (use tensor_mux to
pair a data stream with a label stream). Each process() call runs one
jitted (optionally mesh-sharded) train step; the src pad emits a scalar
float32 loss per step so a tensor_sink can chart/stop on it.

Properties:
- model:      zoo reference ("zoo://mobilenet_v2?width=0.35&...") whose
              module exposes loss_fn(params, x, y)
- optimizer:  "sgd:<lr>" | "adam:<lr>" (optax)
- mesh:       "dp=4,tp=2" — shard the step over a device mesh
- checkpoint_dir + checkpoint_every: orbax checkpoints every N steps
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from nnstreamer_tpu.core.errors import BackendError, PipelineError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import Element, Emission, PropDef, StreamSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

log = get_logger("trainer")


def _parse_optimizer(s: str):
    import optax

    kind, _, lr = s.partition(":")
    lr_f = float(lr or 1e-3)
    if kind == "sgd":
        return optax.sgd(lr_f)
    if kind == "adam":
        return optax.adam(lr_f)
    if kind == "adamw":
        return optax.adamw(lr_f)
    raise PipelineError(
        f"unknown optimizer {s!r}; use sgd:<lr> | adam:<lr> | adamw:<lr>")


def _parse_mesh(s: str):
    if not s:
        return None
    from nnstreamer_tpu.parallel import MeshSpec, make_mesh

    kw = {}
    for part in s.split(","):
        k, _, v = part.partition("=")
        kw[k.strip()] = int(v)
    return make_mesh(MeshSpec(**kw))


@register_element("tensor_trainer")
class TensorTrainer(Element):
    ELEMENT_NAME = "tensor_trainer"
    PROPS = {
        "model": PropDef(lambda s: s, None, "zoo:// model with loss_fn"),
        "optimizer": PropDef(str, "sgd:0.01"),
        "mesh": PropDef(str, "", "e.g. 'dp=4,tp=2'; empty = single device"),
        "checkpoint_dir": PropDef(str, ""),
        "checkpoint_every": PropDef(int, 100),
        "resume_from": PropDef(str, "", "checkpoint path to restore at "
                                        "start (full train state)"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._step_fn = None
        self._state = None
        self._loss_fn = None
        self.steps = 0

    def _resolve_loss(self):
        model = self.props["model"]
        if callable(model):  # loss_fn(params, x, y) given directly
            return model, None
        if not isinstance(model, str) or not model.startswith("zoo://"):
            raise PipelineError(
                f"tensor_trainer {self.name}: model= must be a zoo:// "
                f"reference or a callable loss_fn; got {model!r}")
        from urllib.parse import parse_qsl

        name, _, query = model[len("zoo://"):].partition("?")
        kwargs = {k.replace("-", "_"): v for k, v in parse_qsl(query)}
        import importlib

        try:
            mod = importlib.import_module(f"nnstreamer_tpu.models.{name}")
        except ImportError as e:
            raise PipelineError(
                f"tensor_trainer {self.name}: no trainable model "
                f"{name!r}: {e}") from e
        if not hasattr(mod, "loss_fn") or not hasattr(mod, "init_params"):
            raise PipelineError(
                f"model {name!r} is not trainable (needs loss_fn + "
                f"init_params)")
        width = float(kwargs.get("width", 1.0))
        num_classes = int(kwargs.get("num_classes", 1001))
        import jax.numpy as jnp

        params = mod.init_params(width=width, num_classes=num_classes)

        def loss(p, x, y):
            return mod.loss_fn(p, x, y, width=width, dtype=jnp.float32)

        return loss, params

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0])
        if spec.num_tensors != 2:
            self.fail_negotiation(
                f"tensor_trainer takes (x, label) 2-tensor frames (pair "
                f"them with tensor_mux); got {spec.num_tensors} tensors")
        from nnstreamer_tpu.parallel.train import init_state

        self._loss_fn, params = self._resolve_loss()
        if params is None:
            self.fail_negotiation(
                "callable loss models must be passed with explicit params "
                "— use the zoo:// form instead")
        opt = _parse_optimizer(self.props["optimizer"])
        mesh = _parse_mesh(self.props["mesh"])
        from nnstreamer_tpu.parallel.train import make_train_step, shard_state

        state = init_state(params, opt)
        self._mesh = mesh
        if mesh is not None:
            state = shard_state(state, mesh)
            # batch_spec defaults to dp-sharded leading dims inside
            # make_train_step — spec construction stays in parallel/
            # (NNL012 shard-safety)
            self._step_fn = make_train_step(self._loss_fn, opt, mesh=mesh)
        else:
            self._step_fn = make_train_step(self._loss_fn, opt)
        self._state = state
        if self.props["resume_from"]:
            self.restore_checkpoint(self.props["resume_from"])
        return [TensorsSpec.of(TensorInfo((1,), DType.FLOAT32),
                               rate=spec.rate)]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        import jax.numpy as jnp

        x, y = buf.tensors[0], buf.tensors[1]
        y = jnp.asarray(np.asarray(y).reshape(-1).astype(np.int32))
        try:
            self._state, loss = self._step_fn(self._state, jnp.asarray(x), y)
        except Exception as e:
            raise BackendError(
                f"tensor_trainer {self.name}: train step failed at step "
                f"{self.steps}: {e}") from e
        self.steps += 1
        every = self.props["checkpoint_every"]
        if self.props["checkpoint_dir"] and every > 0 and \
                self.steps % every == 0:
            self.save_checkpoint()
        return [(0, buf.with_tensors(
            (np.asarray(loss, np.float32).reshape(1),)))]

    # -- checkpoint / resume (SURVEY.md §5.4 — exceeds reference parity) ---
    def save_checkpoint(self) -> None:
        """FULL train state (params + optimizer moments + step), so a
        resumed run continues the optimizer trajectory instead of
        restarting Adam/momentum statistics from zero."""
        import jax
        import orbax.checkpoint as ocp

        path = f"{self.props['checkpoint_dir']}/step_{self.steps}"
        tree = {
            "params": self._state.params,
            "opt_state": self._state.opt_state,
            "step": np.asarray(self._state.step),
        }
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(path, jax.tree_util.tree_map(np.asarray, tree))
        log.info("trainer %s: checkpoint at step %d → %s",
                 self.name, self.steps, path)

    def restore_checkpoint(self, path: str) -> None:
        import jax
        import jax.numpy as jnp
        import orbax.checkpoint as ocp

        from dataclasses import replace

        import os

        if not os.path.isdir(path):
            raise PipelineError(
                f"trainer {self.name}: resume checkpoint {path!r} does "
                f"not exist")

        def abstract(tree):
            # shapes/dtypes only — never a D2H copy of the live state
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    getattr(a, "shape", ()),
                    getattr(a, "dtype", np.dtype(np.int32))), tree)

        full = {
            "params": self._state.params,
            "opt_state": self._state.opt_state,
            "step": np.asarray(self._state.step),
        }
        with ocp.StandardCheckpointer() as ckptr:
            try:
                restored = ckptr.restore(path, abstract(full))
            except (ValueError, KeyError):
                # structure mismatch ⇒ legacy params-only layout
                # (pre-full-state saves); moments restart from zero.
                # Real I/O errors propagate above untouched.
                restored = {
                    "params": ckptr.restore(
                        path, abstract(self._state.params)),
                    "opt_state": self._state.opt_state,
                    "step": np.asarray(self.steps, np.int32),
                }
                log.warning(
                    "trainer %s: %s is a legacy params-only checkpoint; "
                    "optimizer state restarts fresh", self.name, path)
        self._state = replace(self._state, params=restored["params"],
                              opt_state=restored["opt_state"],
                              step=jnp.asarray(restored["step"], jnp.int32))
        if getattr(self, "_mesh", None) is not None:
            # restore yields host numpy: re-place on the mesh or the
            # sharded train step silently falls back to full replication
            from nnstreamer_tpu.parallel.train import shard_state

            self._state = shard_state(self._state, self._mesh)
        self.steps = int(np.asarray(restored["step"]))
        log.info("trainer %s: resumed from %s at step %d",
                 self.name, path, self.steps)

    @property
    def params(self):
        return self._state.params if self._state is not None else None
