"""On-stream training (reference: the reserved `tensor_trainer` subplugin
type, nnstreamer_subplugin.h TRAINER slot — never fleshed out upstream;
first-class here because TPUs train).

`tensor_trainer` consumes (x, label) tensor frames and runs one optimizer
step per frame/batch on a zoo model — optionally sharded over a mesh
(parallel/train.py) — and periodically emits the scalar loss downstream
plus checkpoints via orbax when `checkpoint_dir` is set.
"""

from nnstreamer_tpu.trainer.element import TensorTrainer

__all__ = ["TensorTrainer"]
