"""Model zoo registry.

`zoo://<name>[?k=v&k2=v2]` references resolve here. Builders are
registered lazily (import side effects of nnstreamer_tpu.models.*) and
return `backends.xla.ModelBundle` objects.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List
from urllib.parse import parse_qsl

from nnstreamer_tpu.core.errors import BackendError

_builders: Dict[str, Callable] = {}
_lock = threading.Lock()


def register_model(name: str):
    """`@register_model("mobilenet_v2")` on a builder(**kwargs)->ModelBundle.

    Names are unique: a second registration raises (the zoo seeds the
    model store as version ``@0``, and store versions are immutable —
    register variants under the store instead, ``ModelStore.register``).
    """
    def deco(fn):
        with _lock:
            prev = _builders.get(name)
            if prev is not None and prev is not fn:
                raise BackendError(
                    f"zoo model {name!r} is already registered (builder "
                    f"{prev.__module__}.{prev.__qualname__}); zoo names "
                    f"seed the model store as {name!r}@0 and versions "
                    f"are immutable — register updated weights via "
                    f"ModelStore.register({name!r}, ...) instead")
            _builders[name] = fn
        # seed the model store so store://<name> serves this builder as
        # version @0 (idempotent; lazy import avoids a module cycle)
        from nnstreamer_tpu.serving.store import get_store

        get_store().seed_zoo(name, fn)
        return fn
    return deco


def list_models() -> List[str]:
    _load_builtins()
    with _lock:
        return sorted(_builders)


def build_model(ref: str):
    """Build a bundle from a zoo reference (name + optional ?query args)."""
    _load_builtins()
    name, _, query = ref.partition("?")
    kwargs = {}
    for k, v in parse_qsl(query):
        kwargs[k.replace("-", "_")] = _coerce(v)
    with _lock:
        builder = _builders.get(name)
    if builder is None:
        raise BackendError(
            f"no zoo model named {name!r}; available: "
            f"{list_models() or '(none)'}"
        )
    try:
        return builder(**kwargs)
    except TypeError as e:
        raise BackendError(f"bad zoo model arguments in {ref!r}: {e}") from e


def _coerce(v: str):
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            continue
    if v.lower() in ("true", "false"):
        return v.lower() == "true"
    return v


_loaded = False


def _load_builtins() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    # import for registration side effects; keep failures actionable but
    # non-fatal so one broken model doesn't take down the zoo
    import importlib

    for mod in ("mobilenet_v2", "ssd_mobilenet", "posenet", "lstm",
                "transformer", "audio_classifier", "probe"):
        try:
            importlib.import_module(f"nnstreamer_tpu.models.{mod}")
        except ImportError:
            pass
