"""PoseNet — single-person pose estimation (zoo://posenet).

Covers the reference's pose pipeline: posenet model + `tensor_decoder
mode=pose_estimation` (ext/nnstreamer/tensor_decoder/tensordec-pose.c,
tests/nnstreamer_decoder_pose/). Outputs the decoder's expected pair:
keypoint heatmaps (N, H/16, W/16, K) and short-range offsets
(N, H/16, W/16, 2K) for K=17 COCO keypoints.

Backbone is MobileNetV2 truncated at stride 16 (output_stride=16 via
skipping the last stride-2 — standard PoseNet practice), heads are 1x1
convs — all one fused XLA computation on TPU.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import layers as L
from nnstreamer_tpu.models import mobilenet_v2 as mnv2
from nnstreamer_tpu.models.zoo import register_model

NUM_KEYPOINTS = 17


def init_params(key=None, *, width: float = 1.0, seed: int = 0) -> Dict[str, Any]:
    if key is None:
        key = jax.random.PRNGKey(seed)
    kb, kp, kh, ko = jax.random.split(key, 4)
    backbone = mnv2.init_params(kb, width=width)
    c16 = mnv2._make_divisible(96 * width)  # stride-16 feature channels
    chead = 256
    return {
        "backbone": backbone,
        "proj": L.init_conv_bn(kp, 1, 1, c16, chead),
        "heatmap": L.init_conv(kh, 1, 1, chead, NUM_KEYPOINTS),
        "offset": L.init_conv(ko, 1, 1, chead, 2 * NUM_KEYPOINTS),
    }


def apply(params, x, *, width: float = 1.0, train: bool = False,
          dtype=jnp.bfloat16):
    """x: (N, H, W, 3) float → (heatmaps (N,h,w,17) sigmoid f32,
    offsets (N,h,w,34) f32) at output stride 16."""
    feats = mnv2.apply(params["backbone"], x, width=width, train=train,
                       dtype=dtype, features_only=True)
    # run the head on the stride-16 map upsampled path: PoseNet keeps
    # output_stride 16 by using the pre-stride-32 features; the 1280-ch
    # head conv of the backbone ran at stride 32, so re-project from the
    # stride-16 map instead.
    h16 = feats[-2]
    h = L.conv_bn(params["proj"], h16, train=train, dtype=dtype)
    heat = L.conv2d(params["heatmap"], h, dtype=dtype)
    off = L.conv2d(params["offset"], h, dtype=dtype)
    return (jax.nn.sigmoid(heat).astype(jnp.float32),
            off.astype(jnp.float32))


@register_model("posenet")
def build(width: float = 1.0, input_size: int = 257, batch: int = 1,
          dtype: str = "bfloat16", seed: int = 0):
    from nnstreamer_tpu.backends.xla import ModelBundle
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    cdtype = jnp.dtype(dtype)
    params = init_params(width=width, seed=seed)

    def fn(params, x):
        return apply(params, x, width=width, dtype=cdtype)

    in_spec = TensorsSpec.of(
        TensorInfo((batch, input_size, input_size, 3), DType.FLOAT32))
    return ModelBundle(fn=fn, params=params, in_spec=in_spec,
                       out_spec=None,  # negotiated via eval_shape
                       name="posenet_mnv2")
