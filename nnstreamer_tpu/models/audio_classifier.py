"""1-D conv audio classifier (zoo://audio_classifier) — the audio family.

Reference analog: the audio ingest path (tensor_converter audio branch,
gsttensor_converter.c:1110) feeding a keyword-spotting-style model; the
reference ships no audio model, so this closes the loop the same way the
vision zoo does for video. Architecture: log-energy frontend → stacked
strided conv1d blocks → global pool → linear head, all MXU matmul-shaped
(conv1d lowers to dot_general) and trainable (loss_fn for
tensor_trainer).

Pipeline shape (tests/test_streaming_models.py):
    audiotestsrc ! tensor_converter frames-per-tensor=<window> !
    tensor_transform mode=typecast option=float32 !
    tensor_filter model=zoo://audio_classifier?window=<window> !
    tensor_sink
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import layers as L
from nnstreamer_tpu.models.zoo import register_model


def init_params(key=None, *, channels: int = 32, n_blocks: int = 3,
                num_classes: int = 12, seed: int = 0,
                **_) -> Dict[str, Any]:
    """`**_` absorbs pass-through kwargs (e.g. tensor_trainer's width);
    the conv stack is window-agnostic — window only shapes the stream."""
    if key is None:
        key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, n_blocks + 2)
    blocks = []
    cin = 1
    for i in range(n_blocks):
        blocks.append({
            "w": L.xavier_init(keys[i], (8, cin, channels)),   # (K, Cin, Cout)
            "b": jnp.zeros((channels,), jnp.float32),
        })
        cin = channels
    return {
        "blocks": blocks,
        "head_w": L.xavier_init(keys[-2], (channels, num_classes)),
        "head_b": jnp.zeros((num_classes,), jnp.float32),
    }


def apply(params, x, *, dtype=jnp.float32):
    """x: (B, T) or (B, T, 1) waveform → (B, num_classes) logits."""
    if x.ndim == 2:
        x = x[..., None]
    h = x.astype(dtype)
    # frontend: per-window mean/scale normalize (robust to gain)
    mu = jnp.mean(h, axis=1, keepdims=True)
    sd = jnp.std(h, axis=1, keepdims=True) + 1e-5
    h = (h - mu) / sd
    for blk in params["blocks"]:
        h = jax.lax.conv_general_dilated(
            h, blk["w"].astype(dtype), window_strides=(4,),
            padding="SAME", dimension_numbers=("NWC", "WIO", "NWC"))
        h = jax.nn.relu(h + blk["b"].astype(dtype))
    pooled = jnp.mean(h, axis=1)                          # (B, C)
    return (pooled @ params["head_w"].astype(dtype)
            + params["head_b"].astype(dtype)).astype(jnp.float32)


def loss_fn(params, x, y, *, dtype=jnp.float32, **_):
    logits = apply(params, x, dtype=dtype)
    onehot = jax.nn.one_hot(y, logits.shape[-1])
    return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))


@register_model("audio_classifier")
def build(window: int = 1024, channels: int = 32, n_blocks: int = 3,
          num_classes: int = 12, batch: int = 1, dtype: str = "float32",
          seed: int = 0):
    from nnstreamer_tpu.backends.xla import ModelBundle
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    cdtype = jnp.dtype(dtype)
    params = init_params(channels=channels, n_blocks=n_blocks,
                         num_classes=num_classes, seed=seed)

    # the stream unit is ONE converter window (window, 1) — the shape
    # `tensor_converter frames-per-tensor=<window>` emits; batch>1 takes
    # stacked windows (batch, window, 1)
    if batch == 1:
        def fn(params, x):
            return apply(params, x[None], dtype=cdtype)[0]

        in_spec = TensorsSpec.of(
            TensorInfo((window, 1), DType.FLOAT32, name="wave"))
        out_spec = TensorsSpec.of(
            TensorInfo((num_classes,), DType.FLOAT32, name="logits"))
    else:
        def fn(params, x):
            return apply(params, x, dtype=cdtype)

        in_spec = TensorsSpec.of(
            TensorInfo((batch, window, 1), DType.FLOAT32, name="wave"))
        out_spec = TensorsSpec.of(
            TensorInfo((batch, num_classes), DType.FLOAT32, name="logits"))
    return ModelBundle(fn=fn, params=params, in_spec=in_spec,
                       out_spec=out_spec, name="audio_classifier")
