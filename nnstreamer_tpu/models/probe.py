"""Known-answer probe models (zoo://probe_scale | probe_negate |
probe_offset) — the multi-tenant multiplexing test fixtures.

Three distinct models sharing one input contract (``(8, 1) float32``,
the pool tests' frame shape) whose outputs are exactly predictable from
the input: ``scale * x``, ``-x``, and ``x + offset``. A multiplex
worker serving all three lets a test assert *which* model answered a
frame from the numbers alone — cross-tenant routing errors, stale
compiles after an LRU eviction, or a swap leaking into another tenant's
traffic all become wrong arithmetic instead of silent corruption.

Each builder is parametric (``zoo://probe_scale?scale=3``), so the same
zoo name yields distinguishable *versions* for hot-swap tests: register
``probe_scale`` with a different scale as ``@1`` and a swap flips the
answer by a known factor.
"""

from __future__ import annotations

import jax.numpy as jnp

from nnstreamer_tpu.models.zoo import register_model

_ROWS = 8  # matches the serving tests' canonical 8:1 float32 frame


def _bundle(fn, params, name: str, rows: int):
    from nnstreamer_tpu.backends.xla import ModelBundle
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    spec = TensorsSpec.of(TensorInfo((rows, 1), DType.FLOAT32, name="x"))
    return ModelBundle(fn=fn, params=params, in_spec=spec,
                       out_spec=spec, name=name)


@register_model("probe_scale")
def build_scale(scale: float = 2.0, rows: int = _ROWS):
    params = {"scale": jnp.float32(scale)}

    def fn(params, x):
        return x * params["scale"]

    return _bundle(fn, params, "probe_scale", rows)


@register_model("probe_negate")
def build_negate(rows: int = _ROWS):
    def fn(params, x):
        return -x

    return _bundle(fn, None, "probe_negate", rows)


@register_model("probe_offset")
def build_offset(offset: float = 10.0, rows: int = _ROWS):
    params = {"offset": jnp.float32(offset)}

    def fn(params, x):
        return x + params["offset"]

    return _bundle(fn, params, "probe_offset", rows)
