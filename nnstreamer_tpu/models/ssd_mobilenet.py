"""SSD-MobileNetV2 object detector (zoo://ssd_mobilenet).

Covers the reference's detection pipeline: SSD model + `tensor_decoder
mode=bounding_boxes option1=mobilenet-ssd` with a box-priors file
(gst/nnstreamer/tensor_query/README.md:46-53 pipeline;
ext/nnstreamer/tensor_decoder/tensordec-boundingbox.c). TPU-first: the
priors are generated in-code (`generate_anchors`) and shared with the
decoder — no sidecar file — and the whole detector is one fused XLA
computation.

Outputs (per frame): loc deltas (N, A, 4) [ty, tx, th, tw] and class
logits (N, A, num_classes) for A=1917 anchors at input 300², the standard
TF-SSD anchor grid the reference's decoder expects.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.models import layers as L
from nnstreamer_tpu.models import mobilenet_v2 as mnv2
from nnstreamer_tpu.models.zoo import register_model

# feature-map grid sizes for 300x300 input and anchors per cell — yields
# the canonical 1917-anchor layout (19²·3 + (10²+5²+3²+2²+1)·6).
_GRIDS_300 = ((19, 3), (10, 6), (5, 6), (3, 6), (2, 6), (1, 6))
_SCALES = (0.2, 0.35, 0.5, 0.65, 0.8, 0.95, 1.0)
_ASPECTS = (1.0, 2.0, 0.5, 3.0, 1.0 / 3.0)
_BOX_CODER = (10.0, 10.0, 5.0, 5.0)  # ty, tx, th, tw scale factors


def generate_anchors(grids=_GRIDS_300) -> np.ndarray:
    """→ (A, 4) float32 [cy, cx, h, w] in [0,1] — the box-priors analog."""
    out: List[np.ndarray] = []
    for level, (g, n_anchor) in enumerate(grids):
        s = _SCALES[level]
        s_next = _SCALES[level + 1]
        if n_anchor == 3:
            # first layer: reduced set {1.0 scaled-down, 2.0, 0.5}
            hw = [(0.1, 0.1),
                  (s / math.sqrt(2.0), s * math.sqrt(2.0)),
                  (s * math.sqrt(2.0), s / math.sqrt(2.0))]
        else:
            hw = [(s / math.sqrt(a), s * math.sqrt(a)) for a in _ASPECTS]
            hw.append((math.sqrt(s * s_next), math.sqrt(s * s_next)))
        ys, xs = np.meshgrid(np.arange(g), np.arange(g), indexing="ij")
        cy = ((ys + 0.5) / g).reshape(-1)
        cx = ((xs + 0.5) / g).reshape(-1)
        per_anchor = [
            np.stack([cy, cx, np.full_like(cy, h), np.full_like(cx, w)], axis=-1)
            for h, w in hw[:n_anchor]
        ]
        # per-cell interleave (anchors of one cell contiguous) — matches the
        # head's reshape(n, -1, 4) ordering
        lvl = np.stack(per_anchor, axis=1)  # (cells, n_anchor, 4)
        out.append(lvl.reshape(g * g * n_anchor, 4))
    return np.concatenate(out, axis=0).astype(np.float32)


def decode_boxes(loc, anchors):
    """SSD box-coder decode: deltas+priors → (ymin, xmin, ymax, xmax).

    jnp-traceable (used on-device by the fused decoder path) and
    numpy-compatible (host decoder).
    """
    ty, tx, th, tw = (loc[..., 0] / _BOX_CODER[0], loc[..., 1] / _BOX_CODER[1],
                      loc[..., 2] / _BOX_CODER[2], loc[..., 3] / _BOX_CODER[3])
    acy, acx, ah, aw = (anchors[..., 0], anchors[..., 1],
                        anchors[..., 2], anchors[..., 3])
    xp = jnp if not isinstance(loc, np.ndarray) else np
    cy = ty * ah + acy
    cx = tx * aw + acx
    h = ah * xp.exp(th)
    w = aw * xp.exp(tw)
    return xp.stack([cy - h / 2, cx - w / 2, cy + h / 2, cx + w / 2], axis=-1)


def init_params(key=None, *, num_classes: int = 91, width: float = 1.0,
                seed: int = 0) -> Dict[str, Any]:
    if key is None:
        key = jax.random.PRNGKey(seed)
    kb, kx, kh = jax.random.split(key, 3)
    params: Dict[str, Any] = {"backbone": mnv2.init_params(kb, width=width)}
    # extra feature layers past the backbone: 1280→512→256→256→128, each a
    # 1x1 squeeze + 3x3 stride-2 conv (SSD extra-layer pattern)
    head_in = [
        mnv2._make_divisible(96 * width),    # stride-16 feature map (19²)
        mnv2._make_divisible(1280 * max(1.0, width)),
        512, 256, 256, 128,
    ]
    extras = []
    cin = head_in[1]
    xkeys = jax.random.split(kx, 4)
    for i, cout in enumerate(head_in[2:]):
        k1, k2 = jax.random.split(xkeys[i])
        extras.append({
            "squeeze": L.init_conv_bn(k1, 1, 1, cin, cout // 2),
            "conv": L.init_conv_bn(k2, 3, 3, cout // 2, cout),
        })
        cin = cout
    params["extras"] = extras
    # prediction heads: per level a loc conv (n_anchor*4) and cls conv
    locs, clss = [], []
    hkeys = jax.random.split(kh, len(_GRIDS_300) * 2)
    for i, ((g, n_anchor), cin) in enumerate(zip(_GRIDS_300, head_in)):
        locs.append(L.init_conv(hkeys[2 * i], 3, 3, cin, n_anchor * 4))
        clss.append(L.init_conv(hkeys[2 * i + 1], 3, 3, cin, n_anchor * num_classes))
    params["loc_heads"] = locs
    params["cls_heads"] = clss
    return params


def apply(params, x, *, num_classes: int = 91, width: float = 1.0,
          train: bool = False, dtype=jnp.bfloat16):
    """x: (N, 300, 300, 3) float → (loc (N,A,4) f32, logits (N,A,C) f32)."""
    n = x.shape[0]
    feats = mnv2.apply(params["backbone"], x, width=width, train=train,
                       dtype=dtype, features_only=True)
    # stride-16 map (19², pre-stride-32 input) and the 1280-ch head (10²)
    levels = [feats[-2], feats[-1]]
    h = feats[-1]
    for extra in params["extras"]:
        h = L.conv_bn(extra["squeeze"], h, train=train, dtype=dtype)
        h = L.conv_bn(extra["conv"], h, stride=2, train=train, dtype=dtype)
        levels.append(h)
    locs, clss = [], []
    for lvl, lp, cp in zip(levels, params["loc_heads"], params["cls_heads"]):
        loc = L.conv2d(lp, lvl, dtype=dtype)
        cls = L.conv2d(cp, lvl, dtype=dtype)
        locs.append(loc.reshape(n, -1, 4))
        clss.append(cls.reshape(n, -1, num_classes))
    return (jnp.concatenate(locs, axis=1).astype(jnp.float32),
            jnp.concatenate(clss, axis=1).astype(jnp.float32))


@register_model("ssd_mobilenet")
def build(num_classes: int = 91, width: float = 1.0, input_size: int = 300,
          batch: int = 1, dtype: str = "bfloat16", seed: int = 0):
    from nnstreamer_tpu.backends.xla import ModelBundle
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    if input_size != 300:
        raise ValueError(
            "zoo://ssd_mobilenet currently ships the canonical 300x300 "
            "anchor grid (1917 anchors); input_size must be 300"
        )
    cdtype = jnp.dtype(dtype)
    params = init_params(num_classes=num_classes, width=width, seed=seed)
    n_anchors = int(generate_anchors().shape[0])

    def fn(params, x):
        return apply(params, x, num_classes=num_classes, width=width,
                     dtype=cdtype)

    in_spec = TensorsSpec.of(
        TensorInfo((batch, input_size, input_size, 3), DType.FLOAT32))
    out_spec = TensorsSpec.of(
        TensorInfo((batch, n_anchors, 4), DType.FLOAT32, name="loc"),
        TensorInfo((batch, n_anchors, num_classes), DType.FLOAT32, name="scores"),
    )
    return ModelBundle(fn=fn, params=params, in_spec=in_spec,
                       out_spec=out_spec, name="ssd_mobilenet_v2")
