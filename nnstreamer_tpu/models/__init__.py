"""Flagship model zoo — jax/flax models loadable as `model=zoo://<name>`.

The reference ships tiny test models per vendor framework
(tests/test_models/models/). Here the zoo is first-class: each entry
builds a `ModelBundle` (fn + params + specs) ready for the xla backend.
"""

from nnstreamer_tpu.models.zoo import build_model, list_models, register_model

__all__ = ["build_model", "list_models", "register_model"]
