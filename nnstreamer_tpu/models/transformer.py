"""Decoder-only transformer (zoo://transformer) — the long-context family.

No reference counterpart (the reference is CNN-era inference plumbing;
SURVEY.md §5.7 maps its closest analogs). This is the model family that
exercises the framework's long-context machinery end-to-end:

- **Streaming decode**: the KV cache is explicit state tensors, so
  autoregressive generation runs as a *pipeline loop* — cache loops
  through tensor_repo exactly like the LSTM's (h, c), one token per
  frame (tests/test_streaming_models.py pattern).
- **Sequence parallelism**: full-sequence forward (prefill/training)
  attends via parallel/ring_attention.py when a mesh is given — the
  sequence dim shards over `sp` and K/V blocks rotate over ICI.

Architecture: pre-RMSNorm, rotary position embeddings, multi-head
causal attention, SwiGLU MLP — the standard modern decoder block, all
MXU-shaped matmuls in the caller's dtype (bf16 on TPU).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import layers as L
from nnstreamer_tpu.models.zoo import register_model


def rmsnorm(x, w, eps=1e-6):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype) * w


def rope(x, pos):
    """Rotary embedding. x: (B, S, H, D); pos: (S,) absolute positions."""
    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = pos.astype(jnp.float32)[:, None] * freqs[None, :]   # (S, half)
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


def init_params(key=None, *, d_model=64, n_heads=4, n_layers=2, d_ff=None,
                vocab=256, n_kv_heads=None, seed=0) -> Dict[str, Any]:
    """n_kv_heads < n_heads = grouped-query attention: the KV cache (and
    K/V projections) shrink by the group factor — the standard long-
    context memory lever. Default (None) = full multi-head."""
    if key is None:
        key = jax.random.PRNGKey(seed)
    d_ff = d_ff or 4 * d_model
    n_kv = n_kv_heads or n_heads
    if n_heads % n_kv:
        raise ValueError(f"n_heads={n_heads} not divisible by "
                         f"n_kv_heads={n_kv}")
    kv_dim = (d_model // n_heads) * n_kv
    keys = jax.random.split(key, n_layers * 4 + 2)
    blocks = []
    for i in range(n_layers):
        k0, k1, k2, k3 = keys[4 * i:4 * i + 4]
        blocks.append({
            "ln1": jnp.ones((d_model,), jnp.float32),
            "wqkv": L.xavier_init(k0, (d_model, d_model + 2 * kv_dim)),
            "wo": L.xavier_init(k1, (d_model, d_model)),
            "ln2": jnp.ones((d_model,), jnp.float32),
            "wi": L.xavier_init(k2, (d_model, 2 * d_ff)),   # SwiGLU gate+up
            "wd": L.xavier_init(k3, (d_ff, d_model)),
        })
    return {
        "embed": L.xavier_init(keys[-2], (vocab, d_model)),
        "blocks": blocks,
        "ln_f": jnp.ones((d_model,), jnp.float32),
        "head": L.xavier_init(keys[-1], (d_model, vocab)),
    }


def _mlp(blk, x, dtype):
    gate_up = x @ blk["wi"].astype(dtype)
    gate, up = jnp.split(gate_up, 2, axis=-1)
    return (jax.nn.silu(gate) * up) @ blk["wd"].astype(dtype)


def _qkv(blk, x, n_heads, dtype):
    """Project to q (n_heads) and k/v (n_kv_heads, inferred from the
    weight shape), then repeat KV groups so attention sees full heads —
    the cache stays narrow, the compute path stays uniform."""
    b, s, d = x.shape
    hd = d // n_heads
    total = blk["wqkv"].shape[1]
    kv_dim = (total - d) // 2
    n_kv = kv_dim // hd
    qkv = x @ blk["wqkv"].astype(dtype)
    q = qkv[..., :d].reshape(b, s, n_heads, hd)
    k = qkv[..., d:d + kv_dim].reshape(b, s, n_kv, hd)
    v = qkv[..., d + kv_dim:].reshape(b, s, n_kv, hd)
    return q, k, v


def _expand_kv(k, n_heads):
    """(B, S, n_kv, D) → (B, S, n_heads, D) by group repetition."""
    n_kv = k.shape[2]
    if n_kv == n_heads:
        return k
    return jnp.repeat(k, n_heads // n_kv, axis=2)


def apply_seq(params, ids, *, n_heads=4, dtype=jnp.float32,
              mesh=None, sp_axis: str = "sp", attn: str = "auto"):
    """Full-sequence forward: (B, S) int32 → (B, S, vocab) logits.

    With a mesh, attention runs ring-parallel over `sp_axis` (sequence
    sharded, K/V rotating over ICI). Without, `attn` picks the kernel:
    "pallas" = the flash-attention Pallas kernel (~7x over the XLA
    softmax at S=2048 on v5e, driver-measured in BENCH_r04.json —
    growing with S), "xla" = plain causal softmax,
    "auto" = pallas when the sequence divides its 128-blocks, else xla.
    """
    from nnstreamer_tpu.parallel.ring_attention import (
        reference_attention, ring_attention)

    b, s = ids.shape
    x = params["embed"][ids].astype(dtype)
    pos = jnp.arange(s)
    # explicit attn="pallas" always takes the kernel (flash_attention
    # raises its pad-upstream error on indivisible S rather than
    # silently substituting the XLA path); "auto" requires 128-blocks
    use_pallas = mesh is None and (
        attn == "pallas" or (attn == "auto" and s % 128 == 0))
    for blk in params["blocks"]:
        h = rmsnorm(x, blk["ln1"].astype(dtype))
        q, k, v = _qkv(blk, h, n_heads, dtype)
        q, k = rope(q, pos), rope(k, pos)
        k, v = _expand_kv(k, n_heads), _expand_kv(v, n_heads)
        if mesh is not None:
            attn = ring_attention(q, k, v, mesh=mesh, axis=sp_axis,
                                  causal=True)
        elif use_pallas:
            from nnstreamer_tpu.backends.pallas_ops import flash_attention

            # per-path auto block sizes (512² resident / 1024² K-grid,
            # see _flash_plan): the MXU needs big blocks — 128² here
            # measured ~12× slower than the defaults at S=2048
            attn = flash_attention(q, k, v, causal=True)
        else:
            attn = reference_attention(q, k, v, causal=True)
        attn = attn.reshape(b, s, -1)
        x = x + attn @ blk["wo"].astype(dtype)
        h = rmsnorm(x, blk["ln2"].astype(dtype))
        x = x + _mlp(blk, h, dtype)
    x = rmsnorm(x, params["ln_f"].astype(dtype))
    return (x @ params["head"].astype(dtype)).astype(jnp.float32)


def apply_seq_kv(params, ids, *, n_heads=4, dtype=jnp.float32):
    """Full-sequence forward that ALSO returns every layer's rope'd K/V.

    (B, S) int32 → (logits (B, S, vocab) f32,
                    k (L, B, S, n_kv, D), v (L, B, S, n_kv, D))

    This is the prefill path of the continuous-batching LLM engine
    (llm/engine.py): one bucketed forward computes the prompt's whole KV
    set, which then lands in the paged cache, instead of `generate()`'s
    per-token `_step_jit` loop. The attention here is deliberately
    formulated EXACTLY like `_step_impl`'s cached attention — the same
    f32 einsums ("bqhd,bkhd->bhqk" / "bhqk,bkhd->bqhd"), the same -1e30
    additive mask, softmax in f32 — rather than reusing `apply_seq`'s
    kernel dispatch: masked positions then contribute exact 0.0 terms in
    both paths, so the paged engine's tokens match `generate()`
    token-for-token at temperature 0 (tests/test_llm.py parity gate).
    """
    b, s = ids.shape
    x = params["embed"][ids].astype(dtype)
    pos = jnp.arange(s)
    causal = (jnp.arange(s)[None, :] <=
              jnp.arange(s)[:, None])[None, None, :, :]   # (1,1,Sq,Sk)
    ks, vs = [], []
    for blk in params["blocks"]:
        h = rmsnorm(x, blk["ln1"].astype(dtype))
        q, k, v = _qkv(blk, h, n_heads, dtype)
        q, k = rope(q, pos), rope(k, pos)
        ks.append(k)
        vs.append(v)
        hd = x.shape[-1] // n_heads
        kcx = _expand_kv(k, n_heads).astype(jnp.float32)
        sc = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        kcx) * hd ** -0.5
        sc = jnp.where(causal, sc, -1e30)
        pattn = jax.nn.softmax(sc, axis=-1)
        vcx = _expand_kv(v, n_heads).astype(jnp.float32)
        attn = jnp.einsum("bhqk,bkhd->bqhd", pattn, vcx).astype(dtype)
        x = x + attn.reshape(b, s, -1) @ blk["wo"].astype(dtype)
        h = rmsnorm(x, blk["ln2"].astype(dtype))
        x = x + _mlp(blk, h, dtype)
    x = rmsnorm(x, params["ln_f"].astype(dtype))
    logits = (x @ params["head"].astype(dtype)).astype(jnp.float32)
    return logits, jnp.stack(ks, axis=0), jnp.stack(vs, axis=0)


def init_cache(*, batch=1, max_len=128, d_model=64, n_heads=4, n_layers=2,
               n_kv_heads=None, dtype=jnp.float32):
    """KV cache as TWO stacked tensors (pipeline-friendly state):
    k/v: (L, B, max_len, n_kv, D) — GQA narrows it by the group factor.
    Position rides a (1,) int32 tensor.

    `dtype` is the cache STORAGE type; attention math upcasts to f32 on
    read regardless (softmax/accumulator precision unchanged). bf16
    storage halves the cache's HBM footprint and sweep traffic —
    measured round 5 (after the in-place write-through fix): 0.85 vs
    1.07 ms/step at d=1024/4L/B=8/max_len=2048, +26% tokens/s. (The
    earlier "~2×" held only while every step also COPIED the cache;
    the copy scaled with storage bytes and is gone.)"""
    hd = d_model // n_heads
    n_kv = n_kv_heads or n_heads
    shape = (n_layers, batch, max_len, n_kv, hd)
    return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
            jnp.zeros((1,), jnp.int32))


def _step_impl(params, ids, k_cache, v_cache, pos, n_heads, dtype, proj):
    """Shared decode-step body for the float and W8A8 paths.

    `proj(store, name, x)` runs one projection matmul and returns in
    `dtype` — the ONLY thing the two paths differ in (dense `x @ w`
    here; int8 `w8a8_matmul` in models/quant.py). Everything
    load-bearing lives once: the ring-slot write goes THROUGH the
    stacked cache (one dynamic_update_slice on the full (L,B,S,Hkv,D)
    array per tensor) — never unstack and restack: a per-layer
    k_cache[li] → update → jnp.stack round-trip defeats XLA's in-place
    aliasing of the donated cache inside lax.scan/_step_jit and copies
    the whole cache every token (measured 2.6× slower at max_len=2048:
    2.24 vs 0.86 ms/step, bit-identical outputs)."""
    b = ids.shape[0]
    max_len = k_cache.shape[2]
    p = pos.astype(jnp.int32)[0]
    slot = p % max_len
    x = params["embed"][ids[:, 0]][:, None, :].astype(dtype)   # (B,1,D)
    pvec = p[None]
    for li, blk in enumerate(params["blocks"]):
        h = rmsnorm(x, blk["ln1"].astype(dtype))
        d = x.shape[-1]
        hd = d // n_heads
        qkv = proj(blk, "wqkv", h)
        kv_dim = (qkv.shape[-1] - d) // 2
        n_kv = kv_dim // hd
        q = qkv[..., :d].reshape(b, 1, n_heads, hd)
        k = qkv[..., d:d + kv_dim].reshape(b, 1, n_kv, hd)
        v = qkv[..., d + kv_dim:].reshape(b, 1, n_kv, hd)
        q, k = rope(q, pvec), rope(k, pvec)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype)[None], (li, 0, slot, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype)[None], (li, 0, slot, 0, 0))
        kc, vc = k_cache[li], v_cache[li]
        # attend over the populated window (all slots once wrapped)
        scale = hd ** -0.5
        # cache layout is (B, max_len, n_kv, D): expand KV groups to
        # full heads for the attention einsum; scores/softmax in f32
        # regardless of the cache storage dtype
        kcx = _expand_kv(kc, n_heads).astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       kcx) * scale                 # (B,H,1,max_len)
        mask = (jnp.arange(max_len) <=
                jnp.minimum(p, max_len - 1))[None, None, None, :]
        s = jnp.where(mask, s, -1e30)
        pattn = jax.nn.softmax(s, axis=-1)
        vcx = _expand_kv(vc, n_heads).astype(jnp.float32)
        attn = jnp.einsum("bhqk,bkhd->bqhd", pattn, vcx).astype(dtype)
        x = x + proj(blk, "wo", attn.reshape(b, 1, -1))
        h = rmsnorm(x, blk["ln2"].astype(dtype))
        gate, up = jnp.split(proj(blk, "wi", h), 2, axis=-1)
        x = x + proj(blk, "wd", jax.nn.silu(gate) * up)
    x = rmsnorm(x, params["ln_f"].astype(dtype))
    logits = proj(params, "head", x[:, 0]).astype(jnp.float32)
    return (logits, k_cache, v_cache, (p + 1)[None].astype(jnp.int32))


def apply_step(params, ids, k_cache, v_cache, pos, *, n_heads=4,
               dtype=jnp.float32):
    """One streaming decode step: ids (B, 1) int32 + cache → logits
    (B, vocab) + updated cache. Static shapes throughout: the cache is a
    TRUE ring — writes land at pos % max_len, so past max_len tokens the
    window slides (sliding-window attention over the last max_len
    tokens; RoPE keys carry absolute positions, so relative geometry
    stays correct across the wrap). Body shared with the W8A8 twin via
    `_step_impl`."""
    def proj(store, name, x):
        return x @ store[name].astype(dtype)

    return _step_impl(params, ids, k_cache, v_cache, pos, n_heads,
                      dtype, proj)


#: one compiled decode step per (n_heads, dtype) — generate() calls
#: reuse it instead of paying a fresh XLA compile per invocation
_step_jit = jax.jit(apply_step, static_argnames=("n_heads", "dtype"),
                    donate_argnums=(2, 3))


def _decode_one(params, cur, k_cache, v_cache, pos, key, *, n_heads,
                dtype, temperature, top_k):
    """Step + sample fused in ONE program: a token in, the next token
    out. Keeps the decode loop at one dispatch per token — per-token
    host-side argmax/sort/categorical ops each cost a full dispatch
    round-trip on remote backends (measured 11 tok/s vs ~190 fused)."""
    logits, kc, vc, pos = apply_step(params, cur[:, None], k_cache,
                                     v_cache, pos, n_heads=n_heads,
                                     dtype=dtype)
    if temperature <= 0:
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        lg = logits / temperature
        if top_k > 0:
            kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
            lg = jnp.where(lg < kth, -1e30, lg)
        key, sub = jax.random.split(key)
        nxt = jax.random.categorical(sub, lg, axis=-1).astype(jnp.int32)
    return nxt, kc, vc, pos, key


_decode_jit = jax.jit(
    _decode_one,
    static_argnames=("n_heads", "dtype", "temperature", "top_k"),
    donate_argnums=(2, 3))


def generate(params, prompt_ids, n_tokens, *, n_heads=4, max_len=128,
             temperature: float = 0.0, top_k: int = 0, seed: int = 0,
             dtype=jnp.float32):
    """Autoregressive sampling: prompt (B, P) int32 → (B, P + n_tokens).

    temperature=0 is greedy argmax; otherwise softmax sampling, optionally
    top-k truncated (clamped to the vocab). One jitted step with donated
    cache — the KV ring stays in HBM across tokens, and each sampled
    token's D2H overlaps the next step's compute."""
    import numpy as np

    d_model = params["embed"].shape[1]
    n_layers = len(params["blocks"])
    hd = d_model // n_heads
    n_kv = (params["blocks"][0]["wqkv"].shape[1] - d_model) // 2 // hd
    b, plen = prompt_ids.shape
    if plen == 0:
        raise ValueError("generate() needs a non-empty prompt (the model "
                         "has no BOS convention to start from)")
    vocab = params["head"].shape[1]
    top_k = min(top_k, vocab)
    kc, vc, pos = init_cache(batch=b, max_len=max_len, d_model=d_model,
                             n_heads=n_heads, n_layers=n_layers,
                             n_kv_heads=n_kv)

    key = jax.random.PRNGKey(seed)
    out = [np.asarray(prompt_ids)]
    # prefill all but the last prompt token (its step is fused into the
    # first decode call)
    for t in range(plen - 1):
        _, kc, vc, pos = _step_jit(params, prompt_ids[:, t:t + 1],
                                   kc, vc, pos, n_heads=n_heads,
                                   dtype=dtype)
    cur = prompt_ids[:, plen - 1]
    pending = []                                # device tokens, D2H deferred
    for _ in range(n_tokens):
        cur, kc, vc, pos, key = _decode_jit(
            params, cur, kc, vc, pos, key, n_heads=n_heads, dtype=dtype,
            temperature=float(temperature), top_k=int(top_k))
        pending.append(cur)
    # ONE D2H for all sampled tokens: per-token np.asarray would pay a
    # full transfer round-trip each (measured 11 → ~2000 tok/s on a
    # tunneled chip)
    if pending:
        out.append(np.asarray(jnp.stack(pending, axis=1)))
    return np.concatenate(out, axis=1)


@register_model("transformer")
def build(d_model: int = 64, n_heads: int = 4, n_layers: int = 2,
          vocab: int = 256, max_len: int = 128, batch: int = 1,
          n_kv_heads: int = 0, dtype: str = "float32", seed: int = 0):
    """Streaming-decode bundle: (ids, k_cache, v_cache, pos) →
    (logits, k_cache, v_cache, pos) — state loops through tensor_repo."""
    from nnstreamer_tpu.backends.xla import ModelBundle
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    cdtype = jnp.dtype(dtype)
    n_kv = n_kv_heads or n_heads
    params = init_params(d_model=d_model, n_heads=n_heads,
                         n_layers=n_layers, vocab=vocab,
                         n_kv_heads=n_kv, seed=seed)
    hd = d_model // n_heads
    cshape = (n_layers, batch, max_len, n_kv, hd)

    def fn(params, ids, k_cache, v_cache, pos):
        return apply_step(params, ids, k_cache, v_cache, pos,
                          n_heads=n_heads, dtype=cdtype)

    in_spec = TensorsSpec.of(
        TensorInfo((batch, 1), DType.INT32, name="ids"),
        TensorInfo(cshape, DType.FLOAT32, name="k_cache"),
        TensorInfo(cshape, DType.FLOAT32, name="v_cache"),
        TensorInfo((1,), DType.INT32, name="pos"),
    )
    out_spec = TensorsSpec.of(
        TensorInfo((batch, vocab), DType.FLOAT32, name="logits"),
        TensorInfo(cshape, DType.FLOAT32, name="k_cache"),
        TensorInfo(cshape, DType.FLOAT32, name="v_cache"),
        TensorInfo((1,), DType.INT32, name="pos"),
    )
    return ModelBundle(fn=fn, params=params, in_spec=in_spec,
                       out_spec=out_spec, name="transformer")
