"""Decoder-only transformer (zoo://transformer) — the long-context family.

No reference counterpart (the reference is CNN-era inference plumbing;
SURVEY.md §5.7 maps its closest analogs). This is the model family that
exercises the framework's long-context machinery end-to-end:

- **Streaming decode**: the KV cache is explicit state tensors, so
  autoregressive generation runs as a *pipeline loop* — cache loops
  through tensor_repo exactly like the LSTM's (h, c), one token per
  frame (tests/test_streaming_models.py pattern).
- **Sequence parallelism**: full-sequence forward (prefill/training)
  attends via parallel/ring_attention.py when a mesh is given — the
  sequence dim shards over `sp` and K/V blocks rotate over ICI.

Architecture: pre-RMSNorm, rotary position embeddings, multi-head
causal attention, SwiGLU MLP — the standard modern decoder block, all
MXU-shaped matmuls in the caller's dtype (bf16 on TPU).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import layers as L
from nnstreamer_tpu.models.zoo import register_model


def rmsnorm(x, w, eps=1e-6):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype) * w


def rope(x, pos):
    """Rotary embedding. x: (B, S, H, D); pos: (S,) absolute positions."""
    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = pos.astype(jnp.float32)[:, None] * freqs[None, :]   # (S, half)
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


def init_params(key=None, *, d_model=64, n_heads=4, n_layers=2, d_ff=None,
                vocab=256, seed=0) -> Dict[str, Any]:
    if key is None:
        key = jax.random.PRNGKey(seed)
    d_ff = d_ff or 4 * d_model
    keys = jax.random.split(key, n_layers * 4 + 2)
    blocks = []
    for i in range(n_layers):
        k0, k1, k2, k3 = keys[4 * i:4 * i + 4]
        blocks.append({
            "ln1": jnp.ones((d_model,), jnp.float32),
            "wqkv": L.xavier_init(k0, (d_model, 3 * d_model)),
            "wo": L.xavier_init(k1, (d_model, d_model)),
            "ln2": jnp.ones((d_model,), jnp.float32),
            "wi": L.xavier_init(k2, (d_model, 2 * d_ff)),   # SwiGLU gate+up
            "wd": L.xavier_init(k3, (d_ff, d_model)),
        })
    return {
        "embed": L.xavier_init(keys[-2], (vocab, d_model)),
        "blocks": blocks,
        "ln_f": jnp.ones((d_model,), jnp.float32),
        "head": L.xavier_init(keys[-1], (d_model, vocab)),
    }


def _mlp(blk, x, dtype):
    gate_up = x @ blk["wi"].astype(dtype)
    gate, up = jnp.split(gate_up, 2, axis=-1)
    return (jax.nn.silu(gate) * up) @ blk["wd"].astype(dtype)


def _qkv(blk, x, n_heads, dtype):
    b, s, d = x.shape
    hd = d // n_heads
    qkv = x @ blk["wqkv"].astype(dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shp = (b, s, n_heads, hd)
    return q.reshape(shp), k.reshape(shp), v.reshape(shp)


def apply_seq(params, ids, *, n_heads=4, dtype=jnp.float32,
              mesh=None, sp_axis: str = "sp", attn: str = "auto"):
    """Full-sequence forward: (B, S) int32 → (B, S, vocab) logits.

    With a mesh, attention runs ring-parallel over `sp_axis` (sequence
    sharded, K/V rotating over ICI). Without, `attn` picks the kernel:
    "pallas" = the flash-attention Pallas kernel (1.6-21x over the XLA
    softmax at S=2k-8k on v5e, measured), "xla" = plain causal softmax,
    "auto" = pallas when the sequence divides its 128-blocks, else xla.
    """
    from nnstreamer_tpu.parallel.ring_attention import (
        reference_attention, ring_attention)

    b, s = ids.shape
    x = params["embed"][ids].astype(dtype)
    pos = jnp.arange(s)
    # explicit attn="pallas" always takes the kernel (flash_attention
    # raises its pad-upstream error on indivisible S rather than
    # silently substituting the XLA path); "auto" requires 128-blocks
    use_pallas = mesh is None and (
        attn == "pallas" or (attn == "auto" and s % 128 == 0))
    for blk in params["blocks"]:
        h = rmsnorm(x, blk["ln1"].astype(dtype))
        q, k, v = _qkv(blk, h, n_heads, dtype)
        q, k = rope(q, pos), rope(k, pos)
        if mesh is not None:
            attn = ring_attention(q, k, v, mesh=mesh, axis=sp_axis,
                                  causal=True)
        elif use_pallas:
            from nnstreamer_tpu.backends.pallas_ops import flash_attention

            bs = 128 if s % 128 == 0 else 16
            attn = flash_attention(q, k, v, causal=True,
                                   block_q=bs, block_k=bs)
        else:
            attn = reference_attention(q, k, v, causal=True)
        attn = attn.reshape(b, s, -1)
        x = x + attn @ blk["wo"].astype(dtype)
        h = rmsnorm(x, blk["ln2"].astype(dtype))
        x = x + _mlp(blk, h, dtype)
    x = rmsnorm(x, params["ln_f"].astype(dtype))
    return (x @ params["head"].astype(dtype)).astype(jnp.float32)


def init_cache(*, batch=1, max_len=128, d_model=64, n_heads=4, n_layers=2):
    """KV cache as TWO stacked tensors (pipeline-friendly state):
    k/v: (L, B, max_len, H, D). Position rides a (1,) int32 tensor."""
    hd = d_model // n_heads
    shape = (n_layers, batch, max_len, n_heads, hd)
    return (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32),
            jnp.zeros((1,), jnp.int32))


def apply_step(params, ids, k_cache, v_cache, pos, *, n_heads=4,
               dtype=jnp.float32):
    """One streaming decode step: ids (B, 1) int32 + cache → logits
    (B, vocab) + updated cache. Static shapes throughout: the cache is a
    TRUE ring — writes land at pos % max_len, so past max_len tokens the
    window slides (sliding-window attention over the last max_len
    tokens; RoPE keys carry absolute positions, so relative geometry
    stays correct across the wrap)."""
    b = ids.shape[0]
    max_len = k_cache.shape[2]
    p = pos.astype(jnp.int32)[0]
    slot = p % max_len
    x = params["embed"][ids[:, 0]][:, None, :].astype(dtype)   # (B,1,D)
    pvec = p[None]
    new_k, new_v = [], []
    for li, blk in enumerate(params["blocks"]):
        h = rmsnorm(x, blk["ln1"].astype(dtype))
        q, k, v = _qkv(blk, h, n_heads, dtype)
        q, k = rope(q, pvec), rope(k, pvec)
        kc = jax.lax.dynamic_update_slice(
            k_cache[li], k.astype(jnp.float32), (0, slot, 0, 0))
        vc = jax.lax.dynamic_update_slice(
            v_cache[li], v.astype(jnp.float32), (0, slot, 0, 0))
        new_k.append(kc)
        new_v.append(vc)
        # attend over the populated window (all slots once wrapped)
        scale = q.shape[-1] ** -0.5
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       kc) * scale                  # (B,H,1,max_len)
        mask = (jnp.arange(max_len) <=
                jnp.minimum(p, max_len - 1))[None, None, None, :]
        s = jnp.where(mask, s, -1e30)
        pattn = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", pattn, vc).astype(dtype)
        x = x + attn.reshape(b, 1, -1) @ blk["wo"].astype(dtype)
        h = rmsnorm(x, blk["ln2"].astype(dtype))
        x = x + _mlp(blk, h, dtype)
    x = rmsnorm(x, params["ln_f"].astype(dtype))
    logits = (x[:, 0] @ params["head"].astype(dtype)).astype(jnp.float32)
    return (logits, jnp.stack(new_k), jnp.stack(new_v),
            (p + 1)[None].astype(jnp.int32))


@register_model("transformer")
def build(d_model: int = 64, n_heads: int = 4, n_layers: int = 2,
          vocab: int = 256, max_len: int = 128, batch: int = 1,
          dtype: str = "float32", seed: int = 0):
    """Streaming-decode bundle: (ids, k_cache, v_cache, pos) →
    (logits, k_cache, v_cache, pos) — state loops through tensor_repo."""
    from nnstreamer_tpu.backends.xla import ModelBundle
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    cdtype = jnp.dtype(dtype)
    params = init_params(d_model=d_model, n_heads=n_heads,
                         n_layers=n_layers, vocab=vocab, seed=seed)
    hd = d_model // n_heads
    cshape = (n_layers, batch, max_len, n_heads, hd)

    def fn(params, ids, k_cache, v_cache, pos):
        return apply_step(params, ids, k_cache, v_cache, pos,
                          n_heads=n_heads, dtype=cdtype)

    in_spec = TensorsSpec.of(
        TensorInfo((batch, 1), DType.INT32, name="ids"),
        TensorInfo(cshape, DType.FLOAT32, name="k_cache"),
        TensorInfo(cshape, DType.FLOAT32, name="v_cache"),
        TensorInfo((1,), DType.INT32, name="pos"),
    )
    out_spec = TensorsSpec.of(
        TensorInfo((batch, vocab), DType.FLOAT32, name="logits"),
        TensorInfo(cshape, DType.FLOAT32, name="k_cache"),
        TensorInfo(cshape, DType.FLOAT32, name="v_cache"),
        TensorInfo((1,), DType.INT32, name="pos"),
    )
    return ModelBundle(fn=fn, params=params, in_spec=in_spec,
                       out_spec=out_spec, name="transformer")
