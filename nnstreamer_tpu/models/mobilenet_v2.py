"""MobileNetV2 — the flagship classification model (zoo://mobilenet_v2).

Covers the reference's headline pipeline: tensor_filter running
mobilenet_v2_1.0_224_quant.tflite for image labeling
(tests/nnstreamer_filter_tensorflow_lite/runTest.sh, BASELINE.md config 1)
— rebuilt as traced JAX code so the surrounding tensor_transform chain
fuses into the same XLA computation.

Architecture: Sandler et al. 2018 inverted residuals, width-multiplier
aware, NHWC, bf16 compute / f32 params. Output is 1001 classes
(background + ImageNet), matching the reference model's label layout.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import layers as L
from nnstreamer_tpu.models.zoo import register_model

# (expansion t, out channels c, repeats n, first stride s) — the paper's
# table 2 / standard 1.0 config.
_INVERTED_RESIDUAL_CFG = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


def _make_divisible(v: float, divisor: int = 8) -> int:
    """Round channel counts the MobileNet way (multiples of 8 — also the
    TPU-friendly lane multiple)."""
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def init_block(key, cin: int, cout: int, t: int, stride: int) -> Dict[str, Any]:
    hidden = cin * t
    keys = jax.random.split(key, 3)
    p: Dict[str, Any] = {}
    if t != 1:
        p["expand"] = L.init_conv_bn(keys[0], 1, 1, cin, hidden)
    p["depthwise"] = L.init_conv_bn(keys[1], 3, 3, hidden, hidden, groups=hidden)
    p["project"] = L.init_conv_bn(keys[2], 1, 1, hidden, cout)
    return p


def block_apply(p, x, *, cin, cout, t, stride, train=False, dtype=None):
    h = x
    if t != 1:
        h = L.conv_bn(p["expand"], h, train=train, dtype=dtype)
    h = L.conv_bn(p["depthwise"], h, stride=stride,
                  groups=h.shape[-1], train=train, dtype=dtype)
    h = L.conv_bn(p["project"], h, act=None, train=train, dtype=dtype)
    if stride == 1 and cin == cout:
        h = h + x
    return h


def init_params(key=None, *, width: float = 1.0, num_classes: int = 1001,
                seed: int = 0) -> Dict[str, Any]:
    if key is None:
        key = jax.random.PRNGKey(seed)
    n_blocks = sum(n for _, _, n, _ in _INVERTED_RESIDUAL_CFG)
    keys = jax.random.split(key, n_blocks + 3)
    ki = iter(range(n_blocks + 3))

    stem_out = _make_divisible(32 * width)
    params: Dict[str, Any] = {
        "stem": L.init_conv_bn(keys[next(ki)], 3, 3, 3, stem_out),
        "blocks": [],
    }
    cin = stem_out
    for t, c, n, s in _INVERTED_RESIDUAL_CFG:
        cout = _make_divisible(c * width)
        for i in range(n):
            stride = s if i == 0 else 1
            params["blocks"].append(init_block(keys[next(ki)], cin, cout, t, stride))
            cin = cout
    head_out = _make_divisible(1280 * max(1.0, width))
    params["head"] = L.init_conv_bn(keys[next(ki)], 1, 1, cin, head_out)
    params["classifier"] = L.init_dense(keys[next(ki)], head_out, num_classes)
    return params


def apply(params, x, *, width: float = 1.0, train: bool = False,
          dtype=jnp.bfloat16, features_only: bool = False):
    """Forward. x: NHWC float (any float dtype), already normalized to
    roughly [-1, 1]. Returns logits (N, num_classes) in float32, or the
    list of stride-{8,16,32} feature maps when features_only (SSD use).
    """
    x = x.astype(dtype)
    h = L.conv_bn(params["stem"], x, stride=2, train=train, dtype=dtype)
    feats = []
    bi = 0
    cin = h.shape[-1]
    for t, c, n, s in _INVERTED_RESIDUAL_CFG:
        cout = _make_divisible(c * width)
        for i in range(n):
            stride = s if i == 0 else 1
            if stride == 2:
                feats.append(h)
            h = block_apply(params["blocks"][bi], h, cin=cin, cout=cout,
                            t=t, stride=stride, train=train, dtype=dtype)
            cin = cout
            bi += 1
    h = L.conv_bn(params["head"], h, train=train, dtype=dtype)
    if features_only:
        feats.append(h)
        return feats
    h = L.global_avg_pool(h)
    logits = L.dense(params["classifier"], h, dtype=dtype)
    return logits.astype(jnp.float32)


def loss_fn(params, x, labels, *, width: float = 1.0, dtype=jnp.bfloat16):
    """Softmax cross-entropy training loss (used by trainer/ and the
    multichip dry-run train step)."""
    logits = apply(params, x, width=width, train=True, dtype=dtype)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)


@register_model("mobilenet_v2")
def build(width: float = 1.0, num_classes: int = 1001, input_size: int = 224,
          batch: int = 1, dtype: str = "bfloat16", seed: int = 0):
    from nnstreamer_tpu.backends.xla import ModelBundle
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    cdtype = jnp.dtype(dtype)
    params = init_params(width=width, num_classes=num_classes, seed=seed)

    def fn(params, x):
        return apply(params, x, width=width, dtype=cdtype)

    in_spec = TensorsSpec.of(
        TensorInfo((batch, input_size, input_size, 3), DType.FLOAT32)
    )
    out_spec = TensorsSpec.of(TensorInfo((batch, num_classes), DType.FLOAT32))
    return ModelBundle(fn=fn, params=params, in_spec=in_spec,
                       out_spec=out_spec, name=f"mobilenet_v2_{width}")
