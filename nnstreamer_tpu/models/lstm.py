"""Streaming LSTM (zoo://lstm) — the recurrent-state use case.

Reference parity: the RNN/LSTM custom-filter examples driven through
tensor_repo feedback loops (tests/nnstreamer_repo_{rnn,lstm},
tests/nnstreamer_example/custom_example_{RNN,LSTM}). Here the cell is a
real traced LSTM whose (h, c) state flows through the pipeline as
tensors — pair it with tensor_repo_src/sink to close the loop:

    tensor_repo_src (state) ─┐
    appsrc (x)              ─┴→ tensor_mux → tensor_filter(zoo://lstm)
                                 → tensor_demux ┬→ outputs
                                                └→ tensor_repo_sink

Model signature: fn(params, x, h, c) → (y, h', c') with x (B, D_in),
h/c (B, D_hidden).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import layers as L
from nnstreamer_tpu.models.zoo import register_model


def init_params(key=None, *, d_in: int = 32, d_hidden: int = 64,
                seed: int = 0) -> Dict[str, Any]:
    if key is None:
        key = jax.random.PRNGKey(seed)
    kx, kh = jax.random.split(key)
    # one fused kernel for the 4 gates (i, f, g, o) — a single MXU matmul
    return {
        "wx": L.xavier_init(kx, (d_in, 4 * d_hidden)),
        "wh": L.xavier_init(kh, (d_hidden, 4 * d_hidden)),
        "b": jnp.zeros((4 * d_hidden,), jnp.float32),
    }


def apply(params, x, h, c, *, dtype=jnp.float32):
    x = x.astype(dtype)
    h = h.astype(dtype)
    c = c.astype(dtype)
    z = x @ params["wx"].astype(dtype) + h @ params["wh"].astype(dtype) \
        + params["b"].astype(dtype)
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c_new = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    return (h_new.astype(jnp.float32), h_new.astype(jnp.float32),
            c_new.astype(jnp.float32))


@register_model("lstm")
def build(d_in: int = 32, d_hidden: int = 64, batch: int = 1,
          dtype: str = "float32", seed: int = 0):
    from nnstreamer_tpu.backends.xla import ModelBundle
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    cdtype = jnp.dtype(dtype)
    params = init_params(d_in=d_in, d_hidden=d_hidden, seed=seed)

    def fn(params, x, h, c):
        return apply(params, x, h, c, dtype=cdtype)

    in_spec = TensorsSpec.of(
        TensorInfo((batch, d_in), DType.FLOAT32, name="x"),
        TensorInfo((batch, d_hidden), DType.FLOAT32, name="h"),
        TensorInfo((batch, d_hidden), DType.FLOAT32, name="c"),
    )
    out_spec = TensorsSpec.of(
        TensorInfo((batch, d_hidden), DType.FLOAT32, name="y"),
        TensorInfo((batch, d_hidden), DType.FLOAT32, name="h"),
        TensorInfo((batch, d_hidden), DType.FLOAT32, name="c"),
    )
    return ModelBundle(fn=fn, params=params, in_spec=in_spec,
                       out_spec=out_spec, name="lstm")
