"""Shared plain-JAX NN layers for the model zoo.

Design: models are *pure functions* over explicit param pytrees (nested
dicts of jnp arrays) — no framework classes. This keeps every model
directly jit/pjit/shard_map-able and makes param sharding rules trivial
to express as pytree paths (parallel/mesh.py).

TPU-first conventions:
- NHWC layouts and channel-last convs: XLA tiles these onto the MXU.
- Channel counts padded to multiples of 8 where architectures allow.
- `dtype` threading: params live in float32 (optimizer precision), the
  forward cast to bfloat16 happens at the compute boundary so matmuls/
  convs run in bf16 on the MXU with float32 accumulation (the default
  `preferred_element_type` behavior).

Replaces: the reference has no model code at all — its models are opaque
vendor files run by filter subplugins (SURVEY.md §2.3). A TPU-native
framework ships models as traced code so transforms fuse around them.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# Initializers (deterministic given the key)
# ---------------------------------------------------------------------------

def _fan_in_out(shape: Sequence[int]) -> Tuple[int, int]:
    if len(shape) == 2:  # dense (in, out)
        return shape[0], shape[1]
    # conv HWIO: receptive * in, receptive * out
    receptive = math.prod(shape[:-2])
    return receptive * shape[-2], receptive * shape[-1]


def kaiming_init(key, shape, dtype=jnp.float32):
    fan_in, _ = _fan_in_out(shape)
    std = math.sqrt(2.0 / max(1, fan_in))
    return std * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)


def xavier_init(key, shape, dtype=jnp.float32):
    fan_in, fan_out = _fan_in_out(shape)
    limit = math.sqrt(6.0 / max(1, fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -limit, limit)


# ---------------------------------------------------------------------------
# Conv / BN / dense primitives. Params are dicts; init_* builds them.
# ---------------------------------------------------------------------------

def init_conv(key, kh, kw, cin, cout, *, groups: int = 1) -> Params:
    """HWIO conv kernel. groups=cin & cout=cin → depthwise."""
    w = kaiming_init(key, (kh, kw, cin // groups, cout))
    return {"w": w}


def conv2d(p: Params, x, *, stride: int = 1, padding="SAME",
           groups: int = 1, dtype=None):
    w = p["w"]
    if dtype is not None:
        w = w.astype(dtype)
        x = x.astype(dtype)
    return lax.conv_general_dilated(
        x, w,
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
    )


def init_bn(cout: int) -> Params:
    return {
        "scale": jnp.ones((cout,), jnp.float32),
        "bias": jnp.zeros((cout,), jnp.float32),
        "mean": jnp.zeros((cout,), jnp.float32),
        "var": jnp.ones((cout,), jnp.float32),
    }


def batch_norm(p: Params, x, *, train: bool = False, eps: float = 1e-3):
    """Inference BN uses stored stats; train uses batch stats.

    Returns (y, batch_stats) where batch_stats is (mean, var) under
    train=True (for the caller to fold into running stats) else None.
    """
    if train:
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(x.astype(jnp.float32), axis=axes)
        var = jnp.var(x.astype(jnp.float32), axis=axes)
        stats = (mean, var)
    else:
        mean, var = p["mean"], p["var"]
        stats = None
    inv = lax.rsqrt(var + eps) * p["scale"]
    y = (x - mean.astype(x.dtype)) * inv.astype(x.dtype) + p["bias"].astype(x.dtype)
    return y, stats


def init_dense(key, cin: int, cout: int) -> Params:
    kw, _ = jax.random.split(key)
    return {"w": xavier_init(kw, (cin, cout)), "b": jnp.zeros((cout,), jnp.float32)}


def dense(p: Params, x, *, dtype=None):
    w, b = p["w"], p["b"]
    if dtype is not None:
        w, b, x = w.astype(dtype), b.astype(dtype), x.astype(dtype)
    return x @ w + b


def relu6(x):
    return jnp.clip(x, 0.0, 6.0)


# ---------------------------------------------------------------------------
# Conv + BN (+relu6) block — the MobileNet building unit
# ---------------------------------------------------------------------------

def init_conv_bn(key, kh, kw, cin, cout, *, groups: int = 1) -> Params:
    return {"conv": init_conv(key, kh, kw, cin, cout, groups=groups),
            "bn": init_bn(cout)}


def conv_bn(p: Params, x, *, stride=1, groups=1, act=relu6,
            train: bool = False, dtype=None):
    y = conv2d(p["conv"], x, stride=stride, groups=groups, dtype=dtype)
    y, _ = batch_norm(p["bn"], y, train=train)
    return act(y) if act is not None else y


def global_avg_pool(x):
    """NHWC → NC mean over spatial dims."""
    return jnp.mean(x, axis=(1, 2))


def count_params(params) -> int:
    return sum(int(a.size) for a in jax.tree_util.tree_leaves(params))
