"""W8A8 quantized matmul path for the transformer (MXU int8).

On v5e the MXU runs int8×int8→int32 at ~2× the bf16 rate (measured
376–496 TOP/s vs the 197 TFLOP/s bf16 peak — bench `mxu_peak`), but
int8 NHWC *convolutions* lose to relayout costs on this backend, so the
int8 story here targets what actually wins: the transformer's large
matmuls. Weights are quantized per-output-channel (symmetric int8),
activations per-token at runtime (dynamic symmetric int8 — one amax +
scale per row, fused by XLA into the surrounding elementwise work), and
the int32 accumulator is rescaled in f32. Attention stays in bf16
(the flash kernel path); RMSNorm/softmax/rope stay f32/bf16 — only the
MXU-bound projections change.

This mirrors the role of the reference's quantized execution providers
(`tensor_filter_tensorrt.cc` int8 calibration, `tensor_filter_snpe`
quantized DLCs): quantization as an execution feature with the accuracy
contract checked against the float path (tests).

**Measured perf reality on v5e**: the int8 dot itself runs ~2-3× the
bf16 rate at transformer shapes, and the former bottleneck — the
dynamic activation-quant pass, which as plain XLA ops made ~3 HBM
trips over the activations and cost more than the matmul it fed
(0.62 ms vs 0.13 ms at 16384×1024; round 4 measured the whole W8A8
matmul at 0.74× bf16 because of it) — is now a single-VMEM-pass
Pallas kernel (`backends/pallas_ops.quantize_rows`). With it the full
W8A8 matmul measures **1.9× the bf16 matmul** (0.37 vs 0.71 ms at
16384×1024×3072, round 5): W8A8 is a genuine perf path for MXU-bound
projections, not just an accuracy-verified capability. Int8
*convolutions* still lose to relayout on this backend, so
tflite_quant.py keeps dequantize→bf16 as its conv default.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np


def quantize_weight(w, axis: int = 1):
    """Symmetric per-output-channel int8 quantization of a 2-D weight.

    `axis` is the OUTPUT dim (scales broadcast over it on dequant)."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=1 - axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def quantize_transformer(params: Dict[str, Any]) -> Dict[str, Any]:
    """Float transformer params → W8A8 params: every large matmul
    weight (wqkv/wo/wi/wd/head) becomes (int8, per-col scale); norms,
    embeddings and everything small stay float."""
    out: Dict[str, Any] = {"embed": params["embed"],
                           "ln_f": params["ln_f"], "blocks": []}
    for blk in params["blocks"]:
        qblk = {"ln1": blk["ln1"], "ln2": blk["ln2"]}
        for name in ("wqkv", "wo", "wi", "wd"):
            q, s = quantize_weight(blk[name])
            qblk[name] = q
            qblk[f"{name}_scale"] = s
        out["blocks"].append(qblk)
    q, s = quantize_weight(params["head"])
    out["head"], out["head_scale"] = q, s
    return out


def w8a8_matmul(x, w_q, w_scale):
    """(…, K) f32/bf16 × int8 (K, N) → (…, N) f32.

    Dynamic per-row activation quantization (the Pallas single-pass
    `quantize_rows` kernel), int8×int8→int32 on the MXU, one fused
    rescale. Expressed in plain XLA the quant pass made ~3 HBM trips
    over the activations and cost more than the int8 dot it feeds;
    with the fused kernel the whole W8A8 matmul measured **1.9× the
    bf16 matmul** at 16384×1024×3072 on v5e (0.37 vs 0.71 ms, round
    5) — see the perf-reality note in the module docstring. Row counts
    that can't tile the kernel fall back to the equivalent XLA
    expression inside quantize_rows itself."""
    from nnstreamer_tpu.backends.pallas_ops import quantize_rows

    lead = x.shape[:-1]
    k = x.shape[-1]
    x2 = x.reshape(-1, k)
    x_q, x_scale = quantize_rows(x2)
    acc = jax.lax.dot_general(
        x_q, w_q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * x_scale * w_scale.reshape(1, -1)
    return out.reshape(lead + (out.shape[-1],))


def apply_seq_w8a8(params_q, ids, *, n_heads=4, attn: str = "auto",
                   dtype=jnp.float32):
    """Full-sequence forward with W8A8 projections — the quantized twin
    of transformer.apply_seq (same block structure, same attention
    kernels; only the big matmuls run int8).

    `dtype` is the inter-op activation dtype, exactly like apply_seq's:
    pass bfloat16 for the perf path — the int8 matmuls don't care (they
    re-quantize their input rows), but f32 activations double every
    residual/norm/attention HBM trip between them (measured: the f32
    default ran a d=1024 prefill SLOWER than bf16 apply_seq even with
    each matmul 1.9× faster; bf16 activations let the matmul win
    through, see PARITY)."""
    from nnstreamer_tpu.models import transformer as T
    from nnstreamer_tpu.parallel.ring_attention import reference_attention

    b, s = ids.shape
    x = params_q["embed"][ids].astype(dtype)
    pos = jnp.arange(s)
    use_pallas = attn == "pallas" or (attn == "auto" and s % 128 == 0)
    for blk in params_q["blocks"]:
        h = T.rmsnorm(x, blk["ln1"].astype(dtype))
        qkv = w8a8_matmul(h, blk["wqkv"], blk["wqkv_scale"]).astype(dtype)
        d = x.shape[-1]
        hd = d // n_heads
        kv_dim = (qkv.shape[-1] - d) // 2
        n_kv = kv_dim // hd
        q = qkv[..., :d].reshape(b, s, n_heads, hd)
        k = qkv[..., d:d + kv_dim].reshape(b, s, n_kv, hd)
        v = qkv[..., d + kv_dim:].reshape(b, s, n_kv, hd)
        q, k = T.rope(q, pos), T.rope(k, pos)
        k, v = T._expand_kv(k, n_heads), T._expand_kv(v, n_heads)
        if use_pallas:
            from nnstreamer_tpu.backends.pallas_ops import flash_attention

            attn_out = flash_attention(q.astype(jnp.bfloat16),
                                       k.astype(jnp.bfloat16),
                                       v.astype(jnp.bfloat16),
                                       causal=True)
        else:
            attn_out = reference_attention(q, k, v, causal=True)
        attn_out = attn_out.reshape(b, s, -1).astype(dtype)
        x = x + w8a8_matmul(attn_out, blk["wo"],
                            blk["wo_scale"]).astype(dtype)
        h = T.rmsnorm(x, blk["ln2"].astype(dtype))
        gate_up = w8a8_matmul(h, blk["wi"], blk["wi_scale"]).astype(dtype)
        gate, up = jnp.split(gate_up, 2, axis=-1)
        x = x + w8a8_matmul(jax.nn.silu(gate) * up, blk["wd"],
                            blk["wd_scale"]).astype(dtype)
    x = T.rmsnorm(x, params_q["ln_f"].astype(dtype))
    return w8a8_matmul(x, params_q["head"], params_q["head_scale"])


def apply_step_w8a8(params_q, ids, k_cache, v_cache, pos, *, n_heads=4,
                    dtype=jnp.bfloat16):
    """One streaming decode step with W8A8 projections — the quantized
    twin of transformer.apply_step, sharing the float path's exact body
    (`transformer._step_impl`: ring-slot write-through, RoPE, GQA
    expansion, f32 softmax); only the five projection matmuls differ.

    At decode the matmuls are skinny (M = batch rows): the win is the
    int8 WEIGHTS halving the per-step weight sweep — measured round 5
    at d=1024/4L/B=8 (scan-timed, subprocess-isolated builder probes):
    0.104 vs 0.132 ms/step at max_len=256 (+26%, 77k tok/s) and 0.80
    vs 0.91 at max_len=2048 (+13%) where the bf16 KV sweep takes a
    larger share. The driver-capturable `w8a8_decode` bench row runs
    the max_len=2048 point. `dtype` is the inter-op activation dtype
    (bf16 default — the f32 lesson from apply_seq_w8a8 applies here
    too)."""
    from nnstreamer_tpu.models.transformer import _step_impl

    def proj(store, name, x):
        out = w8a8_matmul(x, store[name], store[f"{name}_scale"])
        return out.astype(dtype)

    return _step_impl(params_q, ids, k_cache, v_cache, pos, n_heads,
                      dtype, proj)
