"""Graph optimization: transform→filter fusion + device segments.

The north-star optimization (BASELINE.json): linear chains of
`tensor_transform` elements adjacent to a `tensor_filter` are removed from
the graph and their compiled programs handed to the filter, whose backend
traces them into the *same* jit computation as the model. Pre/post
elementwise work then fuses with the model's HLO — no per-element hops, no
extra HBM round trips. The reference instead runs each transform as a
separate GstBaseTransform pass with its own memcpy (gsttensor_transform.c).

`fuse_segments` goes one level further (profiled-segment execution on
TPUs, arXiv 2503.01025): maximal linear runs of
transform → filter → transform → filter … → decoder(device=true) collapse
into ONE surviving head filter whose backend traces every member model
(and the connecting transform chains) into a single bucketed jit — one
dispatch per segment, tensors resident in HBM end-to-end.

Fusion is semantics-preserving: negotiation runs after rewriting, and a
backend that declines fusion gets the chains applied host-side by the
filter element (elements/filter.py), so results are identical either way.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.graph.pipeline import Pipeline

log = get_logger("optimize")


def _is_fusable_transform(pipe: Pipeline, elem) -> bool:
    from nnstreamer_tpu.elements.transform import TensorTransform

    return (
        isinstance(elem, TensorTransform)
        and len(pipe.links_to(elem)) == 1
        and len(pipe.links_from(elem)) == 1
        # a transform with its own error policy must stay a separate
        # element — fused into the filter, its failures would be
        # charged to (and policied by) the filter instead
        and elem.error_policy.kind == "fail"
    )


def chain_fn(programs) -> Optional[Callable]:
    """Tuple-to-tuple elementwise fn applying `programs` in dataflow order.

    Picks numpy for host arrays and jax.numpy for device arrays/tracers,
    so the same chain works host-side and inside a jit trace.
    """
    if not programs:
        return None

    def chain(tensors: Tuple) -> Tuple:
        out = []
        for t in tensors:
            xp = np if isinstance(t, np.ndarray) else _jnp()
            for prog in programs:
                t = prog.apply(xp, t)
            out.append(t)
        return tuple(out)

    return chain


def transfer_spec(programs, spec):
    """Static shape/dtype transfer of a program chain over a TensorsSpec."""
    from dataclasses import replace

    if not programs:
        return spec
    infos = []
    for info in spec.tensors:
        for prog in programs:
            info = prog.out_info(info)
        infos.append(info)
    return replace(spec, tensors=tuple(infos))


def _jnp():
    import jax.numpy as jnp

    return jnp


def _segment_head_ok(f) -> bool:
    """Can `f` anchor a multi-filter device segment? The head survives in
    the graph, keeps its own props/policy (which then govern the whole
    segment), and its backend hosts the composed jit."""
    return (
        f._framework_name() == "xla"
        # dynamic shapes / output rerouting change the tuple contract the
        # composed trace relies on
        and not f.props.get("invoke_dynamic")
        and not f.props.get("output_combination")
    )


def _segment_member_ok(pipe: Pipeline, e) -> bool:
    """Can `e` be absorbed into an upstream head's segment? Members
    vanish from the graph, so anything that gives a member independent
    runtime behavior (its own error policy, breaker, sync latency
    timing, combination routing, manual reload) keeps it separate."""
    from nnstreamer_tpu.elements.filter import TensorFilter

    return (
        isinstance(e, TensorFilter)
        and len(pipe.links_to(e)) == 1
        and len(pipe.links_from(e)) == 1
        and e.error_policy.kind == "fail"
        and e._framework_name() == "xla"
        and not e.props.get("invoke_dynamic")
        and not e.props.get("input_combination")
        and not e.props.get("output_combination")
        and e.props.get("latency_mode") != "sync"
        and not e.props.get("breaker_threshold")
        and not e.props.get("shared_tensor_filter_key")
        and not e.props.get("is_updatable")
        and not e._members
    )


def fuse_segments(pipe: Pipeline, plan=None) -> int:
    """Collapse filter→transform→filter runs into the upstream filter.

    For each eligible head filter, repeatedly: walk the downstream
    linear run of fusable transforms; if it lands on an eligible member
    filter, splice the transforms + member out of the graph and hand
    them to the head (`TensorFilter.absorb_member`). The head's backend
    then traces member models (+ connecting chains) into one jit
    (`XLABackend.compose_segment`); a declining backend gets the member
    invokes applied host-side by the head, so results are identical.

    A placement plan (`serving/placement.SegmentPlan`, passed here or
    installed on the pipeline by `apply_plan` as `pipe.segment_plan`)
    bounds the splice: absorption stops at a planned cut, so each stage
    composes into ONE per-device unit and the cuts survive as real
    element boundaries where the cross-device handoff (the next stage
    backend's device_put staging) happens.

    Run BEFORE `fuse_transforms`: the head's pre chain, the post chain
    trailing the *last* member, and a trailing device decoder are all
    absorbed by the ordinary transform pass afterwards.

    → number of elements removed from the graph.
    """
    from nnstreamer_tpu.elements.filter import TensorFilter

    plan = plan if plan is not None else getattr(pipe, "segment_plan", None)
    stage_of = plan.stage_of() if plan is not None else {}
    removed = 0
    for f in [e for e in list(pipe.elements.values())
              if isinstance(e, TensorFilter)]:
        # upstream heads run first (insertion order ≈ dataflow order for
        # parse_launch); a filter absorbed earlier is gone from the graph
        if f.name not in pipe.elements or not _segment_head_ok(f):
            continue
        while True:
            mids: List = []
            cur = f
            ok = True
            while True:
                out_links = pipe.links_from(cur)
                if len(out_links) != 1:
                    ok = False
                    break
                nxt = out_links[0].dst
                if _is_fusable_transform(pipe, nxt):
                    mids.append(nxt)
                    cur = nxt
                    continue
                break
            if not ok:
                break
            member = pipe.links_from(cur)[0].dst
            if not _segment_member_ok(pipe, member):
                break   # transforms (if any) stay for fuse_transforms
            if stage_of and stage_of.get(member.name, stage_of.get(
                    f.name)) != stage_of.get(f.name):
                log.info(
                    "segment: plan cut between %s (stage %s) and %s "
                    "(stage %s) — not absorbed", f.name,
                    stage_of.get(f.name), member.name,
                    stage_of.get(member.name))
                break   # planned cut: the member heads its own stage
            for t in mids:
                _remove_linear_element(pipe, t)
            _remove_linear_element(pipe, member)
            f.absorb_member([t.program for t in mids], member)
            removed += 1 + len(mids)
            log.info(
                "segment: absorbed filter %s (+%d transform(s)) into %s",
                member.name, len(mids), f.name,
            )
    return removed


def fuse_transforms(pipe: Pipeline) -> int:
    """Rewrite the graph in place; → number of transforms fused."""
    from nnstreamer_tpu.elements.filter import TensorFilter

    fused = 0
    for f in [e for e in list(pipe.elements.values()) if isinstance(e, TensorFilter)]:
        pre_programs = []
        # walk upstream: ... -> t2 -> t1 -> filter   (apply order t2, t1? no:
        # dataflow order is t2 then t1; collect from filter upward, reverse)
        up: List = []
        cur = f
        while True:
            in_links = pipe.links_to(cur)
            if len(in_links) != 1:
                break
            prev = in_links[0].src
            if not _is_fusable_transform(pipe, prev):
                break
            up.append(prev)
            cur = prev
        up.reverse()  # dataflow order
        pre_programs = [t.program for t in up]

        down: List = []
        cur = f
        while True:
            out_links = pipe.links_from(cur)
            if len(out_links) != 1:
                break
            nxt = out_links[0].dst
            if not _is_fusable_transform(pipe, nxt):
                break
            down.append(nxt)
            cur = nxt
        post_programs = [t.program for t in down]

        # a device-mode decoder directly after the post chain traces into
        # the same XLA program: model + postprocess in ONE dispatch
        dec = None
        out_links = pipe.links_from(cur)
        if (len(out_links) == 1 and not f.props.get("invoke_dynamic")
                and not f.props.get("output_combination")):
            cand = out_links[0].dst
            if (_is_device_decoder(cand)
                    and len(pipe.links_to(cand)) == 1
                    and len(pipe.links_from(cand)) == 1):
                dec = cand

        if not pre_programs and not post_programs and dec is None:
            continue
        for t in up + down:
            _remove_linear_element(pipe, t)
            fused += 1
        f.set_fusion(pre_programs, post_programs)
        if dec is not None:
            _remove_linear_element(pipe, dec)
            f.set_decoder_fusion(dec.sub)
            fused += 1
        log.info(
            "fused %d pre + %d post transform(s)%s into %s",
            len(pre_programs), len(post_programs),
            " + device decoder" if dec is not None else "", f.name,
        )
    return fused


def _is_device_decoder(elem) -> bool:
    from nnstreamer_tpu.elements.decoder import TensorDecoder

    # device=compact keeps its host decode stage, so the element must
    # stay in the graph (only full device decodes fold into the filter)
    return (isinstance(elem, TensorDecoder)
            and elem.props.get("device") is True)


def _remove_linear_element(pipe: Pipeline, elem) -> None:
    """Remove a 1-in/1-out element, splicing its neighbours together."""
    (in_link,) = pipe.links_to(elem)
    (out_link,) = pipe.links_from(elem)
    pipe.links.remove(in_link)
    pipe.links.remove(out_link)
    del pipe.elements[elem.name]
    pipe._negotiated = False
    # splice: src pad of upstream → sink pad of downstream
    from nnstreamer_tpu.graph.pipeline import Link

    pipe.links.append(
        Link(in_link.src, in_link.src_pad, out_link.dst, out_link.dst_pad)
    )
