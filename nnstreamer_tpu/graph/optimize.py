"""Graph optimization: transform→filter fusion.

The north-star optimization (BASELINE.json): linear chains of
`tensor_transform` elements adjacent to a `tensor_filter` are removed from
the graph and their compiled programs handed to the filter, whose backend
traces them into the *same* jit computation as the model. Pre/post
elementwise work then fuses with the model's HLO — no per-element hops, no
extra HBM round trips. The reference instead runs each transform as a
separate GstBaseTransform pass with its own memcpy (gsttensor_transform.c).

Fusion is semantics-preserving: negotiation runs after rewriting, and a
backend that declines fusion gets the chains applied host-side by the
filter element (elements/filter.py), so results are identical either way.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.graph.pipeline import Pipeline

log = get_logger("optimize")


def _is_fusable_transform(pipe: Pipeline, elem) -> bool:
    from nnstreamer_tpu.elements.transform import TensorTransform

    return (
        isinstance(elem, TensorTransform)
        and len(pipe.links_to(elem)) == 1
        and len(pipe.links_from(elem)) == 1
        # a transform with its own error policy must stay a separate
        # element — fused into the filter, its failures would be
        # charged to (and policied by) the filter instead
        and elem.error_policy.kind == "fail"
    )


def chain_fn(programs) -> Optional[Callable]:
    """Tuple-to-tuple elementwise fn applying `programs` in dataflow order.

    Picks numpy for host arrays and jax.numpy for device arrays/tracers,
    so the same chain works host-side and inside a jit trace.
    """
    if not programs:
        return None

    def chain(tensors: Tuple) -> Tuple:
        out = []
        for t in tensors:
            xp = np if isinstance(t, np.ndarray) else _jnp()
            for prog in programs:
                t = prog.apply(xp, t)
            out.append(t)
        return tuple(out)

    return chain


def transfer_spec(programs, spec):
    """Static shape/dtype transfer of a program chain over a TensorsSpec."""
    from dataclasses import replace

    if not programs:
        return spec
    infos = []
    for info in spec.tensors:
        for prog in programs:
            info = prog.out_info(info)
        infos.append(info)
    return replace(spec, tensors=tuple(infos))


def _jnp():
    import jax.numpy as jnp

    return jnp


def fuse_transforms(pipe: Pipeline) -> int:
    """Rewrite the graph in place; → number of transforms fused."""
    from nnstreamer_tpu.elements.filter import TensorFilter

    fused = 0
    for f in [e for e in list(pipe.elements.values()) if isinstance(e, TensorFilter)]:
        pre_programs = []
        # walk upstream: ... -> t2 -> t1 -> filter   (apply order t2, t1? no:
        # dataflow order is t2 then t1; collect from filter upward, reverse)
        up: List = []
        cur = f
        while True:
            in_links = pipe.links_to(cur)
            if len(in_links) != 1:
                break
            prev = in_links[0].src
            if not _is_fusable_transform(pipe, prev):
                break
            up.append(prev)
            cur = prev
        up.reverse()  # dataflow order
        pre_programs = [t.program for t in up]

        down: List = []
        cur = f
        while True:
            out_links = pipe.links_from(cur)
            if len(out_links) != 1:
                break
            nxt = out_links[0].dst
            if not _is_fusable_transform(pipe, nxt):
                break
            down.append(nxt)
            cur = nxt
        post_programs = [t.program for t in down]

        # a device-mode decoder directly after the post chain traces into
        # the same XLA program: model + postprocess in ONE dispatch
        dec = None
        out_links = pipe.links_from(cur)
        if (len(out_links) == 1 and not f.props.get("invoke_dynamic")
                and not f.props.get("output_combination")):
            cand = out_links[0].dst
            if (_is_device_decoder(cand)
                    and len(pipe.links_to(cand)) == 1
                    and len(pipe.links_from(cand)) == 1):
                dec = cand

        if not pre_programs and not post_programs and dec is None:
            continue
        for t in up + down:
            _remove_linear_element(pipe, t)
            fused += 1
        f.set_fusion(pre_programs, post_programs)
        if dec is not None:
            _remove_linear_element(pipe, dec)
            f.set_decoder_fusion(dec.sub)
            fused += 1
        log.info(
            "fused %d pre + %d post transform(s)%s into %s",
            len(pre_programs), len(post_programs),
            " + device decoder" if dec is not None else "", f.name,
        )
    return fused


def _is_device_decoder(elem) -> bool:
    from nnstreamer_tpu.elements.decoder import TensorDecoder

    # device=compact keeps its host decode stage, so the element must
    # stay in the graph (only full device decodes fold into the filter)
    return (isinstance(elem, TensorDecoder)
            and elem.props.get("device") is True)


def _remove_linear_element(pipe: Pipeline, elem) -> None:
    """Remove a 1-in/1-out element, splicing its neighbours together."""
    (in_link,) = pipe.links_to(elem)
    (out_link,) = pipe.links_from(elem)
    pipe.links.remove(in_link)
    pipe.links.remove(out_link)
    del pipe.elements[elem.name]
    pipe._negotiated = False
    # splice: src pad of upstream → sink pad of downstream
    from nnstreamer_tpu.graph.pipeline import Link

    pipe.links.append(
        Link(in_link.src, in_link.src_pad, out_link.dst, out_link.dst_pad)
    )
