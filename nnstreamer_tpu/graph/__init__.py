"""Pipeline graph: elements, links, static negotiation, string DSL.

The reference delegates graph topology, caps negotiation and scheduling to
GStreamer core; this package is our replacement substrate. Key design
choice (TPU-first): negotiation runs **once at build time** over the whole
graph and produces a static `TensorsSpec` per link — so the steady-state
loop has zero type checks and every filter sees static shapes, which is
exactly what XLA tracing needs.
"""

from nnstreamer_tpu.graph.media import AudioSpec, MediaSpec, OctetSpec, TextSpec, VideoSpec
from nnstreamer_tpu.graph.pipeline import Element, Pipeline, SinkElement, SourceElement
from nnstreamer_tpu.graph.parse import parse_launch

__all__ = [
    "MediaSpec",
    "VideoSpec",
    "AudioSpec",
    "TextSpec",
    "OctetSpec",
    "Element",
    "SourceElement",
    "SinkElement",
    "Pipeline",
    "parse_launch",
]
