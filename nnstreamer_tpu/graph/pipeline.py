"""Pipeline graph model and build-time negotiation.

Replaces the GStreamer substrate the reference leans on: elements, pads,
links, and a single-pass static negotiation that assigns every link a
`TensorsSpec`/`MediaSpec` before any data flows (the caps-negotiation
analog, run once — SURVEY.md §1 property 1).

Element model (push-based, mirrors §3.2's hot loop without BaseTransform):

- `SourceElement.generate()` yields buffers (driven by the scheduler).
- `Element.process(pad, buf)` → list of (src_pad, buffer) to emit.
  Multi-sink elements buffer internally and emit when their sync policy
  fires (elements/routing.py).
- `Element.negotiate(in_specs)` → out_specs, raising NegotiationError
  with reference-grade actionable messages.

Properties are plain constructor kwargs; string values arrive from the
DSL and are coerced by each element's `PROPS` declaration — the GObject
property-table analog (tensor_filter_common.c:899-1017).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple, Union

from nnstreamer_tpu.core.errors import (
    FAIL_FAST,
    ErrorPolicy,
    NegotiationError,
    PipelineError,
)
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.graph.media import MediaSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec

log = get_logger("graph")

StreamSpec = Union[TensorsSpec, MediaSpec]
Emission = Tuple[int, TensorBuffer]  # (src pad index, buffer)

#: marker for elements whose pad count is set per-instance (mux/demux…)
DYNAMIC = -1


@dataclass
class PropDef:
    """One declared element property: name, parser, default, doc."""

    parse: Callable[[str], Any]
    default: Any = None
    doc: str = ""


def prop_bool(s) -> bool:
    if isinstance(s, bool):
        return s
    return str(s).strip().lower() in ("1", "true", "yes", "on")


class _InertTracer:
    """Tracing stub for elements outside a running pipeline: `.active`
    is False and nothing else is ever called behind that guard.
    PipelineRunner.start() swaps in the session tracer (the real hook
    API lives in runtime/tracing.py; this stub exists here only to break
    the graph→runtime import cycle)."""

    active = False


_NO_TRACE = _InertTracer()


class Element:
    """Base pipeline element.

    Subclasses declare ELEMENT_NAME (DSL name), sink/src pad counts, a
    PROPS table, and implement negotiate()/process().
    """

    ELEMENT_NAME: str = ""
    NUM_SINK_PADS: int = 1
    NUM_SRC_PADS: int = 1
    PROPS: Dict[str, PropDef] = {}
    #: properties every element understands, resolved alongside the
    #: subclass PROPS table (kept separate so subclasses never have to
    #: merge them in by hand)
    COMMON_PROPS: Dict[str, PropDef] = {
        "error_policy": PropDef(
            ErrorPolicy.parse, FAIL_FAST,
            "what the scheduler does when process() raises: fail "
            "(default) | skip | retry:N[:backoff_ms] | degrade "
            "(route input to the auto-added fallback src pad)"),
    }
    #: teardown signal shared by the running pipeline — elements that
    #: block (repo puts, injected delays) should wait on this instead of
    #: sleeping blind; assigned by PipelineRunner.start()
    _stop_evt = None
    #: element consumes host arrays (decoders, sinks, wire encoders): the
    #: scheduler starts async D2H copies when queueing buffers toward it,
    #: overlapping transfers with other in-flight frames
    WANTS_HOST: bool = False
    #: eligible for scheduler-level chain fusion (runtime/scheduler.py):
    #: linear runs of cheap single-in/single-out elements execute in one
    #: worker thread with direct call-through instead of a thread+channel
    #: hop each. Elements whose process() should keep a dedicated thread
    #: (tensor_filter: device dispatch must overlap upstream conversion)
    #: set this False.
    CHAIN_FUSABLE: bool = True
    #: element's outputs may stay as unresolved device arrays: the
    #: scheduler does NOT block on results before enqueueing them
    #: downstream, letting JAX's async engine pipeline invokes. A
    #: bounded in-flight window ([runtime] max_inflight) caps live HBM.
    #: Set by tensor_filter and device-mode tensor_decoder; host-bound
    #: elements (sinks, wire encoders) stay False and are sync points.
    DEVICE_RESIDENT: bool = False
    #: tracing hook surface — the runner assigns the session tracer to
    #: every element before start(); elements emit custom events with
    #: `if self._tracer.active: self._tracer.instant(self.name, ...)`
    _tracer = _NO_TRACE

    def __init__(self, name: Optional[str] = None, **props):
        self.name = name or f"{self.ELEMENT_NAME}{id(self) & 0xFFFF:x}"
        self.props: Dict[str, Any] = {
            k: d.default for k, d in self.COMMON_PROPS.items()
        }
        self.props.update({k: d.default for k, d in self.PROPS.items()})
        self.set_props(**props)
        self.in_specs: List[Optional[StreamSpec]] = []
        self.out_specs: List[Optional[StreamSpec]] = []
        self._pipeline: Optional["Pipeline"] = None

    # -- properties --------------------------------------------------------
    def set_props(self, **props) -> None:
        for key, value in props.items():
            k = key.replace("-", "_")
            pd = self.PROPS.get(k) or self.COMMON_PROPS.get(k)
            if pd is None:
                valid = sorted(p.replace("_", "-") for p in
                               list(self.PROPS) + list(self.COMMON_PROPS))
                raise PipelineError(
                    f"element {self.ELEMENT_NAME!r} ({self.name}) has no "
                    f"property {key!r}; valid properties: {valid}"
                )
            try:
                self.props[k] = (
                    pd.parse(value) if isinstance(value, str) else value
                )
            except (ValueError, TypeError) as e:
                raise PipelineError(
                    f"bad value {value!r} for property {key!r} of element "
                    f"{self.name}: {e}"
                ) from e

    # -- error policy ------------------------------------------------------
    @property
    def error_policy(self) -> ErrorPolicy:
        """Parsed error-policy property (FAIL_FAST unless overridden)."""
        return self.props.get("error_policy") or FAIL_FAST

    @property
    def fallback_src_pad(self) -> Optional[int]:
        """Pad index the scheduler routes failed input buffers to under
        error-policy=degrade: one extra src pad appended after the
        declared ones (so a plain 1-src element degrades on pad 1, and a
        sink degrades on pad 0). Its stream spec is the element's sink
        pad 0 input spec — the fallback consumer sees the *unprocessed*
        input. None unless the policy is degrade."""
        if self.error_policy.kind != "degrade" or self.NUM_SRC_PADS == DYNAMIC:
            return None
        return self.NUM_SRC_PADS

    # -- pads --------------------------------------------------------------
    @property
    def num_sink_pads(self) -> int:
        return self.NUM_SINK_PADS

    @property
    def num_src_pads(self) -> int:
        if self.fallback_src_pad is not None:
            return self.NUM_SRC_PADS + 1
        return self.NUM_SRC_PADS

    # -- upstream events (GStreamer upstream-event analog) ------------------
    def post_upstream_event(self, event: dict) -> None:
        """Send an event toward the pipeline's sources (e.g. tensor_rate
        throttle QoS, gsttensor_rate.c:22-34). Routed against the link
        graph by the runner; each upstream element's
        handle_upstream_event() may consume it (return True) or let it
        propagate further. No-op outside a running pipeline."""
        router = getattr(self, "_event_router", None)
        if router is not None:
            router(self, event)

    def handle_upstream_event(self, event: dict) -> bool:
        """Return True to consume the event (stops propagation)."""
        return False

    # -- negotiation -------------------------------------------------------
    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        """Compute output specs from input specs. Runs once, build time."""
        raise NotImplementedError

    def fail_negotiation(self, msg: str) -> None:
        raise NegotiationError(f"element {self.name} ({self.ELEMENT_NAME}): {msg}")

    #: element understands dynamically micro-batched streams (buffers
    #: carrying a variable leading batch axis, tensor_batch upstream);
    #: everything else refuses them at negotiation via expect_tensors
    ACCEPTS_DYN_BATCH: bool = False

    def expect_tensors(self, spec: StreamSpec, pad: int = 0) -> TensorsSpec:
        if not isinstance(spec, TensorsSpec):
            self.fail_negotiation(
                f"sink pad {pad} requires a tensor stream but got "
                f"{type(spec).__name__} ({spec}); insert a tensor_converter "
                f"upstream to turn media into tensors"
            )
        if spec.dyn_batch and not self.ACCEPTS_DYN_BATCH:
            self.fail_negotiation(
                f"sink pad {pad} stream is dynamically micro-batched "
                f"(tensor_batch upstream, up to {spec.dyn_batch} frames per "
                f"buffer) but {self.ELEMENT_NAME} is not batch-aware; insert "
                f"tensor_unbatch before it to restore per-frame buffers"
            )
        return spec

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Called after negotiation, before data flows (open backends…)."""

    def stop(self) -> None:
        """Called at teardown."""

    # -- dataflow ----------------------------------------------------------
    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        raise NotImplementedError

    def flush(self) -> List[Emission]:
        """Drain internal state at EOS (aggregators, adapters)."""
        return []

    # -- time-based wakeups (deadline coalescing) ---------------------------
    def next_deadline(self) -> Optional[float]:
        """Earliest `time.perf_counter()` instant at which this element
        needs a timer wakeup even if no buffer arrives (e.g. a half-full
        tensor_batch whose max-latency deadline is approaching). None =
        no pending deadline. Called by the scheduler's worker loop to
        bound its queue wait; only ever called from the element's own
        worker thread, so no locking is needed."""
        return None

    def on_timer(self) -> List[Emission]:
        """Fired by the scheduler when next_deadline() expires before a
        buffer arrives. Same threading contract as process()."""
        return []

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"


class SourceElement(Element):
    NUM_SINK_PADS = 0

    #: QoS pacing requested from downstream (0 = none): sources should
    #: not *generate* frames closer together than this (skip-before-
    #: compute, the point of the reference's upstream QoS events)
    qos_min_interval_ns: int = 0
    qos_skipped: int = 0

    def handle_upstream_event(self, event: dict) -> bool:
        if event.get("type") == "qos":
            self.qos_min_interval_ns = int(event.get("min_interval_ns", 0))
            return True
        return False

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        return [self.output_spec()]

    def output_spec(self) -> StreamSpec:
        raise NotImplementedError

    def generate(self) -> Iterator[TensorBuffer]:
        raise NotImplementedError

    def interrupt(self) -> None:
        """Unblock generate() for teardown (called by the scheduler's
        stop(); sources that block on external input must override)."""

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        raise PipelineError(f"source {self.name} cannot receive buffers")


class SinkElement(Element):
    NUM_SRC_PADS = 0

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        return []

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        self.render(buf)
        return []

    def render(self, buf: TensorBuffer) -> None:
        raise NotImplementedError


@dataclass(frozen=True)
class Link:
    src: Element
    src_pad: int
    dst: Element
    dst_pad: int

    def __str__(self):
        return (f"{self.src.name}:src{self.src_pad} → "
                f"{self.dst.name}:sink{self.dst_pad}")


class Pipeline:
    """A DAG of elements + links, negotiated then run by the scheduler.

    (Cycles are supported only via the out-of-band tensor_repo pair, as in
    the reference — the link graph itself must be acyclic.)
    """

    def __init__(self, name: str = "pipeline"):
        self.name = name
        self.elements: Dict[str, Element] = {}
        self.links: List[Link] = []
        self._negotiated = False

    # -- construction ------------------------------------------------------
    def add(self, element: Element) -> Element:
        if element.name in self.elements:
            raise PipelineError(
                f"duplicate element name {element.name!r} in pipeline"
            )
        self.elements[element.name] = element
        element._pipeline = self
        return element

    def link(self, src: Element, dst: Element,
             src_pad: Optional[int] = None, dst_pad: Optional[int] = None) -> Link:
        for e in (src, dst):
            if e.name not in self.elements or self.elements[e.name] is not e:
                raise PipelineError(
                    f"element {e.name!r} is not in pipeline {self.name!r}; "
                    f"add() it before linking"
                )
        if src_pad is None:
            src_pad = self._next_free_src_pad(src)
        if dst_pad is None:
            dst_pad = self._next_free_sink_pad(dst)
        if src.NUM_SRC_PADS != DYNAMIC and src_pad >= src.num_src_pads:
            raise PipelineError(
                f"{src.name} has {src.num_src_pads} src pad(s); "
                f"cannot link pad {src_pad}"
            )
        if dst.NUM_SINK_PADS != DYNAMIC and dst_pad >= dst.num_sink_pads:
            raise PipelineError(
                f"{dst.name} has {dst.num_sink_pads} sink pad(s); "
                f"cannot link pad {dst_pad}"
            )
        for l in self.links:
            if l.src is src and l.src_pad == src_pad:
                raise PipelineError(f"src pad already linked: {l}")
            if l.dst is dst and l.dst_pad == dst_pad:
                raise PipelineError(f"sink pad already linked: {l}")
        link = Link(src, src_pad, dst, dst_pad)
        self.links.append(link)
        self._negotiated = False
        return link

    def _next_free_src_pad(self, e: Element) -> int:
        used = {l.src_pad for l in self.links if l.src is e}
        pad = 0
        while pad in used:
            pad += 1
        return pad

    def _next_free_sink_pad(self, e: Element) -> int:
        used = {l.dst_pad for l in self.links if l.dst is e}
        pad = 0
        while pad in used:
            pad += 1
        return pad

    # -- queries -----------------------------------------------------------
    def sources(self) -> List[SourceElement]:
        return [e for e in self.elements.values() if isinstance(e, SourceElement)]

    def links_from(self, e: Element) -> List[Link]:
        return sorted((l for l in self.links if l.src is e),
                      key=lambda l: l.src_pad)

    def links_to(self, e: Element) -> List[Link]:
        return sorted((l for l in self.links if l.dst is e),
                      key=lambda l: l.dst_pad)

    def get(self, name: str) -> Element:
        try:
            return self.elements[name]
        except KeyError:
            raise PipelineError(
                f"no element named {name!r} in pipeline; elements: "
                f"{sorted(self.elements)}"
            ) from None

    # -- negotiation -------------------------------------------------------
    def negotiate(self) -> None:
        """Single-pass static negotiation in topological order.

        After this, every element has in_specs/out_specs and every link
        carries exactly one immutable spec — the zero-negotiation
        steady-state the reference gets from one-shot caps negotiation.
        """
        self._validate_topology()
        order = self._topo_order()
        link_spec: Dict[Tuple[str, int], StreamSpec] = {}
        for e in order:
            in_links = self.links_to(e)
            n_sink = len(in_links) if e.NUM_SINK_PADS == DYNAMIC else e.num_sink_pads
            in_specs: List[StreamSpec] = [None] * n_sink  # type: ignore
            for l in in_links:
                in_specs[l.dst_pad] = link_spec[(l.src.name, l.src_pad)]
            if any(s is None for s in in_specs):
                missing = [i for i, s in enumerate(in_specs) if s is None]
                raise NegotiationError(
                    f"element {e.name} has unlinked sink pad(s) {missing}"
                )
            # enforced centrally (not just in expect_tensors) so elements
            # whose negotiate() never inspects the spec — sinks — still
            # refuse micro-batched wires they cannot interpret
            for i, s in enumerate(in_specs):
                if isinstance(s, TensorsSpec) and s.dyn_batch \
                        and not e.ACCEPTS_DYN_BATCH:
                    raise NegotiationError(
                        f"element {e.name} ({e.ELEMENT_NAME}): sink pad {i} "
                        f"stream is dynamically micro-batched (tensor_batch "
                        f"upstream, up to {s.dyn_batch} frames per buffer) "
                        f"but {e.ELEMENT_NAME} is not batch-aware; insert "
                        f"tensor_unbatch before it to restore per-frame "
                        f"buffers"
                    )
            out_specs = e.negotiate(in_specs)
            fb = e.fallback_src_pad
            if fb is not None and len(out_specs) == fb:
                # degrade fallback pad: carries the element's pad-0
                # input stream verbatim (the scheduler re-routes failed
                # input buffers there), so its spec IS the input spec
                out_specs = list(out_specs) + [in_specs[0]]
            e.in_specs = list(in_specs)
            e.out_specs = list(out_specs)
            out_links = self.links_from(e)
            n_src = len(out_links) if e.NUM_SRC_PADS == DYNAMIC else e.num_src_pads
            if len(out_specs) != n_src:
                raise NegotiationError(
                    f"element {e.name} produced {len(out_specs)} output "
                    f"spec(s) but has {n_src} src pad(s)"
                )
            for l in out_links:
                link_spec[(l.src.name, l.src_pad)] = out_specs[l.src_pad]
        self._link_specs = link_spec
        self._negotiated = True
        for e in order:
            log.debug("negotiated %s: in=%s out=%s", e.name, e.in_specs, e.out_specs)

    def spec_of_link(self, link: Link) -> StreamSpec:
        if not self._negotiated:
            raise PipelineError("pipeline not negotiated yet")
        return self._link_specs[(link.src.name, link.src_pad)]

    def _validate_topology(self) -> None:
        if not self.elements:
            raise PipelineError("empty pipeline")
        if not self.sources():
            raise PipelineError(
                "pipeline has no source element; every pipeline needs at "
                "least one (appsrc, videotestsrc, filesrc, …)"
            )
        for e in self.elements.values():
            policy = e.error_policy
            if policy.kind != "fail" and isinstance(e, SourceElement):
                raise PipelineError(
                    f"element {e.name}: error-policy={policy} is not "
                    f"supported on a source element — a generate() "
                    f"failure kills its pump thread, so sources are "
                    f"always fail-fast; put the policy on the element "
                    f"that can actually fail per-buffer"
                )
            if policy.kind == "degrade" and e.NUM_SRC_PADS == DYNAMIC:
                raise PipelineError(
                    f"element {e.name}: error-policy=degrade needs a "
                    f"fixed src pad count to place the fallback pad, but "
                    f"{e.ELEMENT_NAME} has dynamic src pads; use skip or "
                    f"retry instead"
                )
            n_in = len(self.links_to(e))
            n_out = len(self.links_from(e))
            if e.NUM_SINK_PADS != DYNAMIC and n_in != e.num_sink_pads:
                raise PipelineError(
                    f"element {e.name} needs {e.num_sink_pads} sink link(s), "
                    f"has {n_in}"
                )
            if e.NUM_SRC_PADS != DYNAMIC and n_out != e.num_src_pads:
                hint = (
                    f" (error-policy=degrade adds a fallback src pad — "
                    f"pad {e.fallback_src_pad} — that must be linked, "
                    f"e.g. to a cheaper model branch or a sink)"
                    if e.fallback_src_pad is not None else
                    " — every src pad must be linked (terminate unused "
                    "branches with a sink such as fakesink)"
                )
                raise PipelineError(
                    f"element {e.name} needs {e.num_src_pads} src link(s), "
                    f"has {n_out}{hint}"
                )

    def _topo_order(self) -> List[Element]:
        indeg = {name: len(self.links_to(e)) for name, e in self.elements.items()}
        ready = [e for n, e in self.elements.items() if indeg[n] == 0]
        order: List[Element] = []
        while ready:
            e = ready.pop()
            order.append(e)
            for l in self.links_from(e):
                indeg[l.dst.name] -= 1
                if indeg[l.dst.name] == 0:
                    ready.append(l.dst)
        if len(order) != len(self.elements):
            cyclic = sorted(n for n, d in indeg.items() if d > 0)
            raise PipelineError(
                f"pipeline graph has a cycle involving {cyclic}; direct "
                f"cycles are not allowed — use a tensor_repo_sink/"
                f"tensor_repo_src pair for feedback loops"
            )
        return order

    def describe(self) -> str:
        lines = [f"pipeline {self.name!r}:"]
        for e in self.elements.values():
            lines.append(f"  {e!r} in={e.in_specs} out={e.out_specs}")
        for l in self.links:
            lines.append(f"  {l}")
        return "\n".join(lines)
