"""gst-launch-style pipeline description parser.

CLI parity with the reference's user surface (`gst-launch-1.0 ... !
tensor_converter ! tensor_filter framework=... ! ...`, SURVEY.md §1 L6).
Supported grammar subset:

  pipeline   := chain (whitespace chain)*
  chain      := node ('!' node)*
  node       := element | ref
  element    := NAME (prop)*
  prop       := KEY '=' VALUE        (VALUE may be "quoted with spaces")
  ref        := NAME '.' [PAD]       (links to/from a named element; PAD
                                      selects an explicit pad — 'sink_0',
                                      'src_1', or a bare index — else the
                                      next free pad is used; mux/demux/tee
                                      branches)

Examples:

  videotestsrc num-buffers=10 ! tensor_converter ! tensor_sink name=out

  appsrc name=a ! mux.  appsrc name=b ! mux.
  tensor_mux name=mux ! tensor_filter model=m.msgpack ! tensor_sink

Element names resolve through the ELEMENT registry, so user plugins are
first-class in the DSL exactly like built-ins (reference: element names
registered in registerer/nnstreamer.c:91-119).
"""

from __future__ import annotations

import re
import shlex
from typing import Dict, List, Optional

from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.core.registry import PluginKind, registry
from nnstreamer_tpu.graph.pipeline import Element, Pipeline

_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_\-]*$")


def parse_launch(description: str, name: str = "pipeline") -> Pipeline:
    """Build a Pipeline from a description string.

    Import of `nnstreamer_tpu.elements` is implicit so built-in element
    names are always available (the plugin_init analog).
    """
    import nnstreamer_tpu.elements  # noqa: F401  (registers built-ins)

    tokens = _tokenize(description)
    if not tokens:
        raise PipelineError("empty pipeline description")

    pipe = Pipeline(name)
    chains = _split_chains(tokens)

    # pass 1: instantiate every element so refs may point forward
    # (gst-launch allows `appsrc ! mux.` before `tensor_mux name=mux`)
    for chain in chains:
        for node in chain:
            if node["kind"] == "element":
                node["instance"] = _instantiate(node)
                pipe.add(node["instance"])

    # pass 2: create links chain by chain
    for chain in chains:
        prev: Optional[Element] = None
        prev_pad: Optional[int] = None
        for node in chain:
            cur = (
                node["instance"]
                if node["kind"] == "element"
                else pipe.get(node["name"])
            )
            cur_pad = _ref_pad(node, "sink")
            if prev is not None:
                pipe.link(prev, cur, src_pad=prev_pad, dst_pad=cur_pad)
            prev = cur
            prev_pad = _ref_pad(node, "src")
    return pipe


def _ref_pad(node: Dict, direction: str) -> Optional[int]:
    """Explicit pad index of a ref node for the given direction, if any.

    'sink_0'/'src_1' are direction-qualified (gst pad-template names); a
    bare integer applies to whichever side the ref is used on.
    """
    if node["kind"] != "ref" or not node.get("pad"):
        return None
    pad = node["pad"]
    if pad.isdigit():
        return int(pad)
    prefix, _, idx = pad.rpartition("_")
    if prefix == direction and idx.isdigit():
        return int(idx)
    other = "src" if direction == "sink" else "sink"
    if prefix == other and idx.isdigit():
        return None  # qualified for the other direction
    raise PipelineError(
        f"bad pad reference {node['name']}.{pad!r}: expected sink_<n>, "
        f"src_<n>, or a bare pad index"
    )


def _tokenize(description: str) -> List[str]:
    try:
        lex = shlex.shlex(description, posix=True)
        lex.whitespace_split = True
        lex.commenters = "#"
        return list(lex)
    except ValueError as e:
        raise PipelineError(f"cannot tokenize pipeline description: {e}") from e


def _split_chains(tokens: List[str]) -> List[List[Dict]]:
    """Group tokens into chains of element/ref nodes."""
    chains: List[List[Dict]] = []
    current: List[Dict] = []
    node: Optional[Dict] = None
    expect_node = True  # True right after '!' or at a chain boundary

    def finish_node():
        nonlocal node
        if node is not None:
            current.append(node)
            node = None

    def finish_chain():
        nonlocal current
        finish_node()
        if current:
            chains.append(current)
            current = []

    for tok in tokens:
        if tok == "!":
            if node is None and not current:
                raise PipelineError("pipeline description starts with '!'")
            finish_node()
            expect_node = True
            continue
        if "=" in tok and not expect_node and node is not None:
            key, _, value = tok.partition("=")
            if not key:
                raise PipelineError(f"malformed property token {tok!r}")
            if node["kind"] != "element":
                raise PipelineError(
                    f"property {tok!r} follows pad reference "
                    f"{node['name']!r}.; properties can only be set on the "
                    f"element's own declaration (where name= is given)"
                )
            if key == "name":
                node["name"] = value
            else:
                node["props"][key] = value
            continue
        # a bare name token: starts a new node; if we weren't expecting one,
        # it also starts a new chain (whitespace-separated chains)
        if not expect_node:
            finish_chain()
        if "." in tok and _NAME_RE.match(tok.split(".", 1)[0] or "") and (
                tok.endswith(".") or _NAME_RE.match(tok.split(".", 1)[1])
                or tok.split(".", 1)[1].isdigit()):
            finish_node()
            elem_name, _, pad = tok.partition(".")
            node = {"kind": "ref", "name": elem_name, "pad": pad or None}
        elif _NAME_RE.match(tok):
            finish_node()
            node = {"kind": "element", "type": tok, "name": None, "props": {}}
        else:
            raise PipelineError(
                f"unexpected token {tok!r} in pipeline description (element "
                f"names match [A-Za-z_][A-Za-z0-9_-]*; properties are "
                f"key=value; links are '!')"
            )
        expect_node = False
    finish_chain()
    return chains


def _instantiate(node: Dict) -> Element:
    type_name = node["type"]
    cls = registry.find(PluginKind.ELEMENT, type_name)
    if cls is None:
        registry.get(PluginKind.ELEMENT, type_name)  # raises with the full list
    try:
        return cls(name=node["name"], **node["props"])
    except PipelineError:
        raise
    except (TypeError, ValueError) as e:
        raise PipelineError(
            f"cannot construct element {type_name!r}: {e}"
        ) from e
