"""Media-domain stream specs (the video/x-raw, audio/x-raw … caps analog).

Only the edges of a pipeline speak media: sources produce media buffers,
`tensor_converter` turns them into tensors, `tensor_decoder` turns tensors
back (SURVEY.md §1 property 2 — strict semantic agnosticism in the
middle). These specs model the subset of GStreamer caps the reference
elements actually negotiate (gsttensor_converter.c per-media branches).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from fractions import Fraction
from typing import Tuple

from nnstreamer_tpu.tensor.info import MediaType

#: video formats the reference converter accepts (gsttensor_converter.c
#: video branch: RGB/BGRx/GRAY8 — :1046) plus RGBA used by decoders.
VIDEO_FORMATS = {"RGB": 3, "BGRx": 4, "RGBA": 4, "GRAY8": 1}


@dataclass(frozen=True)
class MediaSpec:
    """Base for non-tensor stream types; negotiation passes these opaque."""

    rate: Fraction = Fraction(0, 1)

    @property
    def media(self) -> MediaType:
        raise NotImplementedError

    def with_rate(self, rate) -> "MediaSpec":
        return replace(self, rate=Fraction(rate))


@dataclass(frozen=True)
class VideoSpec(MediaSpec):
    width: int = 0
    height: int = 0
    format: str = "RGB"

    def __post_init__(self):
        if self.format not in VIDEO_FORMATS:
            raise ValueError(
                f"unsupported video format {self.format!r}; supported: "
                f"{sorted(VIDEO_FORMATS)}"
            )

    @property
    def media(self) -> MediaType:
        return MediaType.VIDEO

    @property
    def channels(self) -> int:
        return VIDEO_FORMATS[self.format]

    @property
    def frame_shape(self) -> Tuple[int, int, int]:
        """(H, W, C) row-major."""
        return (self.height, self.width, self.channels)


@dataclass(frozen=True)
class AudioSpec(MediaSpec):
    sample_rate: int = 16000
    channels: int = 1
    sample_format: str = "S16LE"  # S8 | S16LE | S32LE | F32LE | F64LE

    _FORMATS = {"S8": "int8", "S16LE": "int16", "S32LE": "int32",
                "F32LE": "float32", "F64LE": "float64"}

    def __post_init__(self):
        if self.sample_format not in self._FORMATS:
            raise ValueError(
                f"unsupported audio format {self.sample_format!r}; "
                f"supported: {sorted(self._FORMATS)}"
            )

    @property
    def media(self) -> MediaType:
        return MediaType.AUDIO

    @property
    def dtype_name(self) -> str:
        return self._FORMATS[self.sample_format]


@dataclass(frozen=True)
class TextSpec(MediaSpec):
    @property
    def media(self) -> MediaType:
        return MediaType.TEXT


@dataclass(frozen=True)
class OctetSpec(MediaSpec):
    @property
    def media(self) -> MediaType:
        return MediaType.OCTET
