"""Pallas paged attention: the flash kernels taught block tables.

The serving gap this closes (ROADMAP item 2, bench round r05): the
continuous-batching LLM path computed attention as plain-XLA block-table
gathers + full einsums + ``-1e30``-mask softmax (`llm/paged_model.py`),
materializing the whole ``(B, max_blocks*block_size, n_kv, hd)``
gathered cache every decode step, while the repo's own flash kernel
(`pallas_ops._flash_kernel`) measured 9.2x (s2048) to 165x (s8192) over
XLA attention. These kernels keep the flash formulation — online
softmax, K/V streamed through VMEM one block at a time — but fetch each
K/V block through the *per-sequence block table* with
``PrefetchScalarGridSpec`` scalar prefetch, so the block-table
indirection happens in the BlockSpec index map (a DMA address
computation), never as a gather materialized in HBM.

Two kernels:

- ``paged_decode_attn`` — one query token per sequence row, grid
  ``(batch, table_blocks)``: program ``(b, j)`` streams pool block
  ``table[b, j]`` through VMEM, carrying the online-softmax state
  ``(m, l, acc)`` in VMEM scratch across the sequential ``j`` steps.
  Rows mask inclusively at ``kv_pos <= pos[b]`` — identical semantics
  to ``paged_decode_step``'s mask, so stale/unwritten slots contribute
  exactly nothing. Blocks entirely past ``pos[b]`` are skipped
  (``pl.when``), so a shallow sequence in a deep batch does not pay for
  the deep one's table length.
- ``paged_prefill_attn`` — causal q-blocked prefill over the pool,
  grid ``(heads, q_blocks, table_blocks)``: the chunk's queries attend
  every pool block the table maps below their absolute positions
  (earlier chunks' KV included), masking ``q_pos >= k_pos`` from global
  offsets. GQA is resolved in the index map (head ``h`` fetches KV head
  ``h // group``), so the narrow KV pool is never group-expanded in
  memory.

On top of them, drop-in twins of the XLA reference functions
(``paged_flash_decode_step`` / ``paged_flash_prefill_chunk``) run the
full layer stack with the same pool-scatter writes and quant-aware
projections; `backends/llm_exec.py` selects between the two families
via the ``paged_kernel`` knob with the XLA path as the bit-reference
(tests/test_paged_kernels.py pins ≤1e-5 logits parity in interpret
mode). The KV scatter itself stays an XLA ``.at[].set`` — scatter is a
gather/scatter-unit op, not a Pallas sweet spot (see
``pallas_ops.sparse_to_dense``); the kernels read the pool *after* the
step's writes land, which inside one jit is just a data dependence.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from nnstreamer_tpu.backends.pallas_ops import (
    _interpret, _online_softmax_update)


def available() -> bool:
    """Whether the paged Pallas kernels can run here (compiled on TPU,
    interpret mode elsewhere). Split out so llm_exec can probe it once
    and count a fallback instead of raising mid-serve."""
    return hasattr(pltpu, "PrefetchScalarGridSpec")


# -- paged flash decode ------------------------------------------------------

def _paged_decode_kernel(scale: float, bs: int, n_kv: int, n_heads: int,
                         tab_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr):
    """One (row, table-block) program. The row's online-softmax carry
    (m, l, acc) lives in VMEM scratch, persisting across the sequential
    innermost grid dim; GQA runs as a static loop over KV heads, each
    group reusing `_online_softmax_update` so the mask/normalizer
    semantics are shared with every flash kernel in pallas_ops."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    n_b = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    pos_b = pos_ref[b]

    # table blocks entirely past this row's write position hold no
    # attended slots — skip the whole program (per-row early exit)
    @pl.when((j * bs) <= pos_b)
    def _step():
        q = q_ref[0].astype(jnp.float32)            # (n_heads, hd)
        k_blk = k_ref[0].astype(jnp.float32)        # (bs, n_kv, hd)
        v_blk = v_ref[0].astype(jnp.float32)
        kvpos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        valid = kvpos <= pos_b                      # (1, bs) inclusive
        g = n_heads // n_kv
        m = m_scr[0]
        l = l_scr[0]
        acc = acc_scr[...]
        ms, ls, accs = [], [], []
        for kv in range(n_kv):                      # static GQA groups
            sl = slice(kv * g, (kv + 1) * g)
            mask = jnp.broadcast_to(valid, (g, bs))
            m_g, l_g, acc_g = _online_softmax_update(
                q[sl], k_blk[:, kv, :], v_blk[:, kv, :],
                m[sl], l[sl], acc[sl], scale, mask)
            ms.append(m_g)
            ls.append(l_g)
            accs.append(acc_g)
        m = jnp.concatenate(ms)
        l = jnp.concatenate(ls)
        acc = jnp.concatenate(accs, axis=0)
        m_scr[...] = jnp.broadcast_to(m[None, :], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l[None, :], l_scr.shape)
        acc_scr[...] = acc

    @pl.when(j == n_b - 1)
    def _finalize():
        l = jnp.maximum(l_scr[0], 1e-20)
        o_ref[0] = (acc_scr[...] / l[:, None]).astype(o_ref.dtype)


def paged_decode_attn(q, k_pool_l, v_pool_l, tables, pos):
    """Paged flash attention for one decode step of one layer.

    q (B, n_heads, hd) — the step's rope'd queries; k/v_pool_l
    (num_blocks, block_size, n_kv, hd) — ONE layer's pool, already
    holding this step's K/V writes; tables (B, max_blocks) int32;
    pos (B,) int32 per-row positions. Returns (B, n_heads, hd) f32.

    The per-row block table rides scalar prefetch: the K/V BlockSpec
    index map reads ``tables[b, j]`` to address pool block DMAs, so the
    full gathered cache never exists — per-program VMEM is one
    (block_size, n_kv, hd) block.
    """
    b, n_heads, hd = q.shape
    _, bs, n_kv, _ = k_pool_l.shape
    mb = tables.shape[1]
    scale = hd ** -0.5
    kern = functools.partial(_paged_decode_kernel, scale, bs, n_kv,
                             n_heads)
    row = pl.BlockSpec((1, n_heads, hd), lambda i, j, tab, pos: (i, 0, 0))
    blk = pl.BlockSpec(
        (1, bs, n_kv, hd),
        lambda i, j, tab, pos: (tab[i * mb + j], 0, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=[row, blk, blk],
        out_specs=row,
        scratch_shapes=[
            pltpu.VMEM((8, n_heads), jnp.float32),   # m (sublane-repl)
            pltpu.VMEM((8, n_heads), jnp.float32),   # l
            pltpu.VMEM((n_heads, hd), jnp.float32),  # acc
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_heads, hd), jnp.float32),
        interpret=_interpret(),
    )(tables.reshape(-1).astype(jnp.int32), pos.astype(jnp.int32),
      q, k_pool_l, v_pool_l)


# -- paged flash prefill -----------------------------------------------------

def _paged_prefill_kernel(scale: float, bs: int, bq: int,
                          tab_ref, p0_ref, q_ref, k_ref, v_ref, o_ref,
                          m_scr, l_scr, acc_scr):
    """One (head, q-block, table-block) program: causal flash update of
    bq chunk queries against pool block ``table[j]``. Global query
    positions are ``p0 + qi*bq + row`` (p0 = the chunk's absolute start,
    scalar-prefetched), key positions ``j*bs + col`` — the same
    rows>=cols mask geometry as `pallas_ops._causal_mask`, shifted by
    the chunk offset so later chunks attend earlier chunks' pool KV."""
    i = pl.program_id(1)
    j = pl.program_id(2)
    n_j = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    p0 = p0_ref[0]
    q_lo = p0 + i * bq

    # blocks entirely above this q-block's last row are fully masked
    @pl.when((j * bs) <= (q_lo + bq - 1))
    def _step():
        q = q_ref[0].astype(jnp.float32)            # (bq, hd)
        k_blk = k_ref[0, :, 0, :].astype(jnp.float32)   # (bs, hd)
        v_blk = v_ref[0, :, 0, :].astype(jnp.float32)
        rows = q_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bs), 0)
        cols = j * bs + jax.lax.broadcasted_iota(jnp.int32, (bq, bs), 1)
        m, l, acc = _online_softmax_update(
            q, k_blk, v_blk, m_scr[0], l_scr[0], acc_scr[...], scale,
            rows >= cols)
        m_scr[...] = jnp.broadcast_to(m[None, :], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l[None, :], l_scr.shape)
        acc_scr[...] = acc

    @pl.when(j == n_j - 1)
    def _finalize():
        l = jnp.maximum(l_scr[0], 1e-20)
        o_ref[0] = (acc_scr[...] / l[:, None]).astype(o_ref.dtype)


def _auto_bq(s: int, want: int = 128) -> int:
    b = min(want, s)
    while b > 8 and s % b:
        b //= 2
    return b


def paged_prefill_attn(q, k_pool_l, v_pool_l, table, pos0):
    """Causal paged flash attention for one prefill chunk of one layer.

    q (n_heads, S_c, hd) — the chunk's rope'd queries (S_c = the padded
    chunk bucket); k/v_pool_l (num_blocks, block_size, n_kv, hd) — one
    layer's pool with the chunk's K/V already scattered in; table
    (max_blocks,) int32 — the sequence's block table; pos0 — the
    chunk's absolute start position (traced scalar). Returns
    (n_heads, S_c, hd) f32.

    Head ``h`` fetches KV head ``h // group`` straight from the narrow
    pool in its index map — GQA without a group-expanded copy.
    """
    n_heads, s_c, hd = q.shape
    _, bs, n_kv, _ = k_pool_l.shape
    mb = table.shape[0]
    g = n_heads // n_kv
    bq = _auto_bq(s_c)
    scale = hd ** -0.5
    kern = functools.partial(_paged_prefill_kernel, scale, bs, bq)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_heads, s_c // bq, mb),
        in_specs=[
            pl.BlockSpec((1, bq, hd), lambda h, i, j, tab, p0: (h, i, 0)),
            pl.BlockSpec((1, bs, 1, hd),
                         lambda h, i, j, tab, p0: (tab[j], 0, h // g, 0)),
            pl.BlockSpec((1, bs, 1, hd),
                         lambda h, i, j, tab, p0: (tab[j], 0, h // g, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, hd),
                               lambda h, i, j, tab, p0: (h, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((8, bq), jnp.float32),
            pltpu.VMEM((8, bq), jnp.float32),
            pltpu.VMEM((bq, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_heads, s_c, hd), jnp.float32),
        interpret=_interpret(),
    )(table.astype(jnp.int32), jnp.asarray(pos0, jnp.int32).reshape(1),
      q, k_pool_l, v_pool_l)


# -- full layer-stack twins (jitted by llm_exec) -----------------------------

def paged_flash_decode_step(params, cur, tables, pos, k_pool, v_pool,
                            *, n_heads=4, dtype=jnp.float32):
    """Drop-in twin of `paged_model.paged_decode_step` with the
    attention einsums replaced by `paged_decode_attn`. Everything else
    — rope, pool write-through, residual/MLP structure, quant-aware
    projections — is shared with the reference via paged_model's
    helpers, so the two paths can only diverge in the attention kernel
    itself (the thing the parity tests pin)."""
    from nnstreamer_tpu.llm.paged_model import (
        _mlp_paged, _proj, _rope_rows)
    from nnstreamer_tpu.models.transformer import rmsnorm

    b = cur.shape[0]
    block_size = k_pool.shape[2]
    rows = jnp.arange(b)
    write_blk = tables[rows, pos // block_size]
    write_off = pos % block_size
    x = params["embed"][cur][:, None, :].astype(dtype)
    for li, blk in enumerate(params["blocks"]):
        h = rmsnorm(x, blk["ln1"].astype(dtype))
        d = x.shape[-1]
        hd = d // n_heads
        qkv = _proj(blk, "wqkv", h, dtype)
        kv_dim = (qkv.shape[-1] - d) // 2
        n_kv = kv_dim // hd
        q = qkv[..., :d].reshape(b, 1, n_heads, hd)
        k = qkv[..., d:d + kv_dim].reshape(b, 1, n_kv, hd)
        v = qkv[..., d + kv_dim:].reshape(b, 1, n_kv, hd)
        q, k = _rope_rows(q, pos), _rope_rows(k, pos)
        k_pool = k_pool.at[li, write_blk, write_off].set(
            k[:, 0].astype(k_pool.dtype))
        v_pool = v_pool.at[li, write_blk, write_off].set(
            v[:, 0].astype(v_pool.dtype))
        attn = paged_decode_attn(q[:, 0], k_pool[li], v_pool[li],
                                 tables, pos)
        x = x + _proj(blk, "wo", attn.reshape(b, 1, -1).astype(dtype),
                      dtype)
        h = rmsnorm(x, blk["ln2"].astype(dtype))
        x = x + _mlp_paged(blk, h, dtype)
    x = rmsnorm(x, params["ln_f"].astype(dtype))
    logits = _proj(params, "head", x[:, 0], dtype).astype(jnp.float32)
    return logits, k_pool, v_pool


def paged_flash_prefill_chunk(params, ids, pos0, blk_idx, blk_off,
                              table, k_pool, v_pool, last_idx,
                              *, n_heads=4, dtype=jnp.float32):
    """Drop-in twin of `paged_model.paged_prefill_chunk` with the
    attention gather+einsum replaced by `paged_prefill_attn`: the chunk
    writes its K/V into the pool and attends the whole prefix (earlier
    chunks included) straight through the block table, one pool block
    per DMA."""
    from nnstreamer_tpu.llm.paged_model import _mlp_paged, _proj
    from nnstreamer_tpu.models.transformer import rmsnorm, rope

    _, c = ids.shape
    x = params["embed"][ids].astype(dtype)            # (1, C, D)
    pos = pos0 + jnp.arange(c)
    for li, blk in enumerate(params["blocks"]):
        h = rmsnorm(x, blk["ln1"].astype(dtype))
        d = x.shape[-1]
        hd = d // n_heads
        qkv = _proj(blk, "wqkv", h, dtype)
        kv_dim = (qkv.shape[-1] - d) // 2
        n_kv = kv_dim // hd
        q = qkv[..., :d].reshape(1, c, n_heads, hd)
        k = qkv[..., d:d + kv_dim].reshape(1, c, n_kv, hd)
        v = qkv[..., d + kv_dim:].reshape(1, c, n_kv, hd)
        q, k = rope(q, pos), rope(k, pos)
        k_pool = k_pool.at[li, blk_idx, blk_off].set(
            k[0].astype(k_pool.dtype))
        v_pool = v_pool.at[li, blk_idx, blk_off].set(
            v[0].astype(v_pool.dtype))
        attn = paged_prefill_attn(q[0].transpose(1, 0, 2), k_pool[li],
                                  v_pool[li], table, pos0)
        attn = attn.transpose(1, 0, 2).reshape(1, c, -1).astype(dtype)
        x = x + _proj(blk, "wo", attn, dtype)
        h = rmsnorm(x, blk["ln2"].astype(dtype))
        x = x + _mlp_paged(blk, h, dtype)
    x = rmsnorm(x, params["ln_f"].astype(dtype))
    logits = _proj(params, "head", x[0, last_idx][None, :],
                   dtype).astype(jnp.float32)
    return logits[0], k_pool, v_pool
