"""Paged-LLM executor: bucketed, version-namespaced prefill/decode jits.

The LLM engine's device half. Owns the paged KV pools and a jit cache
keyed ``(namespace, kind, bucket)`` where namespace is ``("v", version)``
for ``store://`` models and ``("g", 0)`` otherwise — the same
namespacing discipline as the XLA filter backend (backends/xla.py), so
model-store hot swap composes: the store's swap controller calls
``prewarm_version`` on this handle before the epoch flips, and the
engine adopts at a step boundary (one scheduler thread ⇒ a step sees
exactly one version snapshot).

``shards=N`` opens the executor tensor-parallel over N chips
(serving/sharding.py): projections are served canonically blocked and
head-sharded, the KV pools are sharded along the kv-head axis next to
them, and the jit namespace becomes ``("tp", N, version)`` — same
per-bucket compile accounting, same swap protocol, one SPMD executable
per bucket. The sharded path is XLA-only and float-only (Pallas and
W8A8 refuse loudly); prompts at or past ``ring_prefill_min`` prefill
through the sequence-parallel ring-attention twin instead of the
blocked path (allclose-, not bit-, equivalent — decode from ring KV is
still the blocked bit-exact program).

Buckets:
- prefill: prompt length padded to pow2 (``("llmp", S)`` in the
  compile-cache manifest — replayed by ``warm_start`` so a restarted
  server compiles its prompt working set off the hot path);
- decode: active-row count padded to pow2 (``("llmd", B)``), padding
  rows write to the scratch block;
- chunk: one fixed prompt-chunk bucket (``("llmp_chunk", C)``) — every
  chunk of a chunked prefill, including the short final one, pads to
  the same bucket so the whole family is one executable.

Kernel selection (``paged_kernel`` prop / ``NNS_PAGED_KERNEL`` env,
default ``xla``): the attention inner loop is either the XLA reference
(`llm/paged_model.py` — the bit-parity path against
`transformer.generate`) or the paged Pallas flash kernels
(`backends/pallas_paged.py` — the r05 9.2–165x path). The kernel is
part of the jit key, invocations are counted per kernel, and a Pallas
path that cannot build here becomes a *counted* XLA fallback
(`kernel_fallback`), never an error.

Weights are passed as jit *arguments* (not closed over), so a same-
shape hot swap is served by the already-compiled executable — the
version namespace exists for accounting and for swaps that change
widths, which compile fresh under their own keys.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.llm.paged_cache import SCRATCH_BLOCK, PagedKVCache
from nnstreamer_tpu.runtime import devprof
from nnstreamer_tpu.runtime.sync import device_sync
from nnstreamer_tpu.runtime.tracing import NULL_TRACER

log = get_logger("backends.llm")


def _derive_dims(params: dict, n_heads: int) -> dict:
    """Model dims from the transformer params pytree itself (the only
    honest source — a store version may differ from element props)."""
    try:
        d_model = int(params["embed"].shape[1])
        vocab = int(params["head"].shape[1])
        n_layers = len(params["blocks"])
        hd = d_model // n_heads
        n_kv = (int(params["blocks"][0]["wqkv"].shape[1]) - d_model) \
            // 2 // hd
    except (KeyError, IndexError, AttributeError, TypeError) as e:
        raise BackendError(
            f"tensor_llm needs transformer-family params "
            f"(embed/blocks/ln_f/head pytree, models/transformer.py); "
            f"could not read dims: {e}") from e
    if hd * n_heads != d_model:
        raise BackendError(
            f"n_heads={n_heads} does not divide d_model={d_model}")
    return {"d_model": d_model, "vocab": vocab, "n_layers": n_layers,
            "head_dim": hd, "n_kv": n_kv}


class PagedLLMExecutor:
    """Device executor for the continuous-batching engine.

    `model` is a ``store://name[@version]`` ref (tracked or pinned, zoo
    builtins seed as @0) or a raw transformer params dict. One instance
    per engine; all methods run on the engine's single scheduler
    thread.
    """

    def __init__(self, model="store://transformer", *, n_heads: int = 4,
                 dtype=None, block_size: int = 16, num_blocks: int = 64,
                 max_len: int = 128, paged_kernel: Optional[str] = None,
                 shards: int = 0, shard_chips=None,
                 ring_prefill_min: int = 0,
                 tracer=NULL_TRACER, name: str = "llm"):
        import jax.numpy as jnp

        self.name = name
        self.tracer = tracer
        self.shards = int(shards)
        self.ring_prefill_min = int(ring_prefill_min)
        self.kernel_fallback = 0
        self.kernel_invokes: Dict[str, int] = {"pallas": 0, "xla": 0}
        kern = (paged_kernel or os.environ.get("NNS_PAGED_KERNEL")
                or "xla").strip().lower()
        if kern not in ("pallas", "xla"):
            raise BackendError(
                f"paged_kernel must be 'pallas' or 'xla', got {kern!r}")
        if kern == "pallas" and self.shards > 0:
            log.warning(
                "llm %s: paged_kernel=pallas is single-chip; shards=%d "
                "serves on the sharded XLA path (counted fallback)",
                name, self.shards)
            self.kernel_fallback += 1
            kern = "xla"
        if kern == "pallas":
            from nnstreamer_tpu.backends import pallas_paged

            if not pallas_paged.available():
                log.warning(
                    "llm %s: paged_kernel=pallas requested but the "
                    "Pallas paged kernels are unavailable here — "
                    "serving on the XLA reference (counted fallback)",
                    name)
                self.kernel_fallback += 1
                kern = "xla"
        self.paged_kernel = kern
        self.n_heads = int(n_heads)
        self.dtype = jnp.dtype(dtype) if dtype is not None \
            else jnp.float32
        self.max_len = int(max_len)
        self._entry = None
        self._pinned: Optional[int] = None
        self._version: Optional[int] = None
        self.adopted_epoch = -1
        self.swap_count = 0
        if isinstance(model, str):
            from nnstreamer_tpu.serving.store import (
                get_store, parse_store_ref)

            if model.startswith("zoo://"):
                model = "store://" + model[len("zoo://"):]
            ref = parse_store_ref(model)
            self._entry = get_store().entry(ref.name)
            if ref.version is not None:
                self._pinned = self._entry.resolve_version(ref.version)
                self._version = self._pinned
            else:
                cur, epoch = self._entry.state
                self._version, self.adopted_epoch = cur, epoch
            self.params = self._entry.bundle(self._version).params
            self._entry.attach(self)
        elif isinstance(model, dict):
            self.params = model
        else:
            raise BackendError(
                f"tensor_llm model must be a store:// ref or a params "
                f"dict, got {type(model).__name__}")
        dims = _derive_dims(self.params, self.n_heads)
        self.__dict__.update(dims)
        self._mesh = None
        self._shard_chips: tuple = ()
        self._sparams: Dict[Any, Any] = {}   # vkey → blocked+placed tree
        self._rparams: Dict[Any, Any] = {}   # vkey → replicated raw (ring)
        self._sspecs = None
        self._sfns = None
        placer = None
        if self.shards:
            from nnstreamer_tpu.serving import sharding as shg

            shg.validate_shards(self.shards)
            chips = tuple(int(c) for c in shard_chips) \
                if shard_chips is not None else tuple(range(self.shards))
            if len(chips) != self.shards:
                raise BackendError(
                    f"llm {name}: shards={self.shards} but {len(chips)} "
                    f"chips leased: {chips}")
            self._shard_chips = chips
            self._shard_devs = shg.shard_devices(chips)
            self._mesh = shg._tp_mesh(self._shard_devs)
            # raises the typed float-only / 8-divisibility errors up
            # front, before any pool or jit exists
            placed, self._sspecs = shg.shard_llm_params(
                self.params, self._mesh, n_heads=self.n_heads)
            self._sparams[self._vkey()] = placed
            placer = shg.kv_pool_placer(self._mesh)
        bs = int(block_size)
        self.max_blocks = max(1, -(-self.max_len // bs))
        self.cache = PagedKVCache(
            num_blocks=int(num_blocks), block_size=bs,
            n_layers=self.n_layers, n_kv=self.n_kv,
            head_dim=self.head_dim, placer=placer)
        #: (ns, kind, bucket) → jitted callable
        self._jits: Dict[tuple, Any] = {}
        self.compile_count = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.prefills = 0
        self.chunk_prefills = 0
        self.decode_steps = 0
        # compiled decode windows (decode_multi): windows dispatched /
        # decode steps served through a window
        self.decode_windows = 0
        self.window_steps = 0

    # -- store integration -------------------------------------------------
    def _vkey(self, version: Optional[int] = None):
        """Version key for the sharded param caches: the explicit
        version, else the bound one, else 0 for raw-dict models."""
        if version is not None:
            return version
        return self._version if self._entry is not None else 0

    def _ns(self, version: Optional[int] = None) -> tuple:
        if self.shards:
            return ("tp", self.shards, self._vkey(version))
        if self._entry is not None:
            return ("v", version if version is not None
                    else self._version)
        return ("g", 0)

    # -- sharded serving (serving/sharding.py) -----------------------------
    def _shard_fns(self):
        if self._sfns is None:
            from nnstreamer_tpu.serving import sharding as shg

            self._sfns = shg.make_llm_fns(self._mesh, self._sspecs,
                                          self._shard_devs)
        return self._sfns

    def _raw_params(self, vkey):
        if self._entry is not None and vkey != self._version:
            return self._entry.bundle(vkey).params
        return self.params

    def _exec_params(self, kind: str = "prefill", version=None):
        """The params tree one jit call serves: single-chip, the raw
        host tree; sharded, the canonically-blocked head-sharded tree
        for the version (ring prefill: the replicated raw tree), placed
        once per version and cached — a hot-path call is a dict hit."""
        if not self.shards:
            return self.params
        from nnstreamer_tpu.serving import sharding as shg

        vkey = self._vkey(version)
        if kind == "ring":
            if vkey not in self._rparams:
                self._rparams[vkey] = shg.replicate_params(
                    self._raw_params(vkey), self._mesh)
            return self._rparams[vkey]
        if vkey not in self._sparams:
            self._sparams[vkey], _ = shg.shard_llm_params(
                self._raw_params(vkey), self._mesh, n_heads=self.n_heads)
        return self._sparams[vkey]

    @property
    def tracks_store_epoch(self) -> bool:
        return self._entry is not None and self._pinned is None

    def maybe_adopt(self) -> None:
        """Adopt a flipped store epoch at a step boundary. In-flight
        sequences keep their old-version KV (documented serving
        tradeoff, docs/llm_serving.md) — retiring them instead would
        turn every swap into a latency spike for every live request."""
        if not self.tracks_store_epoch:
            return
        cur, epoch = self._entry.state        # one read = consistent
        if epoch == self.adopted_epoch:
            return
        old = self._version
        self.params = self._entry.bundle(cur).params
        dims = _derive_dims(self.params, self.n_heads)
        if dims["n_layers"] != self.n_layers or dims["n_kv"] != self.n_kv \
                or dims["head_dim"] != self.head_dim:
            # pool-incompatible geometry cannot serve in-flight
            # sequences; refuse the adoption loudly rather than corrupt
            raise BackendError(
                f"store swap {self._entry.name}@{old} → @{cur} changes "
                f"cache geometry (layers/kv-heads/head-dim); restart the "
                f"tensor_llm element to serve it")
        self.__dict__.update(dims)
        keep = {cur, self._pinned}
        if self.shards:
            for k in [k for k in self._jits
                      if k[0][0] == "tp" and k[0][2] not in keep]:
                del self._jits[k]
            self._sparams = {v: p for v, p in self._sparams.items()
                             if v in keep}
            self._rparams = {v: p for v, p in self._rparams.items()
                             if v in keep}
            # place cur now if the swap controller's prewarm missed us
            self._exec_params("prefill", cur)
        else:
            for k in [k for k in self._jits
                      if k[0][0] == "v" and k[0][1] not in keep]:
                del self._jits[k]
        self._version, self.adopted_epoch = cur, epoch
        self.swap_count += 1
        self.tracer.record_swap(
            self.name, time.perf_counter(), model=self._entry.name,
            from_version=old, to_version=cur, epoch=epoch,
            prewarmed=True)
        log.info("llm %s adopted %s@%d epoch=%d", self.name,
                 self._entry.name, cur, epoch)

    def _note_bucket(self, bucket_key: tuple) -> None:
        if self._entry is not None and self._version is not None:
            self._entry.note_bucket(self._version, bucket_key)

    # -- jit cache ---------------------------------------------------------
    def _kind_kernel(self, kind: str) -> str:
        """Which attention kernel serves `kind`. The full-sequence
        prefill is always the XLA `apply_seq_kv` path (it is the bit-
        parity anchor against `transformer.generate`); chunk and decode
        follow the selected kernel."""
        return "xla" if kind == "prefill" else self.paged_kernel

    def _prefill_kind(self) -> str:
        """Whole-prompt prefills route through the chunk family (one
        chunk covering the prompt) when the selected kernel is Pallas or
        the bound params are W8A8-quantized — `apply_seq_kv` is float-
        only and kernel-fixed; the chunk path is quant-aware and
        kernel-selectable. Float + xla keeps the original path, so the
        token-for-token `generate` parity contract is untouched there."""
        if self.shards:
            # sharded init already refused pallas and quantized params;
            # the ring cutover is decided per prompt in prefill()
            return "prefill"
        if self.paged_kernel == "pallas":
            return "chunk"
        try:
            if "wqkv_scale" in self.params["blocks"][0]:
                return "chunk"
        except (KeyError, IndexError, TypeError):
            pass
        return "prefill"

    def _get_jit(self, kind: str, bucket: int, version=None):
        import jax

        from nnstreamer_tpu.llm.paged_model import (
            paged_decode_step, paged_prefill, paged_prefill_chunk)

        kernel = self._kind_kernel(kind)
        key = (self._ns(version), kind, bucket, kernel)
        jitted = self._jits.get(key)
        if jitted is not None:
            self.cache_hits += 1
            return jitted, False
        self.cache_misses += 1
        if self.shards:
            if kind == "chunk":
                raise BackendError(
                    f"llm {self.name}: chunked prefill is not supported "
                    f"with shards={self.shards}; long prompts go through "
                    f"the sequence-parallel ring prefill "
                    f"(ring_prefill_min)")
            # one SPMD executable per bucket under ("tp", N, version) —
            # same donate/static discipline as the single-chip jits
            jitted = jax.jit(self._shard_fns()[kind],
                             static_argnames=("n_heads", "dtype"),
                             donate_argnums=(4, 5))
            self._jits[key] = jitted
            return jitted, True
        if kind == "prefill":
            fn, donate = paged_prefill, (4, 5)
        elif kind == "chunk":
            if kernel == "pallas":
                from nnstreamer_tpu.backends.pallas_paged import (
                    paged_flash_prefill_chunk)
                fn = paged_flash_prefill_chunk
            else:
                fn = paged_prefill_chunk
            donate = (6, 7)
        else:
            if kernel == "pallas":
                from nnstreamer_tpu.backends.pallas_paged import (
                    paged_flash_decode_step)
                fn = paged_flash_decode_step
            else:
                fn = paged_decode_step
            donate = (4, 5)
        jitted = jax.jit(fn, static_argnames=("n_heads", "dtype"),
                         donate_argnums=donate)
        self._jits[key] = jitted
        return jitted, True

    def _kernel_fallback_to_xla(self, kind: str, exc: Exception) -> None:
        """A fresh Pallas compile failed at serve time: flip the whole
        executor to the XLA reference (sticky — one flip, not one per
        call), count it, and keep serving. Never an error."""
        log.warning(
            "llm %s: pallas %s kernel failed to build (%s: %s) — "
            "falling back to the XLA reference", self.name, kind,
            type(exc).__name__, exc)
        self.kernel_fallback += 1
        self.paged_kernel = "xla"

    def _span(self, kind: str, t0: float, t1: float, **args) -> None:
        if self.tracer.active:
            self.tracer.backend_span(self.name, kind, t0, t1, **args)

    # -- device performance plane (runtime/devprof.py) ---------------------
    def resident_bytes(self) -> int:
        """Device bytes this executor pins: params + the paged KV pool
        — the executor-level HBM attribution row."""
        import jax

        if self.shards:
            # device-resident = the placed trees (blocked + any ring
            # replicas, every cached version), not the raw host pytree
            n = sum(
                getattr(a, "nbytes", 0)
                for tree in list(self._sparams.values())
                + list(self._rparams.values())
                for a in jax.tree_util.tree_leaves(tree))
        else:
            n = sum(getattr(a, "nbytes", 0)
                    for a in jax.tree_util.tree_leaves(self.params))
        for a in (self.cache.k, self.cache.v):
            n += getattr(a, "nbytes", 0)
        return n

    def _prof_capture(self, bucket: str, jitted, args: tuple,
                      kwargs: dict, seconds: float) -> None:
        """Compile-event capture: cost-model read on the freshly
        compiled bucket (re-lower only; compile misses are rare by
        construction — prewarm_buckets exists to make them zero)."""
        prof = devprof.get()
        if not prof.enabled:
            return
        prof.attach_model(self.name, self)
        prof.capture_cost(self.name, bucket, jitted, args,
                          kwargs=kwargs, seconds=seconds)

    # -- prefill -----------------------------------------------------------
    def prefill(self, prompt: np.ndarray, block_table: List[int],
                *, sync: bool = True):
        """One whole prompt; its KV lands in the pool blocks of
        `block_table`. Dispatches between the full-sequence
        `apply_seq_kv` path and the chunk family (`_prefill_kind` —
        pallas / quantized stores go through the chunk path, as one
        chunk covering the prompt). Returns last-token logits: a host
        (vocab,) f32 array when `sync`, else the device array so the
        engine can batch one `device_sync` over a whole step's
        admissions."""
        from nnstreamer_tpu.backends.xla import _next_pow2

        plen = int(prompt.shape[0])
        if self._prefill_kind() == "chunk":
            return self.prefill_chunk(
                prompt, 0, block_table,
                bucket=_next_pow2(plen, 8), sync=sync)
        kind = "prefill"
        if self.shards and 0 < self.ring_prefill_min <= plen:
            kind = "ring"    # sequence-parallel long-context cutover
        s_b = _next_pow2(plen, 8)
        bs = self.cache.block_size
        ids = np.zeros((1, s_b), np.int32)
        ids[0, :plen] = prompt
        blk_idx = np.full((s_b,), SCRATCH_BLOCK, np.int32)
        pos = np.arange(plen)
        blk_idx[:plen] = np.asarray(block_table, np.int32)[pos // bs]
        blk_off = (np.arange(s_b) % bs).astype(np.int32)
        jitted, fresh = self._get_jit(kind, s_b)
        sp = self._exec_params(kind)
        prof = devprof.get()
        if prof.enabled:
            prof.note_dispatch(self.name, f"{kind}:{s_b}")
        t0 = time.perf_counter()
        logits, self.cache.k, self.cache.v = jitted(
            sp, ids, blk_idx, blk_off, self.cache.k,
            self.cache.v, np.int32(plen - 1), n_heads=self.n_heads,
            dtype=self.dtype)
        out = np.asarray(device_sync(
            logits, tracer=self.tracer,
            name=f"{self.name}:prefill")) if sync else logits
        t1 = time.perf_counter()
        kernel = "ring" if kind == "ring" else "xla"
        if fresh:
            self.compile_count += 1
            self._span("compile", t0, t1, what="llm_prefill", bucket=s_b,
                       kernel=kernel)
            self._note_bucket(
                ("llmr" if kind == "ring" else "llmp", s_b))
            self._prof_capture(
                f"{kind}:{s_b}", jitted,
                (sp, ids, blk_idx, blk_off, self.cache.k,
                 self.cache.v, np.int32(plen - 1)),
                {"n_heads": self.n_heads, "dtype": self.dtype}, t1 - t0)
        else:
            self._span("invoke", t0, t1, what="llm_prefill", bucket=s_b,
                       plen=plen, kernel=kernel)
        self.prefills += 1
        self.kernel_invokes["xla"] += 1
        return out

    def prefill_chunk(self, chunk: np.ndarray, pos0: int,
                      block_table: List[int], *, bucket: int = 0,
                      sync: bool = True):
        """One prompt chunk starting at absolute position `pos0`,
        scattered into `block_table`'s blocks and attending the whole
        prefix written so far. `bucket` pins the pad width so every
        chunk of a prompt (the short final one included) hits one
        executable; 0 = pow2 of this chunk. Returns the chunk's
        last-token logits (host when `sync`, device otherwise) — only
        the final chunk's value is meaningful to sampling."""
        from nnstreamer_tpu.backends.xla import _next_pow2

        if self.shards:
            raise BackendError(
                f"llm {self.name}: chunked prefill is not supported with "
                f"shards={self.shards}; long prompts go through the "
                f"sequence-parallel ring prefill (ring_prefill_min)")
        clen = int(chunk.shape[0])
        c_b = max(int(bucket) or 0, _next_pow2(clen, 8))
        bs = self.cache.block_size
        ids = np.zeros((1, c_b), np.int32)
        ids[0, :clen] = chunk
        blk_idx = np.full((c_b,), SCRATCH_BLOCK, np.int32)
        pos = int(pos0) + np.arange(clen)
        blk_idx[:clen] = np.asarray(block_table, np.int32)[pos // bs]
        blk_off = ((int(pos0) + np.arange(c_b)) % bs).astype(np.int32)
        tab = np.full((self.max_blocks,), SCRATCH_BLOCK, np.int32)
        tab[:len(block_table)] = block_table
        args = (ids, blk_idx, blk_off, tab, np.int32(clen - 1))

        def _run():
            jitted, fresh = self._get_jit("chunk", c_b)
            logits, self.cache.k, self.cache.v = jitted(
                self.params, args[0], np.int32(pos0), args[1], args[2],
                args[3], self.cache.k, self.cache.v, args[4],
                n_heads=self.n_heads, dtype=self.dtype)
            return logits, fresh

        prof = devprof.get()
        if prof.enabled:
            prof.note_dispatch(self.name, f"chunk:{c_b}")
        t0 = time.perf_counter()
        try:
            logits, fresh = _run()
        except Exception as e:
            if self.paged_kernel != "pallas":
                raise
            self._kernel_fallback_to_xla("chunk", e)
            logits, fresh = _run()
        kernel = self._kind_kernel("chunk")
        out = np.asarray(device_sync(
            logits, tracer=self.tracer,
            name=f"{self.name}:prefill_chunk")) if sync else logits
        t1 = time.perf_counter()
        if fresh:
            self.compile_count += 1
            self._span("compile", t0, t1, what="llm_prefill_chunk",
                       bucket=c_b, kernel=kernel)
            self._note_bucket(("llmp_chunk", c_b))
            jitted, _ = self._get_jit("chunk", c_b)
            self._prof_capture(
                f"chunk:{c_b}", jitted,
                (self.params, args[0], np.int32(pos0), args[1], args[2],
                 args[3], self.cache.k, self.cache.v, args[4]),
                {"n_heads": self.n_heads, "dtype": self.dtype}, t1 - t0)
        else:
            self._span("invoke", t0, t1, what="llm_prefill_chunk",
                       bucket=c_b, clen=clen, kernel=kernel)
        self.chunk_prefills += 1
        self.kernel_invokes[kernel] += 1
        return out

    # -- decode ------------------------------------------------------------
    def decode(self, cur: List[int], tables: List[List[int]],
               pos: List[int], *, sync: bool = True):
        """One decode step for `len(cur)` live rows (bucketed to pow2;
        padding rows write to the scratch block). With `sync` (default)
        returns host logits (n, vocab) f32 for the live rows only; with
        sync=False returns the padded device array (b_b, vocab) so the
        engine can fold this step's decode into its single whole-step
        `device_sync` (caller slices [:n] after syncing)."""
        from nnstreamer_tpu.backends.xla import _next_pow2

        n = len(cur)
        b_b = _next_pow2(n, 1)
        cur_a = np.zeros((b_b,), np.int32)
        cur_a[:n] = cur
        tab_a = np.full((b_b, self.max_blocks), SCRATCH_BLOCK, np.int32)
        for i, t in enumerate(tables):
            tab_a[i, :len(t)] = t
        pos_a = np.zeros((b_b,), np.int32)
        pos_a[:n] = pos

        def _run():
            jitted, fresh = self._get_jit("decode", b_b)
            logits, self.cache.k, self.cache.v = jitted(
                self._exec_params("decode"), cur_a, tab_a, pos_a,
                self.cache.k, self.cache.v, n_heads=self.n_heads,
                dtype=self.dtype)
            return logits, fresh

        prof = devprof.get()
        if prof.enabled:
            prof.note_dispatch(self.name, f"decode:{b_b}")
        t0 = time.perf_counter()
        try:
            logits, fresh = _run()
        except Exception as e:
            if self.paged_kernel != "pallas":
                raise
            self._kernel_fallback_to_xla("decode", e)
            logits, fresh = _run()
        kernel = self._kind_kernel("decode")
        out = np.asarray(device_sync(
            logits, tracer=self.tracer,
            name=f"{self.name}:decode"))[:n] if sync else logits
        t1 = time.perf_counter()
        if fresh:
            self.compile_count += 1
            self._span("compile", t0, t1, what="llm_decode", bucket=b_b,
                       kernel=kernel)
            self._note_bucket(("llmd", b_b))
            jitted, _ = self._get_jit("decode", b_b)
            self._prof_capture(
                f"decode:{b_b}", jitted,
                (self._exec_params("decode"), cur_a, tab_a, pos_a,
                 self.cache.k, self.cache.v),
                {"n_heads": self.n_heads, "dtype": self.dtype}, t1 - t0)
        else:
            self._span("invoke", t0, t1, what="llm_decode", bucket=b_b,
                       rows=n, kernel=kernel)
        self.decode_steps += 1
        self.kernel_invokes[kernel] += 1
        return out

    def _get_multi_jit(self, bucket: int, steps: int, version=None):
        """Jitted K-step greedy decode window: ``jax.lax.scan`` whose
        body is exactly the per-step decode kernel plus an on-device
        ``jnp.argmax`` feeding the next step. One cache entry per
        (bucket, steps) pair — the engine rounds `steps` down to a
        power of two so the cache stays O(log K) per bucket."""
        import jax
        import jax.numpy as jnp

        kernel = self._kind_kernel("decode")
        key = (self._ns(version), "decmulti", bucket, steps, kernel)
        jitted = self._jits.get(key)
        if jitted is not None:
            self.cache_hits += 1
            return jitted, False
        self.cache_misses += 1
        if kernel == "pallas":
            from nnstreamer_tpu.backends.pallas_paged import (
                paged_flash_decode_step)
            step_fn = paged_flash_decode_step
        else:
            from nnstreamer_tpu.llm.paged_model import paged_decode_step
            step_fn = paged_decode_step

        def multi(params, cur, tab, pos, kc, vc, *, n_heads, dtype):
            def body(carry, _):
                cur_, pos_, kc_, vc_ = carry
                logits, kc2, vc2 = step_fn(
                    params, cur_, tab, pos_, kc_, vc_,
                    n_heads=n_heads, dtype=dtype)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (nxt, pos_ + 1, kc2, vc2), nxt
            (_, _, kc_f, vc_f), toks = jax.lax.scan(
                body, (cur, pos, kc, vc), None, length=steps)
            return toks, kc_f, vc_f

        jitted = jax.jit(multi, static_argnames=("n_heads", "dtype"),
                         donate_argnums=(4, 5))
        self._jits[key] = jitted
        return jitted, True

    def decode_multi(self, cur: List[int], tables: List[List[int]],
                     pos: List[int], steps: int) -> np.ndarray:
        """`steps` greedy decode steps for `len(cur)` live rows as ONE
        compiled dispatch (the engine's `decode_window` fast path): the
        sampled token feeds the next step on-device, so the host pays
        one Python dispatch and one sync per window instead of one per
        token. Returns a host (n, steps) int32 token matrix.

        The caller guarantees the window invariants (llm/engine.py
        `_window_len`): every row is greedy (temperature<=0, matching
        the host argmax tie-breaking bit for bit), `steps` never
        exceeds any row's remaining token budget (block tables are
        fully pre-allocated at admission, so position pos+steps-1 is
        always backed), and rows that hit EOS mid-window have their
        trailing tokens discarded host-side — the extra KV writes land
        in blocks the row still owned when the window ran."""
        from nnstreamer_tpu.backends.xla import _next_pow2

        n = len(cur)
        steps = int(steps)
        b_b = _next_pow2(n, 1)
        cur_a = np.zeros((b_b,), np.int32)
        cur_a[:n] = cur
        tab_a = np.full((b_b, self.max_blocks), SCRATCH_BLOCK, np.int32)
        for i, t in enumerate(tables):
            tab_a[i, :len(t)] = t
        pos_a = np.zeros((b_b,), np.int32)
        pos_a[:n] = pos

        def _run():
            jitted, fresh = self._get_multi_jit(b_b, steps)
            toks, self.cache.k, self.cache.v = jitted(
                self._exec_params("decode"), cur_a, tab_a, pos_a,
                self.cache.k, self.cache.v, n_heads=self.n_heads,
                dtype=self.dtype)
            return toks, fresh

        prof = devprof.get()
        if prof.enabled:
            prof.note_dispatch(self.name, f"decmulti:{b_b}x{steps}")
        t0 = time.perf_counter()
        try:
            toks, fresh = _run()
        except Exception as e:
            if self.paged_kernel != "pallas":
                raise
            self._kernel_fallback_to_xla("decode", e)
            toks, fresh = _run()
        kernel = self._kind_kernel("decode")
        out = np.asarray(device_sync(
            toks, tracer=self.tracer,
            name=f"{self.name}:decmulti"))[:, :n].T
        t1 = time.perf_counter()
        if fresh:
            self.compile_count += 1
            self._span("compile", t0, t1, what="llm_decode_multi",
                       bucket=b_b, steps=steps, kernel=kernel)
            self._note_bucket(("llmw", b_b, steps))
        else:
            self._span("invoke", t0, t1, what="llm_decode_multi",
                       bucket=b_b, steps=steps, rows=n, kernel=kernel)
        # the ledger counts the same decode steps whether or not the
        # window path served them — parity with per-step mode
        self.decode_steps += steps
        self.kernel_invokes[kernel] += steps
        self.decode_windows += 1
        self.window_steps += steps
        return out

    # -- warm paths --------------------------------------------------------
    def _warm_compile(self, kind: str, bucket: int, version=None,
                      params=None) -> bool:
        """Compile one bucket off the hot path by running the jit on
        DUMMY inputs whose every write targets the scratch block — by
        construction that corrupts nothing (scratch absorbs garbage by
        design), and unlike `.lower().compile()` a real invocation
        populates the jit's dispatch cache, so the first *served*
        request is a cache hit, not a second compile. Returns whether a
        fresh executable was built."""
        key = (self._ns(version), kind, bucket, self._kind_kernel(kind))
        if key in self._jits:
            return False
        jitted, _ = self._get_jit(kind, bucket, version)
        if self.shards:
            # sharded jits only accept the placed (blocked / replicated)
            # tree for the version — never a caller-supplied raw tree
            params = self._exec_params(kind, version)
        else:
            params = self.params if params is None else params
        prof = devprof.get()
        if prof.enabled:
            prof.note_dispatch(self.name, f"{kind}:{bucket}")
        t0 = time.perf_counter()
        if kind in ("prefill", "ring"):
            ids = np.zeros((1, bucket), np.int32)
            blk = np.full((bucket,), SCRATCH_BLOCK, np.int32)
            off = (np.arange(bucket)
                   % self.cache.block_size).astype(np.int32)
            logits, self.cache.k, self.cache.v = jitted(
                params, ids, blk, off, self.cache.k, self.cache.v,
                np.int32(0), n_heads=self.n_heads, dtype=self.dtype)
            largs = (params, ids, blk, off, self.cache.k, self.cache.v,
                     np.int32(0))
        elif kind == "chunk":
            ids = np.zeros((1, bucket), np.int32)
            blk = np.full((bucket,), SCRATCH_BLOCK, np.int32)
            off = (np.arange(bucket)
                   % self.cache.block_size).astype(np.int32)
            tab = np.full((self.max_blocks,), SCRATCH_BLOCK, np.int32)
            logits, self.cache.k, self.cache.v = jitted(
                params, ids, np.int32(0), blk, off, tab, self.cache.k,
                self.cache.v, np.int32(0), n_heads=self.n_heads,
                dtype=self.dtype)
            largs = (params, ids, np.int32(0), blk, off, tab,
                     self.cache.k, self.cache.v, np.int32(0))
        else:
            cur = np.zeros((bucket,), np.int32)
            tab = np.full((bucket, self.max_blocks), SCRATCH_BLOCK,
                          np.int32)
            pos = np.zeros((bucket,), np.int32)
            logits, self.cache.k, self.cache.v = jitted(
                params, cur, tab, pos, self.cache.k, self.cache.v,
                n_heads=self.n_heads, dtype=self.dtype)
            largs = (params, cur, tab, pos, self.cache.k, self.cache.v)
        device_sync(logits, tracer=self.tracer,
                    name=f"{self.name}:warm_{kind}")
        self.compile_count += 1
        t1 = time.perf_counter()
        self._span("compile", t0, t1, what=f"llm_{kind}_warm",
                   bucket=bucket)
        self._prof_capture(f"{kind}:{bucket}", jitted, largs,
                           {"n_heads": self.n_heads, "dtype": self.dtype},
                           t1 - t0)
        return True

    def prewarm_buckets(self, *, max_batch: int, max_prompt: int,
                        chunk: int = 0) -> int:
        """Eagerly compile every bucket a serving run can hit: decode
        pow2 buckets up to `max_batch`, prefill pow2 buckets up to
        `max_prompt`, and — when the engine runs chunked prefill — the
        one chunk bucket. Start-time cost, zero hot-path compiles
        after."""
        from nnstreamer_tpu.backends.xla import _next_pow2

        compiled = 0
        b, top_b = 1, _next_pow2(max(1, max_batch), 1)
        while b <= top_b:
            compiled += int(self._warm_compile("decode", b))
            b *= 2
        if chunk > 0:
            compiled += int(self._warm_compile(
                "chunk", _next_pow2(chunk, 8)))
        if self._prefill_kind() == "chunk":
            # whole-prompt prefills route through the chunk family too
            s, top_s = 8, _next_pow2(
                min(max(1, max_prompt), self.max_len), 8)
            while s <= top_s:
                compiled += int(self._warm_compile("chunk", s))
                s *= 2
            return compiled
        s, top_s = 8, _next_pow2(
            min(max(1, max_prompt), self.max_len), 8)
        while s <= top_s:
            compiled += int(self._warm_compile("prefill", s))
            s *= 2
        if self.shards and self.ring_prefill_min > 0:
            # buckets a ring-cutover prompt can land in
            s = _next_pow2(max(8, self.ring_prefill_min), 8)
            while s <= top_s:
                compiled += int(self._warm_compile("ring", s))
                s *= 2
        return compiled

    def warm_start(self) -> int:
        """Replay the persistent manifest's prefill/decode buckets for
        the bound version (element start(), off the hot path)."""
        if self._entry is None:
            return 0
        from nnstreamer_tpu.serving.compile_cache import manifest_buckets

        compiled = 0
        for bk in manifest_buckets(self._entry.name, self._version):
            try:
                if bk[0] == "llmp":
                    compiled += int(self._warm_compile("prefill", bk[1]))
                elif bk[0] == "llmd":
                    compiled += int(self._warm_compile("decode", bk[1]))
                elif bk[0] == "llmp_chunk" and not self.shards:
                    compiled += int(self._warm_compile("chunk", bk[1]))
                elif bk[0] == "llmr" and self.shards:
                    compiled += int(self._warm_compile("ring", bk[1]))
            except Exception as e:    # warm start is never a gate
                log.warning("llm warm_start bucket %s failed: %s", bk, e)
        return compiled

    def prewarm_version(self, version: int, bundle) -> int:
        """Swap-controller hook (serving/store.py update()): compile the
        incoming version's executables for every bucket this executor
        has served, before the epoch flips."""
        params = getattr(bundle, "params", bundle)
        dims = _derive_dims(params, self.n_heads)
        if dims["n_layers"] != self.n_layers or dims["n_kv"] != self.n_kv \
                or dims["head_dim"] != self.head_dim:
            raise BackendError(
                f"incoming {self._entry.name}@{version} changes cache "
                f"geometry; tensor_llm cannot hot-swap it over live "
                f"paged state — swap aborted")
        if self.shards:
            # place the incoming version's blocked tree NOW, from the
            # bundle in hand — if blocking refuses it (quantized, bad
            # divisibility) the swap aborts before any epoch flips
            from nnstreamer_tpu.serving import sharding as shg

            self._sparams[version], _ = shg.shard_llm_params(
                params, self._mesh, n_heads=self.n_heads)
            if self.ring_prefill_min > 0:
                self._rparams[version] = shg.replicate_params(
                    params, self._mesh)
        served = sorted({(k[1], k[2]) for k in self._jits})
        compiled = 0
        for kind, bucket in served:
            if self._warm_compile(kind, bucket, version=version):
                compiled += 1
        return compiled

    def close(self) -> None:
        if self._entry is not None:
            try:
                self._entry.detach(self)
            except Exception:
                pass
        self._jits.clear()
        self._sparams.clear()
        self._rparams.clear()
        self._sfns = None

    def stats(self) -> dict:
        out = {
            "compile_count": self.compile_count,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "prefills": self.prefills,
            "chunk_prefills": self.chunk_prefills,
            "decode_steps": self.decode_steps,
            "decode_windows": self.decode_windows,
            "window_steps": self.window_steps,
            "swap_count": self.swap_count,
            "paged_kernel": self.paged_kernel,
            "kernel_invokes": dict(self.kernel_invokes),
            "kernel_fallback": self.kernel_fallback,
        }
        if self.shards:
            out["shards"] = self.shards
            out["shard_chips"] = list(self._shard_chips)
            out["ring_prefill_min"] = self.ring_prefill_min
        if self._entry is not None:
            out["store"] = f"{self._entry.name}@{self._version}"
        return out
