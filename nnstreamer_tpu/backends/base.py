"""Filter backend interface.

Reference parity: `GstTensorFilterFramework` v1 vtable
(include/nnstreamer_plugin_api_filter.h:273 — open/close/invoke/
getModelInfo/eventHandler). Differences, TPU-first:

- `invoke` takes/returns tuples of arrays (numpy or jax.Array) instead of
  raw memory chunks; a backend may return device arrays so downstream
  elements stay zero-copy on device.
- `fuse(pre, post)` lets the filter element hand the backend the
  elementwise pre/post-processing chains adjacent to it in the graph, so
  they compile **into the same XLA computation** (the north-star fusion;
  no reference equivalent).
- `reload(model)` is the is-updatable hot-swap hook
  (plugin_api_filter.h:377 reloadModel).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from nnstreamer_tpu.core.errors import BackendError, CircuitOpenError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.core.registry import PluginKind, registry
from nnstreamer_tpu.runtime.tracing import NULL_TRACER
from nnstreamer_tpu.tensor.info import TensorsSpec

log = get_logger("backend")

ArrayTuple = Tuple[Any, ...]
ElementwiseFn = Callable[[ArrayTuple], ArrayTuple]


class FilterBackend:
    """One model-execution engine instance (per tensor_filter element)."""

    BACKEND_NAME: str = ""
    #: tracing hooks — the owning tensor_filter forwards the session
    #: tracer (and its element name) at start(), so backends can record
    #: compile/invoke spans onto the element's track when tracer.active
    tracer = NULL_TRACER
    trace_name: str = ""
    #: invoke exceptions observed by the owning tensor_filter (surfaced
    #: as backend_invoke_failures in stats; breaker short-circuits are
    #: NOT counted — the backend was never touched)
    invoke_failures: int = 0
    #: store:// serving (serving/store.py): epoch adoptions this backend
    #: has performed (0 for backends not bound to the model store)
    swap_count: int = 0

    def version_stats(self) -> Dict[int, dict]:
        """Per-version serving counters for a store-bound backend
        ({version: {invokes, errors, p95_us}}); empty otherwise.
        Surfaced by tensor_filter.extra_stats for canary comparisons."""
        return {}

    def warm_start(self) -> int:
        """Off-hot-path warmup hook, called by the owning element's
        start(): a store-bound backend replays its persistent bucket
        manifest here (serving/compile_cache.py). Returns the number of
        buckets compiled; default no-op."""
        return 0

    def open(self, props: Dict[str, Any]) -> None:
        """Load the model described by element properties (fw->open)."""
        raise NotImplementedError

    def close(self) -> None:
        pass

    def get_model_info(self) -> Tuple[Optional[TensorsSpec], Optional[TensorsSpec]]:
        """→ (input spec, output spec); either may be None if the model
        adapts to the negotiated input (fw->getModelInfo)."""
        raise NotImplementedError

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        """Fix the input spec for adaptive models → resulting output spec
        (fw->getModelInfo(SET_INPUT_INFO) analog)."""
        raise BackendError(
            f"backend {self.BACKEND_NAME!r} does not support dynamic input "
            f"reconfiguration; set the model's input dimensions explicitly"
        )

    def fuse(self, pre: Optional[ElementwiseFn], post: Optional[ElementwiseFn]) -> bool:
        """Offer pre/post elementwise chains for compilation into the
        model's computation. Return True if absorbed (the element then
        skips host-side application). Default: not absorbed."""
        return False

    def invoke(self, tensors: ArrayTuple) -> ArrayTuple:
        """Run the model on one frame's tensors (the hot loop)."""
        raise NotImplementedError

    def invoke_flexible(self, regions: Sequence[Any]) -> Sequence[Any]:
        """Run the model over variable-shape per-buffer regions (FLEXIBLE
        streams, e.g. tensor_crop output). Default: one invoke per
        region; XLABackend overrides with batched + bucketed compiles."""
        return [self.invoke((r,))[0] for r in regions]

    def invoke_batched(self, tensors: ArrayTuple, n: int,
                       keepdims: Sequence[bool] = ()) -> ArrayTuple:
        """Run the model over a micro-batched frame (tensor_batch
        upstream): each input tensor carries `n` frames coalesced on
        axis 0 — concatenated where the per-frame leading dim is 1
        (keepdims[j] True, rank preserved), stacked on a new axis
        otherwise. Outputs must come back batched by the same rule
        (leading dim 1 per frame → concatenated, else stacked).

        Default: one invoke per frame, outputs restacked on the host —
        correct for any backend. XLABackend overrides with a single
        padded, bucket-compiled batched XLA call."""
        frames_out = []
        for i in range(n):
            frame = tuple(
                t[i:i + 1] if (j < len(keepdims) and keepdims[j]) else t[i]
                for j, t in enumerate(tensors)
            )
            frames_out.append(self.invoke(frame))
        return _restack_frames(frames_out)

    def reload(self, model: Any) -> None:
        raise BackendError(
            f"backend {self.BACKEND_NAME!r} does not support model reload"
        )


def _restack_frames(frames_out: Sequence[ArrayTuple]) -> ArrayTuple:
    """Recombine per-frame invoke outputs into batched wire format:
    per output k, concatenate along axis 0 when the per-frame result has
    a leading dim of 1, else stack on a new axis (the same rule
    tensor_batch applies on the input side, so tensor_unbatch can split
    by rank alone)."""
    out = []
    for k in range(len(frames_out[0])):
        rows = [f[k] for f in frames_out]
        if any(type(r).__module__.startswith("jax") for r in rows):
            import jax.numpy as xp
        else:
            import numpy as xp
        keep = len(rows[0].shape) >= 1 and rows[0].shape[0] == 1
        out.append(xp.concatenate(rows, axis=0) if keep
                   else xp.stack(rows, axis=0))
    return tuple(out)


class CircuitBreaker:
    """Consecutive-failure circuit breaker around backend invokes
    (docs/robustness.md state machine):

    - **closed** (normal): invokes pass through; `threshold` consecutive
      failures open the circuit.
    - **open**: `guard()` raises CircuitOpenError without touching the
      backend, so the owning tensor_filter's error policy serves the
      degrade/skip path at queue speed instead of stacking timeouts on
      a dead backend. After `cooldown_s` the next guard() half-opens.
    - **half-open**: exactly one probe invoke passes through — success
      closes the circuit, failure re-opens it with a fresh cooldown.

    Driven by the single worker thread of the owning element, so state
    transitions need no lock. `clock` is injectable for deterministic
    unit tests.
    """

    def __init__(self, threshold: int, cooldown_s: float,
                 clock: Callable[[], float] = time.monotonic):
        if threshold < 1:
            raise ValueError(f"breaker threshold must be >= 1, got "
                             f"{threshold}")
        self.threshold = threshold
        self.cooldown_s = max(0.0, cooldown_s)
        self._clock = clock
        self.state = "closed"            # closed | open | half_open
        self._failures = 0               # consecutive, current streak
        self._opened_at = 0.0
        # observability counters (surfaced via tensor_filter extra_stats)
        self.opened_count = 0
        self.short_circuited = 0
        self.probes = 0
        self.recoveries = 0

    def guard(self, owner: str = "backend") -> None:
        """Call before an invoke. Raises CircuitOpenError while the
        circuit is open and cooling down; transitions open → half_open
        once the cooldown has elapsed (the caller's next invoke is the
        probe)."""
        if self.state == "closed":
            return
        if self.state == "open":
            waited = self._clock() - self._opened_at
            if waited < self.cooldown_s:
                self.short_circuited += 1
                raise CircuitOpenError(
                    f"{owner}: circuit open after {self._failures} "
                    f"consecutive backend failures; cooling down "
                    f"({self.cooldown_s - waited:.2f}s of "
                    f"{self.cooldown_s:.2f}s left) — serving the "
                    f"fallback/skip path"
                )
            self.state = "half_open"
            self.probes += 1
            log.info("%s: circuit half-open — probing backend", owner)
        # half_open: let exactly this invoke through as the probe

    def record_success(self) -> None:
        if self.state != "closed":
            self.recoveries += 1
            log.info("circuit closed — probe invoke succeeded")
        self.state = "closed"
        self._failures = 0

    def record_failure(self) -> None:
        self._failures += 1
        if self.state == "half_open" or (
                self.state == "closed" and self._failures >= self.threshold):
            self.state = "open"
            self._opened_at = self._clock()
            self.opened_count += 1
            log.warning("circuit opened after %d consecutive backend "
                        "failure(s); cooling down %.2fs",
                        self._failures, self.cooldown_s)

    def stats(self) -> Dict[str, Any]:
        return {"state": self.state,
                "consecutive_failures": self._failures,
                "opened": self.opened_count,
                "short_circuited": self.short_circuited,
                "probes": self.probes,
                "recoveries": self.recoveries}


def register_backend(name: str):
    """Class decorator registering a FilterBackend under `name`."""
    def deco(cls):
        cls.BACKEND_NAME = name
        registry.register(PluginKind.FILTER, name, cls)
        return cls
    return deco


def get_backend(name: str) -> FilterBackend:
    cls = registry.get(PluginKind.FILTER, name)
    return cls()
