"""Custom python-callable backend (the custom-easy analog).

Reference parity: include/tensor_filter_custom_easy.h
(`NNS_custom_easy_register` — register an in-process function + fixed
in/out info under a name, then `framework=custom-easy model=<name>`), and
tensor_filter_custom.c for loading user code by path.

Two ways to name a model:
- a registered name (``register_custom_easy("scaler", fn, in_spec,
  out_spec)`` → ``framework=custom model=scaler``)
- a python path ``pkg.module:callable`` imported on open (the .so-loading
  analog); the callable may carry ``in_spec``/``out_spec`` attributes.

These double as the **fake frameworks** of the test strategy (SURVEY.md §4
takeaway a): deterministic element tests with no XLA in the loop.
"""

from __future__ import annotations

import importlib
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

from nnstreamer_tpu.backends.base import ArrayTuple, FilterBackend, register_backend
from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.tensor.info import TensorsSpec


@dataclass
class _CustomEntry:
    fn: Callable[[ArrayTuple], ArrayTuple]
    in_spec: Optional[TensorsSpec]
    out_spec: Optional[TensorsSpec]
    # optional: out spec as a function of in spec (adaptive models)
    infer_out: Optional[Callable[[TensorsSpec], TensorsSpec]] = None


_table: Dict[str, _CustomEntry] = {}
_table_lock = threading.Lock()


def register_custom_easy(
    name: str,
    fn: Callable[[ArrayTuple], ArrayTuple],
    in_spec: Optional[TensorsSpec] = None,
    out_spec: Optional[TensorsSpec] = None,
    infer_out: Optional[Callable[[TensorsSpec], TensorsSpec]] = None,
) -> Callable:
    """Register `fn` as an invokable model under `name`.

    `fn` maps a tuple of arrays to a tuple of arrays. Specs may be omitted
    for passthrough-shaped models, or `infer_out` given for adaptive ones.
    """
    with _table_lock:
        _table[name] = _CustomEntry(fn, in_spec, out_spec, infer_out)
    return fn


def unregister_custom_easy(name: str) -> bool:
    with _table_lock:
        return _table.pop(name, None) is not None


@register_backend("custom")
class CustomBackend(FilterBackend):
    def __init__(self):
        self._entry: Optional[_CustomEntry] = None
        self._model_name = ""

    def open(self, props: Dict[str, Any]) -> None:
        model = props.get("model")
        if not model:
            raise BackendError(
                "framework=custom requires model=<registered name or "
                "python path 'pkg.module:callable'>"
            )
        self._model_name = model
        with _table_lock:
            entry = _table.get(model)
        if entry is None and (":" in model):
            entry = self._load_python_path(model)
        if entry is None:
            with _table_lock:
                names = sorted(_table)
            raise BackendError(
                f"no custom model {model!r}; registered: {names or '(none)'}. "
                f"Use register_custom_easy() or a 'pkg.module:callable' path."
            )
        self._entry = entry

    def _load_python_path(self, path: str) -> _CustomEntry:
        mod_name, _, attr = path.partition(":")
        try:
            mod = importlib.import_module(mod_name)
            fn = getattr(mod, attr)
        except (ImportError, AttributeError) as e:
            raise BackendError(
                f"cannot load custom model {path!r}: {e}"
            ) from e
        return _CustomEntry(
            fn,
            getattr(fn, "in_spec", None),
            getattr(fn, "out_spec", None),
            getattr(fn, "infer_out", None),
        )

    def get_model_info(self):
        assert self._entry is not None, "open() not called"
        return self._entry.in_spec, self._entry.out_spec

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        assert self._entry is not None
        if self._entry.infer_out is not None:
            return self._entry.infer_out(in_spec)
        if self._entry.out_spec is not None:
            return self._entry.out_spec
        # No declared output spec: probe the callable once with zeros so
        # negotiation reflects reality (custom fns must be side-effect-free
        # or declare out_spec/infer_out explicitly).
        import numpy as np

        from nnstreamer_tpu.tensor.buffer import TensorBuffer

        probe = tuple(
            np.zeros(t.shape, t.dtype.np_dtype) for t in in_spec.tensors
        )
        try:
            out = self.invoke(probe)
        except Exception as e:
            raise BackendError(
                f"custom model {self._model_name!r} declares no output spec "
                f"and probing it with zero input {in_spec} failed: {e}. "
                f"Register it with out_spec= or infer_out= instead."
            ) from e
        return TensorBuffer.of(*out).spec()

    def invoke(self, tensors: ArrayTuple) -> ArrayTuple:
        assert self._entry is not None
        out = self._entry.fn(tensors)
        if not isinstance(out, tuple):
            out = (out,)
        return out

    def reload(self, model: Any) -> None:
        self.open({"model": model})
