"""XLA backend — the one first-class NN engine (replaces the reference's
vendor subplugin zoo, SURVEY.md §2.3; the BASELINE.json north star).

A model is a jax-traceable callable ``fn(params, *inputs) -> outputs``
plus a params pytree. Sources of models:

- the in-repo model zoo (``model=zoo://mobilenet_v2``) — models/zoo.py
- a python path (``model=pkg.module:build``) whose callable returns a
  `ModelBundle` or is itself the traced function
- a `ModelBundle` passed programmatically to the element

TPU-first properties:
- **Fusion**: the tensor_transform chains adjacent to the filter are
  absorbed via `fuse()` and traced into the *same* jit computation, so
  normalization/typecast/argmax run on-device fused around the matmuls —
  zero extra HBM round-trips (north star: "fold tensor_transform into the
  same XLA computation").
- **Negotiation via tracing**: output specs come from `jax.eval_shape`
  (no device work at build time).
- **Async dispatch**: `invoke` returns device arrays without blocking; the
  scheduler's queues overlap host work with device execution. The D2H
  sync happens once, at a sink/decoder (TensorBuffer.to_host) — the
  anti-pattern this avoids is the reference's per-frame
  cudaDeviceSynchronize (tensor_filter_tensorrt.cc:239).
"""

from __future__ import annotations

import importlib
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from nnstreamer_tpu.backends.base import (
    ArrayTuple,
    ElementwiseFn,
    FilterBackend,
    register_backend,
)
from nnstreamer_tpu.core.errors import BackendError, SegmentStageError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.runtime import devprof
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

log = get_logger("backend.xla")


@dataclass
class ModelBundle:
    """A loadable model: traced function + params + optional fixed specs."""

    fn: Callable[..., Any]            # fn(params, *inputs) -> output(s)
    params: Any = None
    in_spec: Optional[TensorsSpec] = None
    out_spec: Optional[TensorsSpec] = None
    name: str = ""
    #: optional host-side input stage applied before H2D staging — for
    #: inputs that are bytes-parsing, not tensor math (e.g. the GraphDef
    #: DecodeWav entry: RIFF header decode happens here, PCM samples
    #: enter the XLA program)
    host_pre: Optional[Callable[[tuple], tuple]] = None


@dataclass
class _SharedEntry:
    """One device-resident model shared across filter instances
    (shared-tensor-filter-key analog, tensor_filter_common.c:2911-3046).
    On TPU the point is HBM dedup: N filters on one model hold ONE copy
    of the device params; reload swaps the entry for all holders."""

    bundle: ModelBundle
    device_params: Any
    device: Any = None
    model_ref: Optional[str] = None   # str model= of the first holder
    holders: int = 0
    version: int = 0


_shared_models: Dict[str, _SharedEntry] = {}
_shared_lock = threading.Lock()


@dataclass
class _VState:
    """One store version resident in this backend: bundle + device
    params. Compiled buckets live in `_dyn_jits` under ("v", version)
    namespaced keys, so retiring a version is a key sweep."""

    version: int
    bundle: ModelBundle
    device_params: Any = None


def _next_pow2(n: int, floor: int = 1) -> int:
    v = max(n, floor)
    return 1 << (v - 1).bit_length()


def _to_tuple(x) -> Tuple:
    if isinstance(x, tuple):
        return x
    if isinstance(x, list):
        return tuple(x)
    return (x,)


def _spec_from_shapes(shapes) -> TensorsSpec:
    infos = tuple(
        TensorInfo(shape=tuple(s.shape), dtype=DType.from_np(s.dtype))
        for s in shapes
    )
    return TensorsSpec(tensors=infos)


@register_backend("xla")
class XLABackend(FilterBackend):
    def __init__(self):
        self._bundle: Optional[ModelBundle] = None
        self._pre: Optional[ElementwiseFn] = None
        self._post: Optional[ElementwiseFn] = None
        self._post_aux = None
        self._jitted = None
        self._device = None
        self._device_params = None
        self._in_spec: Optional[TensorsSpec] = None
        self._out_spec: Optional[TensorsSpec] = None
        self._loader_opts: Dict[str, Any] = {}
        self._shared: Optional[_SharedEntry] = None
        self._shared_key: Optional[str] = None
        self._jitted_version = -1
        # flexible-shape invoke: bounded cache of per-bucket compilations
        self._dyn_jits: "OrderedDict[tuple, Any]" = OrderedDict()
        self._dyn_cache_max = 16
        self._batch_ok: Dict[tuple, bool] = {}   # batchability verdicts
        self._dynamic_spatial = False
        self.compile_count = 0   # traces, observable for bucketing tests
        # bucket-cache behavior (_bucket_jit), surfaced in stats() via
        # tensor_filter.extra_stats and in backend trace spans
        self.cache_hits = 0
        self.cache_misses = 0
        # host→device staging accounting (zero-redundant-staging
        # dispatch): inputs already committed to the target device skip
        # device_put entirely — a D2H round-trip saved per elision
        self.staging_transfers = 0
        self.staging_elided = 0
        # bucketed invokes that ran a donating jit (freshly-staged
        # inputs only: the backend owns those buffers, so XLA may reuse
        # their HBM for outputs instead of allocating more)
        self.donated_invokes = 0
        self._donate = False         # resolved in open() (platform gate)
        # compiled multi-step windows (invoke_window): K frames through
        # one lax.scan dispatch — the scheduler-bypass hot path
        self.window_invokes = 0
        self.window_frames = 0
        # window-scan traces are counted apart from compile_count: the
        # latter means "per-frame bucket traces" to bucketing tests and
        # the one-dispatch segment invariants, and a ("win", k) bucket
        # is a second executable over the SAME per-frame bucket, not a
        # new per-frame bucket
        self.window_compile_count = 0
        # observed micro-batch occupancy, {n: invokes} — a first-class
        # sensor (tensor_filter.extra_stats → autotuner bucket
        # refinement) instead of making callers infer occupancy from
        # bucket cache keys
        self.batch_size_hist: Dict[int, int] = {}
        # last successfully bucketed per-frame signature
        # ((frame_shape, dtype), ...) — what stage_bucket() rebuilds a
        # different pow2 bucket from
        self._last_dynb: Optional[tuple] = None
        # cache namespace generation for non-store models: bumped on any
        # model change (reload / shared-entry adoption) and prefixed
        # into every _dyn_jits/_batch_ok key, so a stale bucket compiled
        # against old weights can never be served by key collision
        self._gen = 0
        # per-device cache-namespace suffix: ("dev", id) when the
        # accelerator prop pinned an explicit device ordinal (replica /
        # segment placement, serving/placement.py), else () — folded
        # into _ns() so replicas of one model can never trade compiles
        # across chips by key collision
        self._dev_ns: tuple = ()
        # store:// serving state (serving/store.py): versions are cache-
        # namespaced by version number instead of _gen, adoption happens
        # at invoke boundaries (single worker thread per element ⇒ an
        # invoke sees exactly one version snapshot, never a torn mix)
        self._store_entry = None                 # serving.store._Entry
        self._store_ref = None                   # serving.store.StoreRef
        self._pinned_version: Optional[int] = None
        self._vstates: Dict[int, "_VState"] = {}
        self._adopted_version: Optional[int] = None
        self.adopted_epoch = -1                  # store barrier reads this
        self._canary: Optional[Tuple[int, float]] = None
        self._canary_rng = None
        self._staged: Dict[int, dict] = {}       # version → prewarmed state
        self._served: "OrderedDict[tuple, bool]" = OrderedDict()
        self.swap_count = 0                      # epoch adoptions observed
        # composed device segment (graph/optimize.py fuse_segments):
        # downstream member filters' models trace into THIS backend's
        # jits as (mid_chain_fn | None, member XLABackend, member name)
        # stages — one dispatch runs the whole run. _seg_ps/_seg_sig are
        # the per-invoke member-params snapshot + cache-key signature
        # (refreshed by _seg_begin at every invoke boundary, which is
        # where member store epochs are adopted).
        self._segment: List[tuple] = []
        self._seg_ps: tuple = ()
        self._seg_sig: tuple = ()

    # -- open / model resolution ------------------------------------------
    def open(self, props: Dict[str, Any]) -> None:
        import jax

        model = props.get("model")
        if model is None:
            raise BackendError(
                "framework=xla requires model=<zoo://name | pkg.module:attr "
                "| /path/model.{tflite,npz} | ModelBundle | jax callable>"
            )
        from nnstreamer_tpu.modelio import parse_loader_opts

        opts = parse_loader_opts(props.get("custom") or "")
        self._dynamic_spatial = bool(opts.pop("dynamic_spatial", False))
        # reference-style dedicated props override the custom= string
        for prop, key in (("inputname", "input_names"),
                          ("outputname", "output_names")):
            v = props.get(prop) or ""
            if v:
                opts[key] = [s for s in v.split(",") if s]
        self._loader_opts = opts
        accel = props.get("accelerator") or ""
        self._device = self._pick_device(accel)
        if accel.partition(":")[2]:
            # explicitly-indexed placement (dev i of N): namespace every
            # cache key by the device so no compile travels between chips
            self._dev_ns = ("dev", int(getattr(self._device, "id", 0)))
        # input-buffer donation for bucketed jits ([runtime]
        # donate_inputs): skipped on CPU, where XLA ignores the aliasing
        # hint (host buffers) and would warn per compile
        from nnstreamer_tpu.core.config import get_config

        self._donate = (
            get_config().get_bool("runtime", "donate_inputs", True)
            and getattr(self._device, "platform", "cpu") != "cpu")
        if isinstance(model, str) and model.startswith("store://"):
            self._open_store(model, props)
            return
        key = props.get("shared_tensor_filter_key") or None
        self._shared_key = key
        if key is not None:
            with _shared_lock:
                entry = _shared_models.get(key)
                if entry is None:
                    bundle = self._resolve(model)
                    entry = _SharedEntry(
                        bundle=bundle,
                        device_params=jax.device_put(bundle.params,
                                                     self._device)
                        if bundle.params is not None else None,
                        device=self._device,
                        model_ref=model if isinstance(model, str) else None)
                    _shared_models[key] = entry
                else:
                    # a shared entry is ONE device-resident model: every
                    # holder must agree on what and where it is
                    if entry.device != self._device:
                        raise BackendError(
                            f"shared-tensor-filter-key {key!r} is held on "
                            f"{entry.device} but this filter asked for "
                            f"{self._device}; use a different key per "
                            f"device")
                    if (isinstance(model, str) and entry.model_ref is not None
                            and model != entry.model_ref):
                        raise BackendError(
                            f"shared-tensor-filter-key {key!r} already "
                            f"holds model {entry.model_ref!r}; this filter "
                            f"asked for {model!r} (same key ⇒ same model)")
                entry.holders += 1
                self._shared = entry
                self._bundle = entry.bundle
                self._device_params = entry.device_params
            log.info("opened shared model key=%s holders=%d on %s", key,
                     self._shared.holders, self._device)
            return
        self._bundle = self._resolve(model)
        if self._bundle.params is not None:
            self._device_params = jax.device_put(self._bundle.params, self._device)
        else:
            self._device_params = None
        log.info("opened model %s on %s", self._bundle.name or model, self._device)

    def _open_store(self, model: str, props: Dict[str, Any]) -> None:
        """Bind this backend to a served model in the process-wide
        ModelStore (serving/store.py): resolve the baseline version,
        attach as a swap handle, and set up canary routing when the ref
        carries a split (``store://name@2:0.05``)."""
        import random as _random

        import jax

        if props.get("shared_tensor_filter_key"):
            raise BackendError(
                "store:// models are already process-shared through the "
                "model store; shared-tensor-filter-key cannot combine "
                "with a store reference — drop the key")
        from nnstreamer_tpu.serving.compile_cache import (
            maybe_enable_compile_cache,
        )
        from nnstreamer_tpu.serving.store import get_store, parse_store_ref

        maybe_enable_compile_cache()
        ref = parse_store_ref(model)
        entry = get_store().entry(ref.name)
        self._store_entry = entry
        self._store_ref = ref
        # room for two live versions' bucket sets + a staged prewarm
        self._dyn_cache_max = max(self._dyn_cache_max, 32)
        cur, epoch = entry.state
        if ref.version is not None:
            self._pinned_version = entry.resolve_version(ref.version)
            base = self._pinned_version
        else:
            base = entry.resolve_version(None)
        self._adopted_version = base
        self.adopted_epoch = epoch
        self._vstates[base] = self._make_vstate(base, entry.bundle(base))
        self._bundle = self._vstates[base].bundle
        self._device_params = self._vstates[base].device_params
        if ref.canary_version is not None:
            cv = entry.resolve_version(ref.canary_version)
            if cv == base:
                raise BackendError(
                    f"canary reference {model!r} routes to the baseline "
                    f"version @{base} itself; pick a different version "
                    f"to canary")
            self._canary = (cv, ref.canary_ratio)
            self._canary_rng = _random.Random(
                int(props.get("canary_seed") or 0))
            self._vstates[cv] = self._make_vstate(cv, entry.bundle(cv))
        entry.attach(self)
        log.info("opened store model %s@%d epoch=%d%s on %s", ref.name,
                 base, epoch,
                 f" canary=@{self._canary[0]}:{self._canary[1]}"
                 if self._canary else "", self._device)

    @property
    def tracks_store_epoch(self) -> bool:
        """True when this handle follows ``current`` (un-pinned), i.e.
        participates in the swap barrier."""
        return self._store_entry is not None and self._pinned_version is None

    def _make_vstate(self, version: int, bundle: ModelBundle) -> _VState:
        import jax

        return _VState(
            version=version, bundle=bundle,
            device_params=jax.device_put(bundle.params, self._device)
            if bundle.params is not None else None)

    def _resolve(self, model) -> ModelBundle:
        if isinstance(model, ModelBundle):
            return model
        if isinstance(model, str) and model.startswith("store://"):
            raise BackendError(
                f"{model!r} resolves through the ModelStore at open(); "
                f"store refs cannot nest as version sources — register "
                f"the underlying model instead")
        if callable(model):
            return ModelBundle(
                fn=lambda params, *xs: model(*xs),
                params=None,
                in_spec=getattr(model, "in_spec", None),
                out_spec=getattr(model, "out_spec", None),
                name=getattr(model, "__name__", "callable"),
            )
        if isinstance(model, str) and model.startswith("zoo://"):
            try:
                from nnstreamer_tpu.models.zoo import build_model
            except ImportError as e:
                raise BackendError(f"model zoo unavailable: {e}") from e
            return build_model(model[len("zoo://"):])
        if isinstance(model, str):
            from nnstreamer_tpu import modelio

            ext = model.rsplit(".", 1)[-1].lower() if "." in model else ""
            if ext in modelio.MODEL_EXTENSIONS:
                return modelio.load_model_file(model, **self._loader_opts)
        if isinstance(model, str) and ":" in model:
            mod_name, _, attr = model.partition(":")
            try:
                obj = getattr(importlib.import_module(mod_name), attr)
            except (ImportError, AttributeError) as e:
                raise BackendError(f"cannot load model {model!r}: {e}") from e
            built = obj() if not isinstance(obj, ModelBundle) else obj
            if isinstance(built, ModelBundle):
                return built
            return self._resolve(built)
        raise BackendError(
            f"unrecognized model reference {model!r} for framework=xla; "
            f"expected zoo://<name>, pkg.module:attr, a ModelBundle, or a "
            f"jax callable"
        )

    def _pick_device(self, accelerator: str):
        import jax

        devices = jax.devices()
        if accelerator:
            # "tpu:2" / "tpu" / "cpu" (accl_hw-string analog, hw_accel.c)
            kind, _, idx = accelerator.partition(":")
            matching = [d for d in devices if d.platform.lower() == kind.lower()]
            if not matching:
                raise BackendError(
                    f"accelerator={accelerator!r} but no {kind!r} device is "
                    f"visible; available: "
                    f"{sorted({d.platform for d in devices})}"
                )
            return matching[int(idx)] if idx else matching[0]
        return devices[0]

    def close(self) -> None:
        self._jitted = None
        self._device_params = None
        self._dyn_jits.clear()
        self._batch_ok.clear()
        if self._store_entry is not None:
            # detach the swap handle but keep the entry reference:
            # version_stats() stays readable for post-stop reports
            self._store_entry.detach(self)
            self._vstates.clear()
            self._staged.clear()
        if self._shared is not None:
            with _shared_lock:
                self._shared.holders -= 1
                if self._shared.holders <= 0:
                    _shared_models.pop(self._shared_key, None)
            self._shared = None

    # -- info / negotiation ------------------------------------------------
    def get_model_info(self):
        assert self._bundle is not None, "open() not called"
        return self._bundle.in_spec, self._bundle.out_spec

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        """Shape-infer the model's own output via jax.eval_shape.

        `in_spec` is what the *model* sees (the element already applied
        fused pre-chain spec transfer); fused chains affect invoke()
        only, so eval_shape runs on the bare bundle fn.
        """
        import jax

        assert self._bundle is not None
        self._in_spec = in_spec
        bundle = self._bundle
        bare = lambda params, *xs: _to_tuple(bundle.fn(params, *xs))
        args = [
            jax.ShapeDtypeStruct(t.shape, t.dtype.np_dtype)
            for t in in_spec.tensors
        ]
        try:
            out = jax.eval_shape(bare, self._abstract_params(), *args)
        except Exception as e:
            raise BackendError(
                f"model {self._bundle.name!r} does not accept input "
                f"{in_spec}: {e}"
            ) from e
        self._out_spec = _spec_from_shapes(_to_tuple(out))
        return self._out_spec

    def _abstract_params(self):
        return self._abstract_of(self._device_params)

    @staticmethod
    def _abstract_of(params):
        import jax

        if params is None:
            return None
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params
        )

    # -- fusion ------------------------------------------------------------
    def fuse(self, pre: Optional[ElementwiseFn], post: Optional[ElementwiseFn]) -> bool:
        self._pre = pre
        self._post = post
        # aux constants the post chain needs (e.g. SSD anchors from a
        # fused device decoder). They ride as a jit ARGUMENT, never as a
        # closure constant: a large embedded literal degrades the whole
        # process on tunneled backends (measured 0.8ms → 18ms per frame
        # for every program compiled after the literal-carrying one)
        import jax

        aux = getattr(post, "aux_params", None)
        self._post_aux = None if aux is None else jax.tree_util.tree_map(
            lambda a: jax.device_put(a, self._device), aux)
        self._jitted = None  # recompile with the fused graph
        return True

    def compose_segment(self, stages) -> bool:
        """Accept a device segment (graph/optimize.py fuse_segments):
        `stages` is [(mid_chain_fn | None, member_backend, member_name)]
        in dataflow order. Accepting means every member's model traces
        into this backend's jits between the head model and the fused
        post chain — the whole run becomes ONE dispatch, intermediates
        never leave HBM. Declines (→ host-side member invokes in the
        element, bit-identical) when a member can't ride one trace:
        non-XLA backend, different device, a host-side input stage, or
        per-invoke canary routing (the route changes within a buffer
        stream, which a single trace can't express)."""
        for mid, mb, mname in stages:
            if not isinstance(mb, XLABackend):
                log.info("segment declined: member %s is not XLA", mname)
                return False
            if mb._device != self._device:
                log.info("segment declined: member %s is on %s, head on "
                         "%s", mname, mb._device, self._device)
                return False
            if mb._canary is not None:
                log.info("segment declined: member %s has canary "
                         "routing", mname)
                return False
            if any(vs.bundle.host_pre is not None
                   for vs in mb._vstates.values()) or (
                    mb._bundle is not None
                    and mb._bundle.host_pre is not None):
                log.info("segment declined: member %s model has a "
                         "host-side input stage", mname)
                return False
        self._segment = list(stages)
        self._jitted = None
        self._seg_begin()          # initial member params/sig snapshot
        return True

    def _seg_begin(self) -> None:
        """Segment-invoke boundary: adopt flipped member store epochs,
        snapshot member device params (the jit's third packed argument)
        and the cache-key signature. A signature change — any member
        swapped versions — retires the single-path jit; bucketed keys
        carry the signature, so stale compiles simply stop matching."""
        if not self._segment:
            return
        ps: List[Any] = []
        sig: List[tuple] = []
        for _, mb, _ in self._segment:
            if mb._store_entry is not None:
                ver = mb._pick_version()
                ps.append(mb._vstates[ver].device_params)
                sig.append(mb._ns(ver))
            else:
                ps.append(mb._current_params())
                sig.append(mb._ns())
        sig_t = tuple(sig)
        if sig_t != self._seg_sig:
            self._jitted = None
            self._seg_sig = sig_t
        self._seg_ps = tuple(ps)

    def _seg_suffix(self) -> tuple:
        """Cache-key suffix naming every member's version/generation —
        appended (at the END, so _adopt's leading-("v",…) sweeps keep
        working) to every bucketed key and batchability verdict."""
        return (("seg",) + self._seg_sig,) if self._segment else ()

    def _with_seg(self, packed: tuple) -> tuple:
        """Extend a manually-built (params, aux) packed with the member
        params snapshot (prewarm/warm-start paths)."""
        return packed + ((self._seg_ps,) if self._segment else ())

    def _full_fn(self, count: bool = True, bundle: ModelBundle = None):
        bundle = bundle or self._bundle
        pre, post = self._pre, self._post
        seg = list(self._segment)

        def full(packed, *xs):
            params, aux = packed[0], packed[1]
            # member params ride as a jit ARGUMENT (same rule as
            # _post_aux: embedded literals poison downstream compiles);
            # eval_shape callers pass the 2-tuple form and fall back to
            # the concrete member params, which eval_shape tolerates
            segp = packed[2] if len(packed) > 2 else None
            if count:
                # trace-time side effect: counts compilations, not invokes
                self.compile_count += 1
            if pre is not None:
                xs = pre(xs)
            out = _to_tuple(bundle.fn(params, *xs))
            for i, (mid, mb, mname) in enumerate(seg):
                try:
                    if mid is not None:
                        out = _to_tuple(mid(out))
                    mp = segp[i] if segp is not None else mb._device_params
                    out = _to_tuple(mb._bundle.fn(mp, *out))
                except SegmentStageError:
                    raise
                except Exception as e:
                    # trace-time failure inside a member stage: name the
                    # member element, not the surviving head
                    raise SegmentStageError(mname, e) from e
            if post is not None:
                out = post(out) if aux is None else post(out, aux)
            return out

        return full

    def _packed_params(self):
        """(model params, post-chain aux[, member params]) — the jit's
        first argument. Callers must have run _seg_begin this invoke."""
        base = (self._current_params(), getattr(self, "_post_aux", None))
        return base + ((self._seg_ps,) if self._segment else ())

    def _current_params(self):
        """Device params, following shared-entry swaps (hot reload)."""
        if self._shared is not None:
            if self._shared.version != self._jitted_version:
                # a holder reloaded the shared model: recompile against
                # the (possibly different) new bundle fn
                self._bundle = self._shared.bundle
                self._device_params = self._shared.device_params
                self._jitted = None
                self._gen += 1           # new cache namespace
                self._dyn_jits.clear()
                self._batch_ok.clear()
                self._jitted_version = self._shared.version
            return self._shared.device_params
        return self._device_params

    # -- store serving (serving/store.py handle protocol) ------------------
    def _ns(self, version: Optional[int] = None) -> tuple:
        """Cache-namespace prefix: every _dyn_jits/_batch_ok key starts
        with this, so no model change can serve a stale compile by key
        collision — ("v", version) for store models (retired by version
        sweep), ("g", generation) otherwise (cleared + bumped on
        reload/shared adoption). Explicitly-placed backends (replica /
        segment stages) append ("dev", id) so the same model compiled
        for two chips can never collide — _adopt's sweeps read k[0][:2]
        and keep working."""
        if self._store_entry is not None:
            return ("v", version if version is not None
                    else self._adopted_version) + self._dev_ns
        return ("g", self._gen) + self._dev_ns

    def _pick_version(self) -> int:
        """Adopt a flipped epoch, then route this invoke: the pinned
        version (immune to swaps), the canary version at its seeded
        ratio, or the tracked current."""
        e = self._store_entry
        if self._pinned_version is not None:
            return self._pinned_version
        cur, epoch = e.state             # one read = consistent pair
        if epoch != self.adopted_epoch:
            self._adopt(cur, epoch)
        if (self._canary is not None
                and self._canary_rng.random() < self._canary[1]):
            return self._canary[0]
        return self._adopted_version

    def _adopt(self, cur: int, epoch: int) -> None:
        """Flip this backend to the new current version (runs on the
        element's single worker thread, at an invoke boundary): install
        the pre-warmed state staged by `prewarm_version`, retire the
        outgoing version's compiled buckets, and keep self._bundle /
        _device_params pointing at the adopted version so negotiation-
        era paths (eval_shape, flexible invokes) follow along."""
        old = self._adopted_version
        staged = self._staged.pop(cur, None)
        if cur not in self._vstates:
            if staged is not None:
                self._vstates[cur] = staged["vstate"]
            else:                        # un-prewarmed swap: resolve now
                self._vstates[cur] = self._make_vstate(
                    cur, self._store_entry.bundle(cur))
        if staged is not None:
            for basekey, jitted in staged["jits"].items():
                self._insert_jit(
                    (self._ns(cur),) + basekey + self._seg_suffix(), jitted)
        live = {cur}
        if self._canary is not None:
            live.add(self._canary[0])
        if self._pinned_version is not None:
            live.add(self._pinned_version)
        for v in [v for v in self._vstates if v not in live]:
            del self._vstates[v]         # drops old device params
        for cache in (self._dyn_jits, self._batch_ok):
            for k in [k for k in cache
                      if k[0][0] == "v" and k[0][1] not in live]:
                del cache[k]
        self._jitted = None
        vs = self._vstates[cur]
        self._bundle, self._device_params = vs.bundle, vs.device_params
        self._adopted_version, self.adopted_epoch = cur, epoch
        self.swap_count += 1
        self.tracer.record_swap(
            self.trace_name or "xla", time.perf_counter(),
            model=self._store_entry.name, from_version=old,
            to_version=cur, epoch=epoch, prewarmed=staged is not None)
        log.info("adopted %s@%d epoch=%d (from @%s, prewarmed=%s)",
                 self._store_entry.name, cur, epoch, old,
                 staged is not None)

    def prewarm_version(self, version: int, bundle: ModelBundle) -> int:
        """Compile the incoming version against every bucket this
        backend has served, OFF the hot path (called from the
        swap-controller thread, before the epoch flips). The compiled
        jits are staged — the worker installs them at adoption, so the
        post-flip hot path only ever takes cache hits. AOT lower().
        compile() does not populate jit's call cache, so the warmup
        actually CALLS each jit on zero inputs and blocks. A version
        that rejects a served bucket raises here, aborting the swap
        before anything flips. Returns the bucket count compiled."""
        import jax
        import numpy as np_

        from nnstreamer_tpu.runtime.sync import device_sync

        vs = self._make_vstate(version, bundle)
        # NOTE: runs on the swap-controller thread — must NOT call
        # _seg_begin() (worker-owned state); _with_seg reads the last
        # snapshot, which is fine because member params travel as jit
        # ARGUMENTS (the compiled jit serves any same-shaped seg params)
        packed = self._with_seg(
            (vs.device_params, getattr(self, "_post_aux", None)))
        jits: Dict[tuple, Any] = {}
        compiled = 0
        for basekey in list(self._served):
            specs = self._bucket_array_specs(basekey)
            if specs is None:
                continue             # flexible seq/bat: recompile lazily
            if (self._ns(version),) + basekey + self._seg_suffix() \
                    in self._dyn_jits:
                continue             # already live (e.g. was the canary)
            jitted = jax.jit(self._full_fn(bundle=bundle))
            args = tuple(
                jax.device_put(np_.zeros(s, dtype=np_.dtype(d)),
                               self._device) for s, d in specs)
            prof = devprof.get()
            if prof.enabled:
                prof.note_dispatch(self._prof_label(),
                                   devprof.bucket_label(basekey))
            t0 = time.perf_counter()
            try:
                out = _to_tuple(jitted(packed, *args))
                device_sync(out, self.tracer, self.trace_name)
            except Exception as e:
                raise BackendError(
                    f"pre-warm of {self._store_entry.name}@{version} "
                    f"failed on served bucket {basekey[0]} "
                    f"{[s for s, _ in specs]}: {e} — swap aborted before "
                    f"the epoch flip; the serving version is unchanged"
                ) from e
            self._prof_capture(devprof.bucket_label(basekey), jitted,
                               (packed,) + args,
                               time.perf_counter() - t0)
            jits[basekey] = jitted
            compiled += 1
        self._staged[version] = {"vstate": vs, "jits": jits}
        return compiled

    def warm_start(self) -> int:
        """Replay the persistent manifest's bucket set for the bound
        version (called by tensor_filter.start(), off the hot path):
        against a warm XLA disk cache these compile as fast loads, so a
        restarted process serves its first real buffer from cache."""
        if self._store_entry is None:
            return 0
        import jax
        import numpy as np_

        from nnstreamer_tpu.runtime.sync import device_sync
        from nnstreamer_tpu.serving.compile_cache import manifest_buckets

        self._seg_begin()        # single-threaded (tensor_filter.start)
        ver = self._adopted_version
        vs = self._vstates.get(ver)
        if vs is None:
            return 0
        packed = self._with_seg(
            (vs.device_params, getattr(self, "_post_aux", None)))
        compiled = 0
        for basekey in manifest_buckets(self._store_entry.name, ver):
            key = (self._ns(ver),) + basekey + self._seg_suffix()
            if key in self._dyn_jits:
                continue
            specs = self._bucket_array_specs(basekey)
            if specs is None:
                continue
            prof = devprof.get()
            t0 = time.perf_counter()
            try:
                jitted = jax.jit(self._full_fn(bundle=vs.bundle))
                args = tuple(
                    jax.device_put(np_.zeros(s, dtype=np_.dtype(d)),
                                   self._device) for s, d in specs)
                if prof.enabled:
                    prof.note_dispatch(self._prof_label(),
                                       devprof.bucket_label(basekey))
                device_sync(_to_tuple(jitted(packed, *args)),
                            self.tracer, self.trace_name)
            except Exception as e:
                # stale manifest (model changed shape since it was
                # written): warm start is an optimization, never a gate
                log.warning("warm-start bucket %s skipped: %s",
                            basekey[:2], e)
                continue
            self._prof_capture(devprof.bucket_label(basekey), jitted,
                               (packed,) + args,
                               time.perf_counter() - t0)
            self._insert_jit(key, jitted)
            self._served.setdefault(basekey, True)
            compiled += 1
        if compiled:
            log.info("warm start: %d manifest buckets compiled for %s@%d",
                     compiled, self._store_entry.name, ver)
        return compiled

    @staticmethod
    def _bucket_array_specs(basekey: tuple):
        """(shape, dtype) list to materialize a recorded bucket, or None
        for kinds that are not replayed (flexible seq/bat)."""
        kind = basekey[0]
        if kind == "fix":
            return list(basekey[1:])
        if kind == "dynb":
            return list(basekey[2:])
        return None

    def _note_bucket(self, version: int, basekey: tuple) -> None:
        if basekey not in self._served:
            self._served[basekey] = True
            self._store_entry.note_bucket(version, basekey)

    def version_stats(self) -> Dict[int, dict]:
        """Per-version invoke/error/p95 counters of the bound store
        entry (process-wide across handles), for extra_stats."""
        if self._store_entry is None:
            return {}
        return self._store_entry.stats_dict()

    def _record_invoke(self, version: int, t0: float,
                       error: bool = False) -> float:
        dt = time.perf_counter() - t0
        self._store_entry.record(version, dt, error=error)
        return dt

    # -- device performance plane (runtime/devprof.py) ---------------------
    def _prof_label(self) -> str:
        """Stable filter label for devprof keys: the element's trace
        name, else the store model name, else the bundle name."""
        if self.trace_name:
            return self.trace_name
        if self._store_entry is not None:
            return self._store_entry.name
        if self._bundle is not None and self._bundle.name:
            return self._bundle.name
        return "xla"

    def _prof_capture(self, bucket: str, jitted, args: tuple,
                      seconds: float) -> None:
        """Compile-event hook: register this backend for HBM
        attribution and hand the jitted program + concrete args to the
        profiler's cost-model capture (a re-lower, compile misses
        only — never the steady-state hot path)."""
        prof = devprof.get()
        if not prof.enabled:
            return
        label = self._prof_label()
        prof.attach_model(label, self)
        prof.capture_cost(label, bucket, jitted, args, seconds=seconds)

    def _stage(self, arrs) -> Tuple[ArrayTuple, bool]:
        """Move inputs to the target device, skipping `device_put` for
        arrays **already committed there** (a committed jax.Array whose
        device set is exactly {target} is resident by definition — e.g.
        a device-side decoder's output feeding a second filter). Returns
        (staged, all_fresh): all_fresh is True only when every buffer
        was host-side, i.e. every device buffer in `staged` was created
        right here and is exclusively ours — the precondition for
        handing them to a donating jit. Elided arrays are upstream-owned
        and must never be donated."""
        import jax

        dev = self._device
        staged = []
        fresh = True
        for a in arrs:
            if getattr(a, "committed", False) and a.devices() == {dev}:
                self.staging_elided += 1
                staged.append(a)
                fresh = False
            else:
                self.staging_transfers += 1
                staged.append(jax.device_put(a, dev))
        return tuple(staged), fresh

    def _invoke_store(self, tensors: ArrayTuple) -> ArrayTuple:
        """Fixed-shape invoke through the store routing point: pick the
        version (adopting a flipped epoch first), then run its bucketed
        jit. Keys carry shape+dtype so the bucket is pre-warmable and
        manifest-replayable."""
        import jax
        import numpy as np_

        ver = self._pick_version()
        vs = self._vstates[ver]
        if vs.bundle.host_pre is not None:
            tensors = tuple(vs.bundle.host_pre(tuple(tensors)))
        arrs = tuple(t if hasattr(t, "shape") else np_.asarray(t)
                     for t in tensors)
        basekey = ("fix",) + tuple(
            (tuple(a.shape), str(a.dtype)) for a in arrs)
        self._note_bucket(ver, basekey)
        packed = self._with_seg(
            (vs.device_params, getattr(self, "_post_aux", None)))
        hits0 = self.cache_hits
        jitted = self._bucket_jit(
            (self._ns(ver),) + basekey + self._seg_suffix(),
            make=lambda: jax.jit(self._full_fn(bundle=vs.bundle)))
        staged, _ = self._stage(arrs)
        prof = devprof.get()
        blabel = devprof.bucket_label(basekey)
        if prof.enabled:
            prof.note_dispatch(self._prof_label(), blabel)
        t0 = time.perf_counter()
        try:
            out = _to_tuple(jitted(packed, *staged))
        except Exception:
            self._record_invoke(ver, t0, error=True)
            raise
        dt = self._record_invoke(ver, t0)
        if prof.enabled and self.cache_hits == hits0:
            self._prof_capture(blabel, jitted, (packed,) + staged, dt)
        tr = self.tracer
        if tr.active:
            tr.backend_span(self.trace_name or "xla", "invoke", t0,
                            t0 + dt, version=ver,
                            compile="cached" if self.cache_hits > hits0
                            else "fresh")
        return out

    # -- hot loop ----------------------------------------------------------
    def invoke(self, tensors: ArrayTuple) -> ArrayTuple:
        import jax

        self._seg_begin()
        if self._store_entry is not None:
            return self._invoke_store(tensors)
        if self._bundle.host_pre is not None:
            tensors = tuple(self._bundle.host_pre(tuple(tensors)))
        params = self._packed_params()
        fresh = self._jitted is None
        if fresh:
            self._jitted = jax.jit(self._full_fn())
        # explicit async H2D staging before dispatch: on tunneled/remote
        # devices this overlaps the transfer with the previous frame's
        # compute (measured ~3.6x e2e FPS vs jit-internal staging);
        # already-device-committed inputs skip the put entirely
        staged, _ = self._stage(tensors)
        tr = self.tracer
        prof = devprof.get()
        if prof.enabled:
            prof.note_dispatch(self._prof_label(), "static")
        if tr.active or (prof.enabled and fresh):
            t0 = time.perf_counter()
            out = self._jitted(params, *staged)
            t1 = time.perf_counter()
            if tr.active:
                tr.backend_span(self.trace_name or "xla", "invoke", t0,
                                t1, compile="fresh" if fresh else "cached")
            if prof.enabled and fresh:
                self._prof_capture("static", self._jitted,
                                   (params,) + staged, t1 - t0)
        else:
            out = self._jitted(params, *staged)
        return _to_tuple(out)

    def invoke_window(self, frames: List[ArrayTuple]) -> List[ArrayTuple]:
        """Compiled multi-step window: K same-signature frames through
        ONE ``jax.lax.scan`` whose body is exactly the per-frame full
        function — one Python dispatch, one device program, K frames.
        This is the scheduler-bypass hot path: the steady-state loop
        (runtime/compiled_loop.py) collects the window, this runs it.

        Guarantees the scheduler's bail matrix leans on:

        - the scan body IS `_full_fn`, so outputs are bit-identical to
          K per-frame invokes of the same bucket;
        - version pick / epoch adoption happens ONCE at the window
          boundary (the scheduler bails to per-frame when it sees a
          pending swap, so adoption never lands mid-window);
        - store invoke accounting records K invokes of dt/K each —
          per-version counters reconcile exactly with per-frame mode.
        """
        import jax
        import numpy as np_

        k = len(frames)
        self._seg_begin()
        if self._store_entry is not None:
            ver = self._pick_version()
            vs = self._vstates[ver]
            bundle = vs.bundle
            ns = self._ns(ver)
            packed = self._with_seg(
                (vs.device_params, getattr(self, "_post_aux", None)))
        else:
            ver = None
            bundle = self._bundle
            ns = self._ns()
            self._current_params()     # follow shared-entry reloads
            packed = self._packed_params()
        if bundle.host_pre is not None:
            frames = [tuple(bundle.host_pre(tuple(f))) for f in frames]
        n_in = len(frames[0])
        stacked = tuple(
            np_.stack([np_.asarray(f[i]) for f in frames], axis=0)
            for i in range(n_in))
        basekey = ("win", k) + tuple(
            (tuple(a.shape[1:]), str(a.dtype)) for a in stacked)
        full = self._full_fn(count=False,
                             bundle=bundle if ver is not None else None)

        def make():
            self.window_compile_count += 1
            def window_fn(p, *xs):
                def body(carry, x):
                    return carry, _to_tuple(full(carry, *x))
                _, ys = jax.lax.scan(body, p, xs)
                return ys
            return jax.jit(window_fn)

        jitted = self._bucket_jit((ns,) + basekey + self._seg_suffix(),
                                  make=make)
        staged, _ = self._stage(stacked)
        prof = devprof.get()
        if prof.enabled:
            prof.note_dispatch(self._prof_label(), f"win:{k}")
        t0 = time.perf_counter()
        try:
            ys = _to_tuple(jitted(packed, *staged))
        except Exception:
            if ver is not None:
                self._record_invoke(ver, t0, error=True)
            raise
        dt = time.perf_counter() - t0
        if ver is not None:
            # K invokes of dt/K each: the per-version ledger counts the
            # same frames whether or not the window path served them
            for _ in range(k):
                self._store_entry.record(ver, dt / k)
        self.window_invokes += 1
        self.window_frames += k
        tr = self.tracer
        if tr.active:
            tr.backend_span(self.trace_name or "xla", "invoke_window",
                            t0, t0 + dt, frames=k,
                            **({"version": ver} if ver is not None
                               else {}))
        # unstack: row i of every output is frame i's output tuple
        return [tuple(y[i] for y in ys) for i in range(k)]

    def swap_pending(self) -> bool:
        """True when the bound store entry flipped epochs since this
        backend last adopted — the scheduler's compiled loop checks
        this at window entry and bails to per-frame mode so adoption
        happens at an ordinary invoke boundary (bail cause "swap")."""
        if self._store_entry is None or self._pinned_version is not None:
            return False
        _, epoch = self._store_entry.state
        return epoch != self.adopted_epoch

    # -- flexible shapes (invoke-dynamic analog) ---------------------------
    def invoke_flexible(self, regions: List[Any]) -> List[Any]:
        """Run the model over per-buffer variable-shape regions (e.g.
        tensor_crop output) with a **bounded, bucketed** compile policy
        (SURVEY §7 hard part d; reference invoke-dynamic,
        tensor_filter_common.c:899-1017):

        - same-shape regions are stacked along the batch axis, padded to
          the next power-of-two batch bucket, and run as ONE batched XLA
          call (MXU-friendly) — tried via eval_shape first, with a
          per-region fallback for models with a baked-in batch (tflite);
        - with custom=dynamic_spatial=true, spatial dims are additionally
          zero-padded up to power-of-two buckets (≥16) so arbitrary crop
          sizes reuse a small set of compilations — valid for
          shape-polymorphic models (global-pool classifiers);
        - compiled variants live in an LRU of {_dyn_cache_max} entries.
        """
        import jax
        import numpy as np_

        if self._store_entry is not None and self._pinned_version is None:
            # adopt a flipped epoch at the buffer boundary; flexible
            # invokes always run the adopted current (no canary split —
            # per-region shapes make the ratio bookkeeping meaningless)
            cur, epoch = self._store_entry.state
            if epoch != self.adopted_epoch:
                self._adopt(cur, epoch)
        if self._bundle.host_pre is not None:
            raise BackendError(
                f"model {self._bundle.name!r} has a host-side input "
                f"stage (host_pre) which the flexible-shape path does "
                f"not support; use the fixed-shape invoke path")
        params = self._packed_params()
        rs = [np_.asarray(r) if not hasattr(r, "shape") else r
              for r in regions]
        out: List[Any] = [None] * len(rs)
        groups: Dict[tuple, List[int]] = {}
        for i, r in enumerate(rs):
            groups.setdefault(tuple(r.shape), []).append(i)

        for shape, idxs in groups.items():
            arrs = [rs[i] for i in idxs]
            if self._dynamic_spatial and len(shape) >= 3:
                # pad (…, H, W, C) spatial dims up to pow2 buckets ≥16
                pads = []
                padded_shape = list(shape)
                for ax in (len(shape) - 3, len(shape) - 2):
                    b = _next_pow2(shape[ax], 16)
                    pads.append((ax, b - shape[ax]))
                    padded_shape[ax] = b
                if any(p for _, p in pads):
                    widths = [(0, 0)] * len(shape)
                    for ax, p in pads:
                        widths[ax] = (0, p)
                    arrs = [np_.pad(np_.asarray(a), widths) for a in arrs]
                    shape = tuple(padded_shape)
            n = len(arrs)
            batched, nb, stacked = self._batch_group(arrs, shape, n)
            if batched is None:       # model can't batch: sequential path
                jitted = self._bucket_jit((self._ns(), "seq") + shape)
                for i, a in zip(idxs, arrs):
                    out[i] = _to_tuple(jitted(params, a))[0]
                continue
            jitted = self._bucket_jit((self._ns(), "bat", nb) + shape)
            res = _to_tuple(jitted(params, batched))[0]
            for k, i in enumerate(idxs):
                out[i] = res[k:k + 1] if not stacked else res[k]
        return out

    def _batch_group(self, arrs, shape, n):
        """Stack same-shape regions into one batch-bucketed array, or
        (None, 0, False) if the model rejects a batched input shape. The
        batchability verdict is cached per (batched shape, dtype) so the
        hot loop never re-traces eval_shape for a recurring crop shape."""
        import jax
        import numpy as np_

        nb = _next_pow2(n)
        if shape[0] == 1:
            batched_shape = (nb,) + shape[1:]
            stacked = False
        else:
            batched_shape = (nb,) + shape
            stacked = True
        dt = np_.asarray(arrs[0]).dtype
        verdict_key = (self._ns(), batched_shape, str(dt))
        ok = self._batch_ok.get(verdict_key)
        if ok is None:
            try:
                args = [jax.ShapeDtypeStruct(batched_shape, dt)]
                jax.eval_shape(lambda p, x: self._full_fn(count=False)(p, x),
                               (self._abstract_params(),
                                getattr(self, "_post_aux", None)), *args)
                ok = True
            except Exception:
                ok = False
            self._batch_ok[verdict_key] = ok
        if not ok:
            return None, 0, False
        big = np_.concatenate if not stacked else np_.stack
        block = big([np_.asarray(a) for a in arrs], axis=0)
        if nb > block.shape[0]:
            fill = np_.repeat(block[-1:], nb - block.shape[0], axis=0)
            block = np_.concatenate([block, fill], axis=0)
        return block, nb, stacked

    # -- dynamic micro-batches (tensor_batch upstream) ---------------------
    def invoke_batched(self, tensors, n: int, keepdims=()):
        """One batched XLA call per micro-batch, padded to the next
        power-of-two occupancy bucket so ragged batch sizes (deadline
        flushes under varying load) reuse at most log2(max_batch)
        compilations instead of one per occupancy. Shares the LRU'd
        `_dyn_jits` cache and `compile_count` with invoke_flexible.

        Falls back to the per-frame base path when the model rejects a
        batched input shape (baked-in batch dim) or needs host_pre."""
        import jax
        import numpy as np_

        self._seg_begin()
        if self._store_entry is not None:
            return self._invoke_batched_store(tensors, n, keepdims)
        if self._bundle.host_pre is not None:
            # host_pre parses per-frame bytes; it has no batched form
            return super().invoke_batched(tensors, n, keepdims)
        nb = _next_pow2(n)
        # keep device-resident micro-batches as-is (asarray would force
        # a D2H readback just to re-upload them a few lines down)
        arrs = [t if hasattr(t, "shape") else np_.asarray(t)
                for t in tensors]
        batched_shapes = tuple((nb,) + tuple(a.shape[1:]) for a in arrs)
        verdict_key = (self._ns(), "dynb") + tuple(
            (s, str(a.dtype)) for s, a in zip(batched_shapes, arrs)) \
            + self._seg_suffix()
        ok = self._batch_ok.get(verdict_key)
        if ok is None:
            try:
                args = [jax.ShapeDtypeStruct(s, a.dtype)
                        for s, a in zip(batched_shapes, arrs)]
                jax.eval_shape(self._full_fn(count=False),
                               (self._abstract_params(),
                                getattr(self, "_post_aux", None)), *args)
                ok = True
            except Exception:
                ok = False
            self._batch_ok[verdict_key] = ok
        if not ok:
            return super().invoke_batched(tensors, n, keepdims)
        self.batch_size_hist[n] = self.batch_size_hist.get(n, 0) + 1
        self._last_dynb = tuple(
            (tuple(a.shape[1:]), str(a.dtype)) for a in arrs)
        arrs = self._pad_bucket(arrs, n, nb)
        params = self._packed_params()
        hits0 = self.cache_hits
        staged, fresh = self._stage(arrs)
        # donation: only when every device buffer was staged right here
        # (we own them all); the donating variant is its own cache entry
        donate = self._donate and fresh
        key = (self._ns(), "dynb", nb) + batched_shapes \
            + self._seg_suffix()
        if donate:
            self.donated_invokes += 1
            dn = tuple(range(1, 1 + len(staged)))
            jitted = self._bucket_jit(
                key + ("don",),
                make=lambda: jax.jit(self._full_fn(), donate_argnums=dn))
        else:
            jitted = self._bucket_jit(key)
        tr = self.tracer
        prof = devprof.get()
        miss = self.cache_hits == hits0
        blabel = f"dynb:{nb}"
        if prof.enabled:
            prof.note_dispatch(self._prof_label(), blabel)
        if tr.active or (prof.enabled and miss):
            t0 = time.perf_counter()
            out = _to_tuple(jitted(params, *staged))
            t1 = time.perf_counter()
            if tr.active:
                tr.backend_span(self.trace_name or "xla",
                                "invoke_batched", t0, t1, n=n, bucket=nb,
                                cache="miss" if miss else "hit")
            if prof.enabled and miss:
                self._prof_capture(blabel, jitted,
                                   (params,) + tuple(staged), t1 - t0)
        else:
            out = _to_tuple(jitted(params, *staged))
        return tuple(o[:n] for o in out)

    @staticmethod
    def _pad_bucket(arrs, n: int, nb: int):
        """Pad a micro-batch up to its pow2 bucket by repeating the last
        frame's rows: real data keeps padded lanes numerically tame (vs
        zeros hitting e.g. a divide), and the pad rows are sliced away
        before anyone sees them. Device-resident inputs pad on device
        (numpy concatenate would pull them back to host)."""
        import numpy as np_

        if nb <= n:
            return arrs
        out = []
        for a in arrs:
            if type(a).__module__.startswith("jax"):
                import jax.numpy as xp
            else:
                xp = np_
            out.append(xp.concatenate(
                [a, xp.repeat(a[-1:], nb - n, axis=0)], axis=0))
        return out

    def _invoke_batched_store(self, tensors, n: int, keepdims=()):
        """Micro-batched invoke through the store routing point: the
        whole micro-batch goes to ONE version (canary granularity is
        the buffer). Bucket keys are version-namespaced and carry
        shape+dtype so `prewarm_version` can compile the exact set the
        outgoing version served."""
        import jax
        import numpy as np_

        ver = self._pick_version()
        vs = self._vstates[ver]
        if vs.bundle.host_pre is not None:
            return super().invoke_batched(tensors, n, keepdims)
        nb = _next_pow2(n)
        arrs = [t if hasattr(t, "shape") else np_.asarray(t)
                for t in tensors]
        pairs = tuple(((nb,) + tuple(a.shape[1:]), str(a.dtype))
                      for a in arrs)
        basekey = ("dynb", nb) + pairs
        verdict_key = (self._ns(ver),) + basekey + self._seg_suffix()
        ok = self._batch_ok.get(verdict_key)
        if ok is None:
            try:
                args = [jax.ShapeDtypeStruct(s, np_.dtype(d))
                        for s, d in pairs]
                jax.eval_shape(self._full_fn(count=False,
                                             bundle=vs.bundle),
                               (self._abstract_of(vs.device_params),
                                getattr(self, "_post_aux", None)), *args)
                ok = True
            except Exception:
                ok = False
            self._batch_ok[verdict_key] = ok
        if not ok:
            return super().invoke_batched(tensors, n, keepdims)
        self.batch_size_hist[n] = self.batch_size_hist.get(n, 0) + 1
        self._last_dynb = tuple(
            (tuple(a.shape[1:]), str(a.dtype)) for a in arrs)
        arrs = self._pad_bucket(arrs, n, nb)
        self._note_bucket(ver, basekey)
        packed = self._with_seg(
            (vs.device_params, getattr(self, "_post_aux", None)))
        hits0 = self.cache_hits
        staged, fresh = self._stage(arrs)
        donate = self._donate and fresh
        if donate:
            self.donated_invokes += 1
            dn = tuple(range(1, 1 + len(staged)))
            jitted = self._bucket_jit(
                verdict_key + ("don",),
                make=lambda: jax.jit(self._full_fn(bundle=vs.bundle),
                                     donate_argnums=dn))
        else:
            jitted = self._bucket_jit(
                verdict_key,
                make=lambda: jax.jit(self._full_fn(bundle=vs.bundle)))
        prof = devprof.get()
        blabel = devprof.bucket_label(basekey)
        if prof.enabled:
            prof.note_dispatch(self._prof_label(), blabel)
        t0 = time.perf_counter()
        try:
            out = _to_tuple(jitted(packed, *staged))
        except Exception:
            self._record_invoke(ver, t0, error=True)
            raise
        dt = self._record_invoke(ver, t0)
        if prof.enabled and self.cache_hits == hits0:
            self._prof_capture(blabel, jitted,
                               (packed,) + tuple(staged), dt)
        tr = self.tracer
        if tr.active:
            tr.backend_span(self.trace_name or "xla", "invoke_batched",
                            t0, t0 + dt, n=n, bucket=nb, version=ver,
                            cache="hit" if self.cache_hits > hits0
                            else "miss")
        return tuple(o[:n] for o in out)

    def _bucket_jit(self, key: tuple, make=None):
        import jax

        jitted = self._dyn_jits.pop(key, None)
        if jitted is None:
            self.cache_misses += 1
            jitted = jax.jit(self._full_fn()) if make is None else make()
            if len(self._dyn_jits) >= self._dyn_cache_max:
                evicted, _ = self._dyn_jits.popitem(last=False)
                log.info("dyn-shape cache full: evicted %s", evicted)
        else:
            self.cache_hits += 1
        self._dyn_jits[key] = jitted      # re-insert = LRU touch
        return jitted

    def _insert_jit(self, key: tuple, jitted) -> None:
        """Install a pre-compiled jit (staged prewarm / manifest replay)
        without touching the hit/miss counters — these compiles happened
        off the hot path."""
        if key in self._dyn_jits:
            return
        if len(self._dyn_jits) >= self._dyn_cache_max:
            self._dyn_jits.popitem(last=False)
        self._dyn_jits[key] = jitted

    def stage_bucket(self, nb: int) -> bool:
        """Compile the pow2 occupancy bucket ``nb`` for the most
        recently served dynamic-batch signature, OFF the hot path, and
        install it via `_insert_jit` — the autotuner stages a refined
        bucket here *before* flipping ``tensor_batch``'s ``max_batch``,
        so the first flush at the new size takes a cache hit instead of
        an in-band recompile stall. Safe to call from the controller
        thread: it never touches worker-owned seg state (`_seg_begin`),
        and a concurrent `_insert_jit` against the worker's LRU is at
        worst one transient extra cache entry. Returns True when the
        bucket is live (freshly compiled or already cached)."""
        pairs = self._last_dynb
        if pairs is None or nb < 1:
            return False
        import jax
        import numpy as np_

        from nnstreamer_tpu.runtime.sync import device_sync

        nb = _next_pow2(int(nb))
        batched = tuple(((nb,) + tuple(s), d) for s, d in pairs)
        ver = None
        if self._store_entry is not None:
            ver = self._adopted_version
            vs = self._vstates.get(ver)
            if vs is None:
                return False
            basekey = ("dynb", nb) + batched
            key = (self._ns(ver),) + basekey + self._seg_suffix()
            fn = self._full_fn(bundle=vs.bundle)
            packed = self._with_seg(
                (vs.device_params, getattr(self, "_post_aux", None)))
        else:
            key = (self._ns(), "dynb", nb) \
                + tuple(s for s, _ in batched) + self._seg_suffix()
            fn = self._full_fn()
            packed = self._packed_params()
        if key in self._dyn_jits:
            return True
        prof = devprof.get()
        t0 = time.perf_counter()
        try:
            jitted = jax.jit(fn)
            args = tuple(
                jax.device_put(np_.zeros(s, dtype=np_.dtype(d)),
                               self._device) for s, d in batched)
            if prof.enabled:
                prof.note_dispatch(self._prof_label(), f"dynb:{nb}")
            device_sync(_to_tuple(jitted(packed, *args)),
                        self.tracer, self.trace_name)
        except Exception as e:
            log.warning("stage_bucket(%d) skipped: %s", nb, e)
            return False
        self._prof_capture(f"dynb:{nb}", jitted, (packed,) + args,
                           time.perf_counter() - t0)
        self._insert_jit(key, jitted)
        if ver is not None:
            self._note_bucket(ver, basekey)
        return True

    # -- residency pressure hooks (serving/tenancy.ModelResidency) ---------
    def jit_cache_size(self) -> int:
        """Live compiled entries (bucketed jits + the static-path jit).
        A model with zero is 'cold': releasing it again is free."""
        return len(self._dyn_jits) + (1 if self._jitted is not None else 0)

    def release_compiled(self) -> int:
        """Drop every compiled artifact (LRU eviction under memory
        pressure — serving/tenancy.ModelResidency). Params, specs, and
        store attachments stay: the next invoke recompiles the needed
        bucket (a counted cache miss), results are bitwise unchanged.
        Returns the number of entries released."""
        n = self.jit_cache_size()
        self._dyn_jits.clear()
        self._batch_ok.clear()
        self._jitted = None
        return n

    @staticmethod
    def _tree_bytes(params) -> int:
        import jax

        if params is None:
            return 0
        return sum(
            getattr(a, "nbytes", 0)
            for a in jax.tree_util.tree_leaves(params))

    def resident_bytes(self) -> int:
        """Device bytes held by this model's params (all resident store
        versions, or the single non-store param tree)."""
        if self._vstates:
            return sum(self._tree_bytes(vs.device_params)
                       for vs in self._vstates.values())
        return self._tree_bytes(self._device_params)

    def resident_bytes_by_version(self) -> Dict[str, int]:
        """Per-resident-version device bytes ({"v<N>": bytes}) — the
        devprof HBM ledger's per-model-version attribution; empty for
        non-store models (the plain resident_bytes row covers those)."""
        return {f"v{ver}": self._tree_bytes(vs.device_params)
                for ver, vs in sorted(self._vstates.items())}

    def reload(self, model: Any) -> None:
        """Hot model swap (is-updatable analog): double-buffered — the new
        bundle is resolved and staged before the old one is dropped. For a
        shared model, the swap updates the shared entry so ALL holders
        pick it up on their next invoke."""
        import jax

        if self._store_entry is not None:
            raise BackendError(
                f"this filter serves {self._store_entry.name!r} through "
                f"the model store; per-filter reload would fork it from "
                f"the registry — register the new weights as a version "
                f"and ModelStore.update({self._store_entry.name!r}, "
                f"<version>) instead (or `python -m nnstreamer_tpu "
                f"models swap`)")
        new_bundle = self._resolve(model)
        new_params = (
            jax.device_put(new_bundle.params, self._device)
            if new_bundle.params is not None
            else None
        )
        if self._shared is not None:
            with _shared_lock:
                self._shared.bundle = new_bundle
                self._shared.device_params = new_params
                self._shared.version += 1
            return
        self._bundle, self._device_params = new_bundle, new_params
        self._jitted = None
        self._gen += 1               # new cache namespace
        self._dyn_jits.clear()
        self._batch_ok.clear()
