"""XLA backend — the one first-class NN engine (replaces the reference's
vendor subplugin zoo, SURVEY.md §2.3; the BASELINE.json north star).

A model is a jax-traceable callable ``fn(params, *inputs) -> outputs``
plus a params pytree. Sources of models:

- the in-repo model zoo (``model=zoo://mobilenet_v2``) — models/zoo.py
- a python path (``model=pkg.module:build``) whose callable returns a
  `ModelBundle` or is itself the traced function
- a `ModelBundle` passed programmatically to the element

TPU-first properties:
- **Fusion**: the tensor_transform chains adjacent to the filter are
  absorbed via `fuse()` and traced into the *same* jit computation, so
  normalization/typecast/argmax run on-device fused around the matmuls —
  zero extra HBM round-trips (north star: "fold tensor_transform into the
  same XLA computation").
- **Negotiation via tracing**: output specs come from `jax.eval_shape`
  (no device work at build time).
- **Async dispatch**: `invoke` returns device arrays without blocking; the
  scheduler's queues overlap host work with device execution. The D2H
  sync happens once, at a sink/decoder (TensorBuffer.to_host) — the
  anti-pattern this avoids is the reference's per-frame
  cudaDeviceSynchronize (tensor_filter_tensorrt.cc:239).
"""

from __future__ import annotations

import importlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

from nnstreamer_tpu.backends.base import (
    ArrayTuple,
    ElementwiseFn,
    FilterBackend,
    register_backend,
)
from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

log = get_logger("backend.xla")


@dataclass
class ModelBundle:
    """A loadable model: traced function + params + optional fixed specs."""

    fn: Callable[..., Any]            # fn(params, *inputs) -> output(s)
    params: Any = None
    in_spec: Optional[TensorsSpec] = None
    out_spec: Optional[TensorsSpec] = None
    name: str = ""


def _to_tuple(x) -> Tuple:
    if isinstance(x, tuple):
        return x
    if isinstance(x, list):
        return tuple(x)
    return (x,)


def _spec_from_shapes(shapes) -> TensorsSpec:
    infos = tuple(
        TensorInfo(shape=tuple(s.shape), dtype=DType.from_np(s.dtype))
        for s in shapes
    )
    return TensorsSpec(tensors=infos)


@register_backend("xla")
class XLABackend(FilterBackend):
    def __init__(self):
        self._bundle: Optional[ModelBundle] = None
        self._pre: Optional[ElementwiseFn] = None
        self._post: Optional[ElementwiseFn] = None
        self._jitted = None
        self._device = None
        self._device_params = None
        self._in_spec: Optional[TensorsSpec] = None
        self._out_spec: Optional[TensorsSpec] = None
        self._loader_opts: Dict[str, Any] = {}

    # -- open / model resolution ------------------------------------------
    def open(self, props: Dict[str, Any]) -> None:
        import jax

        model = props.get("model")
        if model is None:
            raise BackendError(
                "framework=xla requires model=<zoo://name | pkg.module:attr "
                "| /path/model.{tflite,npz} | ModelBundle | jax callable>"
            )
        from nnstreamer_tpu.modelio import parse_loader_opts

        self._loader_opts = parse_loader_opts(props.get("custom") or "")
        self._bundle = self._resolve(model)
        accel = props.get("accelerator") or ""
        self._device = self._pick_device(accel)
        if self._bundle.params is not None:
            self._device_params = jax.device_put(self._bundle.params, self._device)
        else:
            self._device_params = None
        log.info("opened model %s on %s", self._bundle.name or model, self._device)

    def _resolve(self, model) -> ModelBundle:
        if isinstance(model, ModelBundle):
            return model
        if callable(model):
            return ModelBundle(
                fn=lambda params, *xs: model(*xs),
                params=None,
                in_spec=getattr(model, "in_spec", None),
                out_spec=getattr(model, "out_spec", None),
                name=getattr(model, "__name__", "callable"),
            )
        if isinstance(model, str) and model.startswith("zoo://"):
            try:
                from nnstreamer_tpu.models.zoo import build_model
            except ImportError as e:
                raise BackendError(f"model zoo unavailable: {e}") from e
            return build_model(model[len("zoo://"):])
        if isinstance(model, str):
            from nnstreamer_tpu import modelio

            ext = model.rsplit(".", 1)[-1].lower() if "." in model else ""
            if ext in modelio.MODEL_EXTENSIONS:
                return modelio.load_model_file(model, **self._loader_opts)
        if isinstance(model, str) and ":" in model:
            mod_name, _, attr = model.partition(":")
            try:
                obj = getattr(importlib.import_module(mod_name), attr)
            except (ImportError, AttributeError) as e:
                raise BackendError(f"cannot load model {model!r}: {e}") from e
            built = obj() if not isinstance(obj, ModelBundle) else obj
            if isinstance(built, ModelBundle):
                return built
            return self._resolve(built)
        raise BackendError(
            f"unrecognized model reference {model!r} for framework=xla; "
            f"expected zoo://<name>, pkg.module:attr, a ModelBundle, or a "
            f"jax callable"
        )

    def _pick_device(self, accelerator: str):
        import jax

        devices = jax.devices()
        if accelerator:
            # "tpu:2" / "tpu" / "cpu" (accl_hw-string analog, hw_accel.c)
            kind, _, idx = accelerator.partition(":")
            matching = [d for d in devices if d.platform.lower() == kind.lower()]
            if not matching:
                raise BackendError(
                    f"accelerator={accelerator!r} but no {kind!r} device is "
                    f"visible; available: "
                    f"{sorted({d.platform for d in devices})}"
                )
            return matching[int(idx)] if idx else matching[0]
        return devices[0]

    def close(self) -> None:
        self._jitted = None
        self._device_params = None

    # -- info / negotiation ------------------------------------------------
    def get_model_info(self):
        assert self._bundle is not None, "open() not called"
        return self._bundle.in_spec, self._bundle.out_spec

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        """Shape-infer the model's own output via jax.eval_shape.

        `in_spec` is what the *model* sees (the element already applied
        fused pre-chain spec transfer); fused chains affect invoke()
        only, so eval_shape runs on the bare bundle fn.
        """
        import jax

        assert self._bundle is not None
        self._in_spec = in_spec
        bundle = self._bundle
        bare = lambda params, *xs: _to_tuple(bundle.fn(params, *xs))
        args = [
            jax.ShapeDtypeStruct(t.shape, t.dtype.np_dtype)
            for t in in_spec.tensors
        ]
        try:
            out = jax.eval_shape(bare, self._abstract_params(), *args)
        except Exception as e:
            raise BackendError(
                f"model {self._bundle.name!r} does not accept input "
                f"{in_spec}: {e}"
            ) from e
        self._out_spec = _spec_from_shapes(_to_tuple(out))
        return self._out_spec

    def _abstract_params(self):
        import jax

        if self._device_params is None:
            return None
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), self._device_params
        )

    # -- fusion ------------------------------------------------------------
    def fuse(self, pre: Optional[ElementwiseFn], post: Optional[ElementwiseFn]) -> bool:
        self._pre = pre
        self._post = post
        self._jitted = None  # recompile with the fused graph
        return True

    def _full_fn(self):
        bundle = self._bundle
        pre, post = self._pre, self._post

        def full(params, *xs):
            if pre is not None:
                xs = pre(xs)
            out = _to_tuple(bundle.fn(params, *xs))
            if post is not None:
                out = post(out)
            return out

        return full

    # -- hot loop ----------------------------------------------------------
    def invoke(self, tensors: ArrayTuple) -> ArrayTuple:
        import jax

        if self._jitted is None:
            self._jitted = jax.jit(self._full_fn())
        # explicit async H2D staging before dispatch: on tunneled/remote
        # devices this overlaps the transfer with the previous frame's
        # compute (measured ~3.6x e2e FPS vs jit-internal staging)
        staged = tuple(jax.device_put(t, self._device) for t in tensors)
        out = self._jitted(self._device_params, *staged)
        return _to_tuple(out)

    def reload(self, model: Any) -> None:
        """Hot model swap (is-updatable analog): double-buffered — the new
        bundle is resolved and staged before the old one is dropped."""
        import jax

        new_bundle = self._resolve(model)
        new_params = (
            jax.device_put(new_bundle.params, self._device)
            if new_bundle.params is not None
            else None
        )
        self._bundle, self._device_params = new_bundle, new_params
        self._jitted = None
