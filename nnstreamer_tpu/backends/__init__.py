"""Filter backends — the model-execution engines behind `tensor_filter`.

The reference ships ~20 vendor subplugins implementing
`GstTensorFilterFramework` (SURVEY.md §2.3). The TPU build replaces that
zoo with three first-class backends:

- ``xla``     — models as jax callables / flax modules / StableHLO,
                jit-compiled and executed on TPU (backends/xla.py)
- ``custom``  — in-process python callables (the custom-easy analog,
                include/tensor_filter_custom_easy.h)
- ``pallas``  — hand-written TPU kernels registered as filters
- ``python3`` — reference-contract script files (CustomFilter class,
                tensor_filter_python3.cc analog — runs the reference's
                own passthrough.py/scaler.py unmodified)

Importing this package registers all built-in backends.
"""

from nnstreamer_tpu.backends.base import FilterBackend
from nnstreamer_tpu.backends.custom import CustomBackend, register_custom_easy
from nnstreamer_tpu.backends.pallas_backend import (
    PallasBackend, register_pallas_filter)
from nnstreamer_tpu.backends.python3_script import Python3ScriptBackend
from nnstreamer_tpu.backends.xla import XLABackend

__all__ = [
    "FilterBackend",
    "CustomBackend",
    "PallasBackend",
    "Python3ScriptBackend",
    "XLABackend",
    "register_custom_easy",
    "register_pallas_filter",
]
