"""Pallas filter backend — hand-written TPU kernels as tensor_filter
models (``framework=pallas model=<registered kernel>``).

The reference's closest analog is the custom-easy subplugin (in-process
function registration, include/tensor_filter_custom_easy.h) — here the
registered function is a jax-traceable kernel (usually a `pl.pallas_call`
wrapper from pallas_ops.py), jit-compiled with any fused pre/post chains
into one device program.

Registration:

    @register_pallas_filter("my_norm", out_like=lambda spec: spec)
    def my_norm(tensors):
        return (pallas_ops.normalize_u8(tensors[0]),)

`out_like` maps the input TensorsSpec to the output spec; omit it to have
the backend infer shapes with jax.eval_shape.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from nnstreamer_tpu.backends.base import (
    ArrayTuple, ElementwiseFn, FilterBackend, register_backend)
from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec


@dataclass
class _PallasEntry:
    fn: Callable[[ArrayTuple], ArrayTuple]
    out_like: Optional[Callable[[TensorsSpec], TensorsSpec]] = None


_kernels: Dict[str, _PallasEntry] = {}
# RLock: _builtins() holds it across its check-then-register sequence,
# and register_pallas_filter re-acquires it on the same thread
_lock = threading.RLock()


def register_pallas_filter(name: str, out_like=None):
    """Decorator registering kernel `fn(tensors)->tensors` as a filter."""
    def deco(fn):
        with _lock:
            _kernels[name] = _PallasEntry(fn=fn, out_like=out_like)
        return fn
    return deco


def _builtins() -> None:
    """Register the stock pallas_ops kernels lazily."""
    from nnstreamer_tpu.backends import pallas_ops

    with _lock:   # held across check+register (RLock; no concurrent dupes)
        if "normalize_u8" in _kernels:
            return

        def norm_spec(spec: TensorsSpec) -> TensorsSpec:
            return TensorsSpec(tensors=tuple(
                TensorInfo(t.shape, DType.FLOAT32) for t in spec.tensors),
                rate=spec.rate)

        register_pallas_filter("normalize_u8", out_like=norm_spec)(
            lambda ts: tuple(pallas_ops.normalize_u8(t) for t in ts))


@register_backend("pallas")
class PallasBackend(FilterBackend):
    def __init__(self):
        self._entry: Optional[_PallasEntry] = None
        self._name = ""
        self._pre: Optional[ElementwiseFn] = None
        self._post: Optional[ElementwiseFn] = None
        self._jitted = None
        self._in_spec: Optional[TensorsSpec] = None

    def open(self, props: Dict[str, Any]) -> None:
        _builtins()
        model = props.get("model")
        if callable(model):
            self._entry = _PallasEntry(fn=model)
            self._name = getattr(model, "__name__", "callable")
            return
        with _lock:
            entry = _kernels.get(model)
        if entry is None:
            raise BackendError(
                f"no pallas filter named {model!r} registered; available: "
                f"{sorted(_kernels)} (register with "
                f"@register_pallas_filter)")
        self._entry = entry
        self._name = str(model)

    def get_model_info(self):
        return None, None  # adapts to the negotiated input

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        self._in_spec = in_spec
        if self._entry.out_like is not None:
            return self._entry.out_like(in_spec)
        args = tuple(
            jax.ShapeDtypeStruct(t.shape, t.dtype.np_dtype)
            for t in in_spec.tensors)
        try:
            out = jax.eval_shape(lambda ts: self._entry.fn(ts), args)
        except Exception as e:
            raise BackendError(
                f"pallas filter {self._name!r} rejected input {in_spec}: {e}"
            ) from e
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return TensorsSpec(tensors=tuple(
            TensorInfo(tuple(o.shape), DType.from_np(o.dtype)) for o in outs),
            rate=in_spec.rate)

    def fuse(self, pre, post) -> bool:
        self._pre, self._post = pre, post
        self._jitted = None
        return True

    def invoke(self, tensors: ArrayTuple) -> ArrayTuple:
        if self._jitted is None:
            entry, pre, post = self._entry, self._pre, self._post

            def full(ts):
                if pre is not None:
                    ts = pre(ts)
                out = entry.fn(tuple(ts))
                out = out if isinstance(out, (tuple, list)) else (out,)
                if post is not None:
                    out = post(tuple(out))
                return tuple(out)

            self._jitted = jax.jit(full)
        return tuple(self._jitted(tuple(tensors)))
