"""Built-in Pallas TPU kernels for streaming hot ops.

These cover the per-frame host-side ops the reference implements with Orc
SIMD on CPU (gsttensor_transform.c:463-493 typecast/arith kernels) — on
TPU they are VMEM-resident VPU kernels fused into one pass:

- ``normalize_u8``  — uint8 frame → (x - mean) / std float/bf16, the
  converter+transform ingest path in one kernel.
- ``clamp_scale``   — clamp + affine, the transform `clamp`/`stand` path.
- ``sparse_to_dense`` — device-side COO scatter (gsttensor_sparseutil.c
  to_dense analog, but on-chip).

Kernels run `interpret=True` automatically off-TPU so the same code path
is unit-testable on the CPU mesh (tests/conftest.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# -- normalize: uint8 → (x - mean) / std ------------------------------------

def _normalize_kernel(mean: float, inv_std: float, out_dtype, x_ref, o_ref):
    x = x_ref[:]
    if x.dtype in (jnp.uint8, jnp.int8, jnp.uint16, jnp.int16):
        # Mosaic can't lower narrow-int → float casts directly; widen first
        x = x.astype(jnp.int32)
    x = x.astype(jnp.float32)
    o_ref[:] = ((x - mean) * inv_std).astype(out_dtype)


def normalize_u8(x, mean: float = 127.5, std: float = 127.5,
                 out_dtype=jnp.float32):
    """uint8 (..., W, C) → normalized float. One VMEM pass."""
    kern = functools.partial(_normalize_kernel, float(mean), 1.0 / float(std),
                             out_dtype)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(x.shape, out_dtype),
        interpret=_interpret(),
    )(x)


# -- clamp + affine ----------------------------------------------------------

def _clamp_scale_kernel(lo: float, hi: float, scale: float, offset: float,
                        x_ref, o_ref):
    x = x_ref[:]
    x = jnp.clip(x, lo, hi)
    o_ref[:] = x * scale + offset


def clamp_scale(x, lo: float, hi: float, scale: float = 1.0,
                offset: float = 0.0):
    kern = functools.partial(_clamp_scale_kernel, float(lo), float(hi),
                             float(scale), float(offset))
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_interpret(),
    )(x)


# -- sparse COO → dense on device -------------------------------------------

def sparse_to_dense(values, flat_indices, shape: Tuple[int, ...]):
    """Device-side scatter of a COO wire payload into a dense tensor.

    Scatter is a gather/scatter-unit op, not a Pallas sweet spot — XLA's
    native scatter lowering is already optimal, so this stays jnp (the
    kernel boundary is documented here deliberately).
    """
    n = 1
    for d in shape:
        n *= d
    dense = jnp.zeros((n,), values.dtype)
    dense = dense.at[flat_indices].set(values)
    return dense.reshape(shape)
