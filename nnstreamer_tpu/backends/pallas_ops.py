"""Built-in Pallas TPU kernels for streaming hot ops.

These cover the per-frame host-side ops the reference implements with Orc
SIMD on CPU (gsttensor_transform.c:463-493 typecast/arith kernels) — on
TPU they are VMEM-resident VPU kernels fused into one pass:

- ``normalize_u8``  — uint8 frame → (x - mean) / std float/bf16, the
  converter+transform ingest path in one kernel.
- ``clamp_scale``   — clamp + affine, the transform `clamp`/`stand` path.
- ``sparse_to_dense`` — device-side COO scatter (gsttensor_sparseutil.c
  to_dense analog, but on-chip).

Kernels run `interpret=True` automatically off-TPU so the same code path
is unit-testable on the CPU mesh (tests/conftest.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# -- normalize: uint8 → (x - mean) / std ------------------------------------

def _normalize_kernel(mean: float, inv_std: float, out_dtype, x_ref, o_ref):
    x = x_ref[:]
    if x.dtype in (jnp.uint8, jnp.int8, jnp.uint16, jnp.int16):
        # Mosaic can't lower narrow-int → float casts directly; widen first
        x = x.astype(jnp.int32)
    x = x.astype(jnp.float32)
    o_ref[:] = ((x - mean) * inv_std).astype(out_dtype)


def normalize_u8(x, mean: float = 127.5, std: float = 127.5,
                 out_dtype=jnp.float32):
    """uint8 (..., W, C) → normalized float. One VMEM pass."""
    kern = functools.partial(_normalize_kernel, float(mean), 1.0 / float(std),
                             out_dtype)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(x.shape, out_dtype),
        interpret=_interpret(),
    )(x)


# -- clamp + affine ----------------------------------------------------------

def _clamp_scale_kernel(lo: float, hi: float, scale: float, offset: float,
                        x_ref, o_ref):
    x = x_ref[:]
    x = jnp.clip(x, lo, hi)
    o_ref[:] = x * scale + offset


def clamp_scale(x, lo: float, hi: float, scale: float = 1.0,
                offset: float = 0.0):
    kern = functools.partial(_clamp_scale_kernel, float(lo), float(hi),
                             float(scale), float(offset))
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_interpret(),
    )(x)


# -- sparse COO → dense on device -------------------------------------------

def sparse_to_dense(values, flat_indices, shape: Tuple[int, ...]):
    """Device-side scatter of a COO wire payload into a dense tensor.

    Scatter is a gather/scatter-unit op, not a Pallas sweet spot — XLA's
    native scatter lowering is already optimal, so this stays jnp (the
    kernel boundary is documented here deliberately).
    """
    n = 1
    for d in shape:
        n *= d
    dense = jnp.zeros((n,), values.dtype)
    dense = dense.at[flat_indices].set(values)
    return dense.reshape(shape)


# -- flash attention ---------------------------------------------------------

def _flash_kernel(scale: float, causal: bool, bq: int, bk: int,
                  q_ref, k_ref, v_ref, o_ref):
    """One (batch·head, q-block) program: online-softmax over K/V blocks.

    K/V for this head live fully in VMEM (BlockSpec maps the whole
    sequence); the inner fori_loop streams them block-by-block through
    the MXU with flash-attention running max/normalizer accumulators, so
    the (S × S) score matrix never materializes.
    """
    q = q_ref[0]                              # (bq, D), input dtype

    s_total = k_ref.shape[1]
    qi = pl.program_id(1)
    n_kb = s_total // bk

    def body(j, carry):
        m, l, acc = carry
        # inputs stay in their (bf16) dtype into the MXU; accumulation
        # is f32 via preferred_element_type — the standard flash recipe
        k_blk = k_ref[0, pl.ds(j * bk, bk), :]
        v_blk = v_ref[0, pl.ds(j * bk, bk), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # (bq, bk) f32
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            cols = j * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, -1e30)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(s <= -1e29, 0.0, p)     # fully-masked rows stay 0
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    d = q.shape[-1]
    m0 = jnp.full((bq,), -1e30, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    a0 = jnp.zeros((bq, d), jnp.float32)
    # causal: K blocks entirely above the diagonal are fully masked —
    # skip them instead of burning MXU cycles on zeroed scores (halves
    # the causal FLOPs, the case the transformer always runs)
    upper = pl.cdiv((qi + 1) * bq, bk) if causal else n_kb
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, a0))
    l = jnp.maximum(l, 1e-20)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def flash_attention(q, k, v, *, causal: bool = False,
                    block_q: int = 128, block_k: int = 128):
    """Fused attention for (B, S, H, D) tensors — the transformer hot op
    as a Pallas kernel (flash-attention online softmax; S×S scores never
    leave VMEM). Requires S % block sizes == 0 (pad upstream); falls back
    to interpret mode off-TPU like every kernel here."""
    b, s, h, d = q.shape
    bq = min(block_q, s)
    bk = min(block_k, s)
    if s % bq or s % bk:
        raise ValueError(
            f"flash_attention needs seq len {s} divisible by block sizes "
            f"({bq}, {bk}); pad the sequence upstream")
    scale = d ** -0.5

    def bhsd(t):
        return t.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    qf, kf, vf = bhsd(q), bhsd(k), bhsd(v)
    kern = functools.partial(_flash_kernel, scale, causal, bq, bk)
    out = pl.pallas_call(
        kern,
        grid=(b * h, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=_interpret(),
    )(qf, kf, vf)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
