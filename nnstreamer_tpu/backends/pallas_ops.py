"""Built-in Pallas TPU kernels for streaming hot ops.

These cover the per-frame host-side ops the reference implements with Orc
SIMD on CPU (gsttensor_transform.c:463-493 typecast/arith kernels) — on
TPU they are VMEM-resident VPU kernels fused into one pass:

- ``normalize_u8``  — uint8 frame → (x - mean) / std float/bf16, the
  converter+transform ingest path in one kernel.
- ``clamp_scale``   — clamp + affine, the transform `clamp`/`stand` path.
- ``sparse_to_dense`` — device-side COO scatter (gsttensor_sparseutil.c
  to_dense analog, but on-chip).

Kernels run `interpret=True` automatically off-TPU so the same code path
is unit-testable on the CPU mesh (tests/conftest.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# -- normalize: uint8 → (x - mean) / std ------------------------------------

def _normalize_kernel(mean: float, inv_std: float, out_dtype, x_ref, o_ref):
    x = x_ref[:]
    if x.dtype in (jnp.uint8, jnp.int8, jnp.uint16, jnp.int16):
        # Mosaic can't lower narrow-int → float casts directly; widen first
        x = x.astype(jnp.int32)
    x = x.astype(jnp.float32)
    o_ref[:] = ((x - mean) * inv_std).astype(out_dtype)


def normalize_u8(x, mean: float = 127.5, std: float = 127.5,
                 out_dtype=jnp.float32):
    """uint8 (..., W, C) → normalized float. One VMEM pass."""
    kern = functools.partial(_normalize_kernel, float(mean), 1.0 / float(std),
                             out_dtype)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(x.shape, out_dtype),
        interpret=_interpret(),
    )(x)


# -- fused dynamic row quantization (W8A8 activations) -----------------------

def _quantize_rows_kernel(x_ref, q_ref, s_ref):
    x = x_ref[...].astype(jnp.float32)            # (bm, K) in VMEM
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)   # (bm, 1)
    q_ref[...] = jnp.clip(jnp.round(x / scale),
                          -127, 127).astype(jnp.int8)
    s_ref[...] = scale


def _quantize_rows_xla(x):
    """Plain-XLA twin of _quantize_rows_kernel — the one place the
    quantization formula lives outside the kernel, used for row counts
    the 8-row Mosaic sublane can't tile."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def quantize_rows(x, block_rows: int = 256):
    """(M, K) float → (int8 (M, K), f32 scales (M, 1)): symmetric
    per-row dynamic quantization in ONE VMEM pass.

    This is the W8A8 activation-quant hot path: expressed in XLA (amax
    reduce + round/clip/cast around the int8 dot) the quantization made
    ~3 HBM trips over the activations and cost MORE than the int8
    matmul it feeds (0.62 ms vs 0.13 ms at 16384×1024, the measured
    reason models/quant.py documented W8A8 at 0.74× bf16). Fused here:
    read x once, write int8 + one (M, 1) scale column. Row counts not
    divisible by the 8-row Mosaic sublane are zero-padded up to the
    next multiple of 8 and the outputs sliced back — pad rows quantize
    independently (per-row scales; amax 0 → scale 1 → q 0) so they
    never touch real rows, and the kernel keeps the single-HBM-trip
    property for ragged M (decode steps, tail microbatches) instead of
    falling back to the ~3-trip XLA path. `_quantize_rows_xla` remains
    as the formula's plain-XLA twin for reference/testing."""
    m, k = x.shape
    m_pad = (-m) % 8
    if m_pad:
        x = jnp.pad(x, ((0, m_pad), (0, 0)))
        m += m_pad
    bm = block_rows
    while bm > 8 and m % bm:
        bm //= 2
    q, s = pl.pallas_call(
        _quantize_rows_kernel,
        grid=(m // bm,),
        in_specs=[pl.BlockSpec((bm, k), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((bm, k), lambda i: (i, 0)),
                   pl.BlockSpec((bm, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((m, k), jnp.int8),
                   jax.ShapeDtypeStruct((m, 1), jnp.float32)],
        interpret=_interpret(),
    )(x)
    if m_pad:
        q, s = q[:m - m_pad], s[:m - m_pad]
    return q, s


# -- clamp + affine ----------------------------------------------------------

def _clamp_scale_kernel(lo: float, hi: float, scale: float, offset: float,
                        x_ref, o_ref):
    x = x_ref[:]
    x = jnp.clip(x, lo, hi)
    o_ref[:] = x * scale + offset


def clamp_scale(x, lo: float, hi: float, scale: float = 1.0,
                offset: float = 0.0):
    kern = functools.partial(_clamp_scale_kernel, float(lo), float(hi),
                             float(scale), float(offset))
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_interpret(),
    )(x)


# -- sparse COO → dense on device -------------------------------------------

def sparse_to_dense(values, flat_indices, shape: Tuple[int, ...]):
    """Device-side scatter of a COO wire payload into a dense tensor.

    Scatter is a gather/scatter-unit op, not a Pallas sweet spot — XLA's
    native scatter lowering is already optimal, so this stays jnp (the
    kernel boundary is documented here deliberately).
    """
    n = 1
    for d in shape:
        n *= d
    dense = jnp.zeros((n,), values.dtype)
    dense = dense.at[flat_indices].set(values)
    return dense.reshape(shape)


# -- flash attention ---------------------------------------------------------

def _causal_mask(jnp_mod, row_off, col_off, bq, bk):
    """rows>=cols block mask from global offsets (shared by all three
    flash kernels so the mask semantics can never diverge)."""
    rows = row_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = col_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return rows >= cols


def _online_softmax_update(q, k_blk, v_blk, m, l, acc, scale, mask):
    """One flash block update shared by both kernels: scaled QK^T on the
    MXU, optional mask, running max/normalizer, PV accumulation (f32)."""
    s = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -1e30)
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_cur)
    p = jnp.exp(s - m_new[:, None])
    if mask is not None:
        p = jnp.where(s <= -1e29, 0.0, p)     # fully-masked rows stay 0
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[:, None] + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _flash_kernel(scale: float, causal: bool, bq: int, bk: int,
                  q_ref, k_ref, v_ref, o_ref):
    """One (batch·head, q-block) program: online-softmax over K/V blocks.

    K/V for this head live fully in VMEM (BlockSpec maps the whole
    sequence); the inner fori_loop streams them block-by-block through
    the MXU with flash-attention running max/normalizer accumulators, so
    the (S × S) score matrix never materializes.
    """
    q = q_ref[0]                              # (bq, D), input dtype

    s_total = k_ref.shape[1]
    qi = pl.program_id(1)
    n_kb = s_total // bk

    def body(masked, j, carry):
        # inputs stay in their (bf16) dtype into the MXU; accumulation
        # is f32 via preferred_element_type — the standard flash recipe
        k_blk = k_ref[0, pl.ds(j * bk, bk), :]
        v_blk = v_ref[0, pl.ds(j * bk, bk), :]
        mask = _causal_mask(jnp, qi * bq, j * bk, bq, bk) \
            if masked else None
        return _online_softmax_update(q, k_blk, v_blk, *carry, scale, mask)

    d = q.shape[-1]
    m0 = jnp.full((bq,), -1e30, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    a0 = jnp.zeros((bq, d), jnp.float32)
    carry = (m0, l0, a0)
    if causal:
        # K blocks entirely above the diagonal are fully masked — skip
        # them (halves the causal FLOPs). Blocks entirely BELOW the
        # diagonal need no mask either: with enough blocks per program
        # (long S), running them through an unmasked first loop saves
        # the per-block iota/compare/where VPU lane work and measured
        # +13% at S=8192 (interleaved A/B, round 5). With few blocks
        # (S=2048 → 4) the second loop's pipeline restart costs more
        # than the mask it saves, so short grids keep one masked loop.
        upper = pl.cdiv((qi + 1) * bq, bk)            # first masked blk
        if n_kb >= 8:
            full = (qi * bq) // bk                    # blks fully below
            carry = jax.lax.fori_loop(
                0, full, functools.partial(body, False), carry)
            carry = jax.lax.fori_loop(
                full, upper, functools.partial(body, True), carry)
        else:
            carry = jax.lax.fori_loop(
                0, upper, functools.partial(body, True), carry)
    else:
        carry = jax.lax.fori_loop(
            0, n_kb, functools.partial(body, False), carry)
    m, l, acc = carry
    l = jnp.maximum(l, 1e-20)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_kgrid_kernel(scale: float, causal: bool, bq: int, bk: int,
                        q_ref, k_ref, v_ref, o_ref,
                        m_scr, l_scr, acc_scr):
    """K-blocked grid program for LONG sequences: grid is
    (batch·head, q_blocks, k_blocks) with k innermost, so K/V stream
    through VMEM one (bk, D) block at a time — per-step VMEM is O(bq·D +
    bk·D) regardless of S. The online-softmax carry (m, l, acc) lives in
    VMEM scratch, which persists across sequential grid steps on TPU."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    run = True
    if causal:
        # K blocks fully above the diagonal contribute nothing
        run = (ki * bk) <= (qi * bq + bq - 1)
    q = q_ref[0]
    k_blk = k_ref[0]
    v_blk = v_ref[0]

    @pl.when(run)
    def _step():
        m = m_scr[0, :]
        l = l_scr[0, :]
        acc = acc_scr[...]
        mask = _causal_mask(jnp, qi * bq, ki * bk, bq, bk) \
            if causal else None
        m, l, acc = _online_softmax_update(q, k_blk, v_blk, m, l, acc,
                                           scale, mask)
        m_scr[...] = jnp.broadcast_to(m[None, :], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l[None, :], l_scr.shape)
        acc_scr[...] = acc

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = jnp.maximum(l_scr[0, :], 1e-20)
        o_ref[0] = (acc_scr[...] / l[:, None]).astype(o_ref.dtype)


def _flash_attention_kgrid(qf, kf, vf, *, scale: float, causal: bool,
                           bq: int, bk: int, interpret: bool):
    bh, s, d = qf.shape
    kern = functools.partial(_flash_kgrid_kernel, scale, causal, bq, bk)
    return pl.pallas_call(
        kern,
        grid=(bh, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, k: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, k: (i, k, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, k: (i, k, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j, k: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), qf.dtype),
        scratch_shapes=[
            pltpu.VMEM((8, bq), jnp.float32),       # m (sublane-repl)
            pltpu.VMEM((8, bq), jnp.float32),       # l
            pltpu.VMEM((bq, d), jnp.float32),       # acc
        ],
        interpret=interpret,
    )(qf, kf, vf)


#: VMEM budget for holding a head's full K+V in the single-program
#: kernel; beyond it the K-grid streaming path takes over (long context)
_FLASH_VMEM_KV_BYTES = 8 << 20


def _auto_block(s: int, want: int) -> int:
    """Largest power-of-two block ≤ `want` that divides `s` (≥8 for
    Mosaic sublane tiling)."""
    b = min(want, s)
    while b > 8 and s % b:
        b //= 2
    return b


def _flash_plan(s: int, d: int, itemsize: int,
                block_q: int = 0, block_k: int = 0):
    """(kgrid?, bq, bk) for flash_attention — the per-path defaults the
    round-5 quiet-chip sweep landed on (see flash_attention docstring);
    pure so the choice is pinned by unit test."""
    kgrid = 2 * s * d * itemsize > _FLASH_VMEM_KV_BYTES
    want_q, want_k = (1024, 1024) if kgrid else (512, 512)
    bq = min(block_q or _auto_block(s, want_q), s)
    bk = min(block_k or _auto_block(s, want_k), s)
    return kgrid, bq, bk


def flash_attention(q, k, v, *, causal: bool = False,
                    block_q: int = 0, block_k: int = 0):
    """Fused attention for (B, S, H, D) tensors — the transformer hot op
    as a Pallas kernel (flash-attention online softmax; S×S scores never
    leave VMEM). Block sizes auto-tune per path to the largest dividing
    powers of two ≤ (512, 512) VMEM-resident / (1024, 1024) K-grid —
    round-5 sweep on the quiet chip: at S=2048 bk=512 beats the old
    bk=1024 default 0.61 vs 0.73 ms causal (28.8% vs 23.8% MFU) and
    0.64 vs 0.79 ms non-causal (54.9% vs 44.4%), the smaller K block
    wasting fewer masked FLOPs on diagonal blocks; the streaming K-grid
    runs fewer, larger steps best (S=32768: 30.4 ms/36.8% MFU at
    1024² vs 34.8/32.1 at the old default; 1024×2048 exceeds the 16M
    VMEM scoped limit). S=8192 is insensitive (±1.4%). Requires
    S % block == 0 (pad upstream); falls back to interpret mode off-TPU
    like every kernel here.

    Long sequences: when a head's full K+V would exceed the VMEM budget
    (S ≳ 16k at D=128), the kernel switches to a K-blocked grid that
    streams K/V through VMEM with scratch-carried online-softmax state —
    per-step VMEM is independent of S, so S=64k+ compiles and runs."""
    b, s, h, d = q.shape
    kgrid, bq, bk = _flash_plan(s, d, q.dtype.itemsize, block_q, block_k)
    if s % bq or s % bk:
        raise ValueError(
            f"flash_attention needs seq len {s} divisible by block sizes "
            f"({bq}, {bk}); pad the sequence upstream")
    scale = d ** -0.5

    def bhsd(t):
        return t.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    qf, kf, vf = bhsd(q), bhsd(k), bhsd(v)
    if kgrid:
        out = _flash_attention_kgrid(qf, kf, vf, scale=scale,
                                     causal=causal, bq=bq, bk=bk,
                                     interpret=_interpret())
        return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    kern = functools.partial(_flash_kernel, scale, causal, bq, bk)
    out = pl.pallas_call(
        kern,
        grid=(b * h, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=_interpret(),
    )(qf, kf, vf)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _flash_block_kernel(scale: float, bk: int, causal: bool,
                        qoff_ref, koff_ref,
                        q_ref, k_ref, v_ref, m_ref, l_ref, a_ref,
                        mo_ref, lo_ref, ao_ref):
    """Ring-attention block update: continue online softmax over ONE
    incoming K/V block, carrying (m, l, acc) in/out. Global query/key
    offsets arrive in SMEM so the causal mask works on rotated blocks;
    causal is trace-time static (no mask work on the non-causal path).
    m/l carry a (8, bq) sublane-replicated layout — Mosaic requires
    (8, 128)-tileable blocks, so the per-row scalar rides all 8 sublanes."""
    q = q_ref[0]                                  # (bq, D)
    m = m_ref[0, 0]                               # (bq,) from sublane 0
    l = l_ref[0, 0]
    acc = a_ref[0]                                # (bq, D)
    qi = pl.program_id(1)
    bq = q.shape[0]
    s_k = k_ref.shape[1]
    qoff = qoff_ref[0] + qi * bq
    koff = koff_ref[0]

    def body(j, carry):
        k_blk = k_ref[0, pl.ds(j * bk, bk), :]
        v_blk = v_ref[0, pl.ds(j * bk, bk), :]
        mask = _causal_mask(jnp, qoff, koff + j * bk, bq, bk) \
            if causal else None
        return _online_softmax_update(q, k_blk, v_blk, *carry, scale, mask)

    n_kb = s_k // bk
    if causal:
        # sub-blocks whose first key index exceeds this program's last
        # query index are fully masked: bound the loop instead of zeroing
        # their scores after full MXU work (_flash_kernel's same skip)
        row_max = qoff + bq - 1
        upper = jnp.clip(
            jax.lax.div(row_max - koff, jnp.int32(bk)) + 1, 0, n_kb)
    else:
        upper = n_kb
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m, l, acc))
    mo_ref[0] = jnp.broadcast_to(m[None, :], (8, m.shape[0]))
    lo_ref[0] = jnp.broadcast_to(l[None, :], (8, l.shape[0]))
    ao_ref[0] = acc


def flash_block_update(q, k_blk, v_blk, m, l, acc, *, q_offset, k_offset,
                       causal: bool, block_q: int = 128,
                       block_k: int = 128):
    """One ring-attention step as a Pallas kernel: q (BH, Sq, D) attends
    an incoming K/V block (BH, Sk, D), updating the flash carry
    m/l (BH, Sq) f32 and acc (BH, Sq, D) f32. Offsets are the global
    sequence positions of this device's queries / the rotated block's
    keys (traced scalars — they change every ring step)."""
    bh, sq, d = q.shape
    sk = k_blk.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    if sq % bq or sk % bk:
        raise ValueError(
            f"flash_block_update needs Sq={sq}, Sk={sk} divisible by "
            f"({bq}, {bk})")
    scale = d ** -0.5
    kern = functools.partial(_flash_block_kernel, scale, bk, bool(causal))
    grid = (bh, sq // bq)
    scalars = [jnp.asarray([v], jnp.int32) for v in (q_offset, k_offset)]
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    blk_q = pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0))
    blk_kv = pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0))
    blk_m = pl.BlockSpec((1, 8, bq), lambda i, j: (i, 0, j))
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[smem, smem,
                  blk_q, blk_kv, blk_kv, blk_m, blk_m, blk_q],
        out_specs=[blk_m, blk_m, blk_q],
        out_shape=[jax.ShapeDtypeStruct((bh, 8, sq), jnp.float32),
                   jax.ShapeDtypeStruct((bh, 8, sq), jnp.float32),
                   jax.ShapeDtypeStruct((bh, sq, d), jnp.float32)],
        # donate the carry: each ring step updates (m, l, acc) in place
        # instead of allocating three fresh HBM buffers per rotation
        input_output_aliases={5: 0, 6: 1, 7: 2},
        interpret=_interpret(),
    )(*scalars, q, k_blk, v_blk, m, l, acc)


def flash_carry_init(bh: int, sq: int, d: int):
    """Fresh (m, l, acc) carry for flash_block_update — m/l in the
    (BH, 8, Sq) sublane-replicated layout the kernel requires."""
    return (jnp.full((bh, 8, sq), -1e30, jnp.float32),
            jnp.zeros((bh, 8, sq), jnp.float32),
            jnp.zeros((bh, sq, d), jnp.float32))


def flash_carry_finalize(l, acc):
    """acc / l → attention output (BH, Sq, D)."""
    return acc / jnp.maximum(l[:, 0, :], 1e-20)[..., None]
