"""python3 scripted-filter backend — runs the reference's own scripts.

Reference parity: `ext/nnstreamer/tensor_filter/tensor_filter_python3.cc`
(embeds CPython, loads a user script defining ``class CustomFilter``)
and its API shim module `nnstreamer_python` (TensorShape). This backend
executes the reference's unmodified test scripts
(`tests/test_models/models/passthrough.py`, `scaler.py` — goldens from
`tests/nnstreamer_filter_python3/runTest.sh`): the host language here
IS Python, so "embedding" reduces to importing the script file.

Script contract (reference `nnstreamer_python` module semantics):
- ``import nnstreamer_python as nns`` — provided by this module's shim
  (`TensorShape(dims, np_type)`; dims are reference-order, i.e.
  innermost-first, and `getDims()` returns the mutable list).
- ``class CustomFilter`` with either static shapes
  (``getInputDim``/``getOutputDim`` → [TensorShape]) or adaptive
  (``setInputDim(in_dims) -> [TensorShape]``), plus
  ``invoke([flat np arrays]) -> [np arrays]``.
- ``custom=...`` on the filter element is passed verbatim as the single
  constructor argument, exactly like the reference.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.backends.base import (
    ArrayTuple, FilterBackend, register_backend)
from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec


class TensorShape:
    """Reference `nnstreamer_python.TensorShape`: innermost-first dims +
    numpy type; `getDims()` returns the mutable list (scripts edit it in
    place — scaler.py does)."""

    def __init__(self, dims, np_type=np.uint8):
        self._dims = [int(d) for d in dims]
        self._type = np.dtype(np_type)

    def getDims(self) -> List[int]:
        return self._dims

    def getType(self) -> np.dtype:
        return self._type

    def __repr__(self):
        return f"TensorShape({self._dims}, {self._type})"


def _install_shim() -> None:
    """Make `import nnstreamer_python` resolve to the shim, like the
    reference's embedded interpreter provides it."""
    mod = sys.modules.get("nnstreamer_python")
    if mod is not None and getattr(mod, "TensorShape", None) is TensorShape:
        return
    import types

    shim = types.ModuleType("nnstreamer_python")
    shim.TensorShape = TensorShape
    sys.modules["nnstreamer_python"] = shim


def _shape_to_spec(shapes: List[TensorShape]) -> TensorsSpec:
    infos = []
    for ts in shapes:
        if not isinstance(ts, TensorShape):
            raise BackendError(
                f"python3 script returned {type(ts).__name__}, expected "
                f"nnstreamer_python.TensorShape")
        # reference dims are innermost-first; our shapes are row-major
        infos.append(TensorInfo(tuple(reversed(ts.getDims())),
                                DType.from_np(np.dtype(ts.getType()))))
    return TensorsSpec(tensors=tuple(infos))


def _spec_to_shapes(spec: TensorsSpec) -> List[TensorShape]:
    return [TensorShape(list(reversed(t.shape)), t.dtype.np_dtype)
            for t in spec.tensors]


_load_lock = threading.Lock()
_script_seq = 0


def load_script_class(path: str, class_name: str):
    """Import a reference-contract script file and return its user
    class (CustomFilter / CustomConverter / CustomDecoder). Shared by
    the python3 filter backend and the scripted converter/decoder
    subplugins (elements/script_codec.py)."""
    global _script_seq

    if not isinstance(path, str) or not path.endswith(".py"):
        raise BackendError(
            f"python3 script must be a path ending .py, got {path!r}")
    if not os.path.isfile(path):
        raise BackendError(f"python3 script {path!r} does not exist")
    _install_shim()
    with _load_lock:
        _script_seq += 1
        name = f"_nns_py3_script_{_script_seq}"
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        try:
            spec.loader.exec_module(mod)
        except Exception as e:
            raise BackendError(
                f"python3 script {path!r} failed to import: "
                f"{type(e).__name__}: {e}") from e
    cls = getattr(mod, class_name, None)
    if cls is None:
        raise BackendError(
            f"python3 script {path!r} defines no {class_name} class "
            f"(the reference contract: 'DO NOT CHANGE CLASS NAME')")
    return cls


@register_backend("python3")
class Python3ScriptBackend(FilterBackend):
    """Loads `model=<path>.py`, instantiates CustomFilter(custom_args)."""

    def __init__(self):
        self._filter = None
        self._out_spec: Optional[TensorsSpec] = None
        self._path = ""

    def open(self, props: Dict[str, Any]) -> None:
        path = props.get("model")
        if not isinstance(path, str) or not path.endswith(".py"):
            raise BackendError(
                "framework=python3 requires model=<script path ending "
                ".py> (reference tensor_filter_python3 contract)")
        cls = load_script_class(path, "CustomFilter")
        custom = props.get("custom") or ""
        args = (custom,) if custom else ()
        try:
            self._filter = cls(*args)
        except Exception as e:
            raise BackendError(
                f"python3 script {path!r}: CustomFilter{args} raised "
                f"{type(e).__name__}: {e}") from e
        self._path = path
        self._custom = custom
        self._in_spec: Optional[TensorsSpec] = None

    def get_model_info(self) -> Tuple[Optional[TensorsSpec],
                                      Optional[TensorsSpec]]:
        f = self._filter
        assert f is not None, "open() not called"
        if hasattr(f, "getInputDim") and hasattr(f, "getOutputDim"):
            return (_shape_to_spec(f.getInputDim()),
                    _shape_to_spec(f.getOutputDim()))
        return None, None           # adaptive: setInputDim drives it

    def set_input_info(self, in_spec: TensorsSpec) -> TensorsSpec:
        f = self._filter
        assert f is not None
        if not hasattr(f, "setInputDim"):
            ins, outs = self.get_model_info()
            if outs is None:
                raise BackendError(
                    f"python3 script {self._path!r} has neither "
                    f"getInputDim/getOutputDim nor setInputDim")
            self._out_spec = outs
            return outs
        out = f.setInputDim(_spec_to_shapes(in_spec))
        if out is None:
            raise BackendError(
                f"python3 script {self._path!r}: setInputDim rejected "
                f"input {in_spec}")
        self._in_spec = in_spec
        self._out_spec = _shape_to_spec(out)
        return self._out_spec

    def invoke(self, tensors: ArrayTuple) -> ArrayTuple:
        f = self._filter
        assert f is not None
        from nnstreamer_tpu.runtime.sync import device_sync

        # scripts consume host arrays: resolve the whole tuple in ONE
        # counted sync (free if the scheduler already handed us host
        # data), then the per-leaf asarray below is a plain host view
        tensors = device_sync(tensors, name="python3_script")
        # the reference hands scripts flat arrays of the negotiated
        # dtype (scaler.py reshapes from 1-D itself)
        flat = [np.ravel(np.asarray(t))  # nnlint: disable=NNL002 whole-tuple device_sync above
                for t in tensors]
        out = f.invoke(flat)
        if out is None:
            raise BackendError(
                f"python3 script {self._path!r}: invoke returned None")
        if self._out_spec is None:
            ins, outs = self.get_model_info()
            self._out_spec = outs
        shaped = []
        for i, arr in enumerate(out):
            arr = np.asarray(arr)  # nnlint: disable=NNL002 script ABI returns host lists/ndarrays, never device arrays
            if self._out_spec is not None and \
                    i < len(self._out_spec.tensors):
                t = self._out_spec.tensors[i]
                shaped.append(arr.reshape(t.shape)
                              .astype(t.dtype.np_dtype, copy=False))
            else:
                shaped.append(arr)
        return tuple(shaped)

    def reload(self, model: Any) -> None:
        # carry the custom= constructor args across the hot-swap, and
        # re-drive the adaptive negotiation the old instance had
        in_spec = getattr(self, "_in_spec", None)
        self.open({"model": model, "custom": getattr(self, "_custom",
                                                     "")})
        if in_spec is not None:
            self.set_input_info(in_spec)
