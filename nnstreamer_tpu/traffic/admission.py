"""Bounded admission queue — load shedding at the serving edge.

The reference's query server buffers unboundedly and collapses under
overload (every request eventually times out, goodput → 0). Real
serving edges shed instead: a bounded queue admits up to `max_pending`
requests, refuses the rest with a *typed* rejection the client can act
on (wire `BUSY`, edge/protocol.py), and keeps per-cause counters so the
operator can see exactly what was shed and why.

Policy knobs:

- ``max_pending``   — bound on queued-but-not-yet-dequeued requests.
- ``max_inflight``  — bound on total outstanding requests (queued +
  dequeued-but-not-yet-replied); 0 = unlimited. This caps end-to-end
  concurrency/memory, not just the queue.
- ``shed_policy``   — what happens when the queue is full:
    * ``reject-newest`` (default): refuse the arriving request. FIFO
      fairness; the cheapest policy (nothing admitted is ever wasted).
    * ``reject-oldest``: admit the arrival, shed the oldest *queued*
      request (which has waited longest and is most likely to miss its
      deadline anyway). The victim still gets a BUSY reply — nothing is
      ever silently dropped.
    * ``deadline-drop``: requests carrying a ``meta["deadline_ms"]``
      budget are purged once the budget expires (measured from arrival,
      so no cross-host clock agreement is needed); a full queue with no
      expired entries falls back to reject-newest.

Multi-tenancy (``set_tenants``): with a `TenantTable` installed the
queue grows a weighted-fair front. Each request resolves to a tenant
class via ``meta["tenant"]`` (malformed names are refused with cause
``bad_tenant`` and charged to the ``!invalid`` pseudo-class; missing or
undeclared names fall to the table's default class). Each class gets
its own FIFO deque, and ``get()`` dequeues by start-time fair queueing:
the backlogged class with the smallest virtual time ``_vt[c]`` is
served and charged ``1/weight`` virtual time, so over any backlogged
interval class throughput converges to the weight ratio. A class also
gets a queue bound — explicit ``max_pending`` from its TenantClass, or
a fair share ``ceil(global_max_pending * weight / total_weight)`` — and
arrivals beyond it are refused (or, under reject-oldest, displace that
same class's oldest entry) with cause ``tenant_over_share``: one tenant
flooding can exhaust only its own share, never the whole queue. A class
``deadline_ms`` default applies to requests that don't carry their own.

Accounting contract (the conservation invariant tests assert):

    offered  == admitted + sum(rejected.values())
    admitted == replied + sum(shed.values()) + depth + inflight

``rejected`` counts at-the-door refusals (never entered the queue);
``shed`` counts post-admission victims (reject-oldest, deadline purge,
shutdown drain, dispatch errors). Both reach the client as BUSY. With
tenancy enabled both invariants additionally hold *per class* (see
``counters()["classes"]``), and the per-class counters sum exactly to
the globals: the resolved class is stamped into ``meta["_tenant_class"]``
at offer() and rides the buffer through the wire, so completion
accounting (``note_replied(cls=...)`` / ``note_failed(cls=...)``)
lands on the same class the offer was counted under.

The queue doubles as the serversrc's frame source: ``get()`` is
``queue.Queue``-compatible (blocking, raises ``queue.Empty`` on
timeout) so it drops into the existing drain loops, and ``None``
sentinels pushed via ``put_nowait`` bypass admission entirely (they are
teardown wakeups, not requests — and must never be lost to a full
queue).
"""

from __future__ import annotations

import math
import queue as _queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from nnstreamer_tpu.runtime.tracing import stamp_hop
from nnstreamer_tpu.serving.tenancy import (
    CLASS_META, INVALID_CLASS, TENANT_META, TenantTable,
    validate_tenant_name,
)

SHED_POLICIES = ("reject-newest", "reject-oldest", "deadline-drop")

#: TensorBuffer.meta key: per-request latency budget in ms, measured
#: from server-side arrival (deadline-drop purges expired entries)
DEADLINE_META = "deadline_ms"

#: retry-after suggestion before any service-rate estimate exists
_DEFAULT_RETRY_MS = 50.0


@dataclass
class AdmissionDecision:
    """Outcome of one `offer()`: admitted or not, why not, and any
    previously-admitted victims the caller must send BUSY replies for
    (reject-oldest / deadline purge)."""

    admitted: bool
    cause: Optional[str] = None          # rejection cause when refused
    queue_depth: int = 0
    retry_after_ms: float = _DEFAULT_RETRY_MS
    victims: List[Any] = field(default_factory=list)
    victim_cause: Optional[str] = None   # cause for the victims' BUSY


class _ClassState:
    """Per-tenant-class queue + accounting (all fields under the
    AdmissionQueue lock)."""

    __slots__ = ("name", "weight", "max_pending", "deadline_ms",
                 "q", "vt", "offered", "admitted", "replied",
                 "rejected", "shed", "inflight", "depth_peak")

    def __init__(self, name: str, weight: float = 1.0,
                 max_pending: Optional[int] = None,
                 deadline_ms: Optional[float] = None):
        self.name = name
        self.weight = weight
        self.max_pending = max_pending   # None = fair-share default
        self.deadline_ms = deadline_ms
        self.q: deque = deque()          # (item, enq_t, expiry_or_None)
        self.vt = 0.0                    # virtual finish time (SFQ)
        self.offered = 0
        self.admitted = 0
        self.replied = 0
        self.rejected: Dict[str, int] = {}
        self.shed: Dict[str, int] = {}
        self.inflight = 0
        self.depth_peak = 0


class AdmissionQueue:
    """Bounded request queue with typed rejection (module docstring)."""

    def __init__(self, max_pending: int = 64, max_inflight: int = 0,
                 shed_policy: str = "reject-newest"):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._q: deque = deque()          # (item, enq_t, expiry_or_None)
        self.configure(max_pending=max_pending, max_inflight=max_inflight,
                       shed_policy=shed_policy)
        self._inflight = 0
        self._closed = False
        # counters (all mutated under _lock)
        self._offered = 0
        self._admitted = 0
        self._replied = 0
        self._rejected: Dict[str, int] = {}
        self._shed: Dict[str, int] = {}
        self._depth_peak = 0
        # EWMA of inter-reply interval → retry-after suggestion
        self._ewma_reply_s: Optional[float] = None
        self._last_reply_t: Optional[float] = None
        # tenancy (None = single-tenant legacy mode)
        self._table: Optional[TenantTable] = None
        self._classes: Dict[str, _ClassState] = {}
        self._vnow = 0.0                 # system virtual time (SFQ)

    def configure(self, max_pending: Optional[int] = None,
                  max_inflight: Optional[int] = None,
                  shed_policy: Optional[str] = None) -> List[Any]:
        """Re-knob a live queue (serversrc applies its properties at
        start(); the process-wide QueryServer is created earlier with
        defaults).

        Changing ``shed_policy`` mid-stream re-evaluates the queued
        snapshot under the *new* policy instead of silently keeping the
        old one's assumptions: per-queue FIFO order is preserved (every
        policy dequeues FIFO; they differ only in full-queue/expiry
        behavior), and switching **to** ``deadline-drop`` immediately
        purges entries whose budget already expired — those victims are
        returned and the caller owes each a BUSY (cause ``deadline``),
        exactly as if the purge had happened on an offer().

        Shrinking ``max_pending`` below the current depth under
        ``reject-oldest`` immediately sheds the excess oldest entries
        (cause ``bound_shrink``) — they are returned as victims and the
        caller owes each a BUSY. Under the other policies queued
        entries drain naturally (``reject-newest`` only ever refuses
        arrivals), so the depth falls to the new bound without
        eviction. Either way nothing is stranded or double-counted:
        the conservation invariants hold exactly across a live bound
        change (see tests/test_traffic.py)."""
        victims: List[Any] = []
        with self._lock:
            if max_pending is not None:
                if max_pending < 1:
                    raise ValueError(
                        f"max_pending must be >= 1, got {max_pending}")
                self.max_pending = max_pending
                victims.extend(self._shrink_to_bound_locked())
            if max_inflight is not None:
                if max_inflight < 0:
                    raise ValueError(
                        f"max_inflight must be >= 0 (0 = unlimited), "
                        f"got {max_inflight}")
                self.max_inflight = max_inflight
            if shed_policy is not None:
                if shed_policy not in SHED_POLICIES:
                    raise ValueError(
                        f"shed_policy must be one of "
                        f"{' | '.join(SHED_POLICIES)}, got {shed_policy!r}")
                old = getattr(self, "shed_policy", None)
                self.shed_policy = shed_policy
                if old is not None and old != shed_policy \
                        and shed_policy == "deadline-drop":
                    victims = self._purge_expired(time.monotonic())
        return victims

    # -- tenancy -----------------------------------------------------------
    def set_tenants(self, table: Optional[TenantTable]) -> None:
        """Install (or clear) the weighted-fair tenant front. Existing
        per-class counters for classes that survive are kept; classes
        are created for every table entry so counters() shows declared
        tenants even before their first request."""
        with self._lock:
            self._table = table
            if table is None:
                return
            keep = set(table.names()) | {INVALID_CLASS}
            for name in [n for n in self._classes if n not in keep]:
                if not self._classes[name].q:
                    del self._classes[name]
            for c in table.classes():
                st = self._classes.get(c.name)
                if st is None:
                    st = _ClassState(c.name)
                    self._classes[c.name] = st
                st.weight = c.weight
                st.max_pending = c.max_pending
                st.deadline_ms = c.deadline_ms

    @property
    def tenancy(self) -> bool:
        return self._table is not None

    def _class_for(self, meta) -> Tuple[Optional[_ClassState], bool]:
        """Resolve a request's tenant class (lock held). Returns
        (state, valid): valid=False means the tenant name was malformed
        and the request must be refused with ``bad_tenant``."""
        tenant = meta.get(TENANT_META) if isinstance(meta, dict) else None
        if tenant is not None and not validate_tenant_name(tenant):
            return self._class_state(INVALID_CLASS), False
        cls = self._table.class_of(tenant)
        st = self._class_state(cls.name)
        return st, True

    def _class_state(self, name: str) -> _ClassState:
        st = self._classes.get(name)
        if st is None:
            st = _ClassState(name)
            self._classes[name] = st
        return st

    def _class_bound(self, st: _ClassState) -> int:
        """Effective per-class queue bound: explicit override, else a
        fair share of the global bound by weight (recomputed live so a
        configure(max_pending=...) re-shares automatically)."""
        if st.max_pending is not None:
            return st.max_pending
        total_w = sum(c.weight for c in self._table.classes()) or 1.0
        return max(1, math.ceil(self.max_pending * st.weight / total_w))

    def _total_depth(self) -> int:
        if self._table is None:
            return len(self._q)
        return len(self._q) + sum(
            len(st.q) for st in self._classes.values())

    # -- admission ---------------------------------------------------------
    def offer(self, item, now: Optional[float] = None) -> AdmissionDecision:
        """Admit `item` or return a typed refusal. Never blocks."""
        if now is None:
            now = time.monotonic()
        meta = getattr(item, "meta", None)
        with self._cv:
            self._offered += 1
            if self._table is not None:
                return self._offer_tenant(item, meta, now)
            expiry = self._expiry_from(meta, now, None)
            if self._closed:
                return self._refuse("shutdown")
            victims: List[Any] = []
            victim_cause = None
            if self.shed_policy == "deadline-drop":
                victims = self._purge_expired(now)
                if victims:
                    victim_cause = "deadline"
            if self.max_inflight and \
                    len(self._q) + self._inflight >= self.max_inflight:
                d = self._refuse("inflight_full")
                d.victims, d.victim_cause = victims, victim_cause
                return d
            if len(self._q) >= self.max_pending:
                if self.shed_policy == "reject-oldest":
                    victim, _, _ = self._q.popleft()
                    victims.append(victim)
                    victim_cause = "reject_oldest"
                    self._shed["reject_oldest"] = \
                        self._shed.get("reject_oldest", 0) + 1
                else:      # reject-newest, or deadline-drop w/o expiries
                    d = self._refuse("queue_full")
                    d.victims, d.victim_cause = victims, victim_cause
                    return d
            self._admitted += 1
            self._q.append((item, now, expiry))
            if isinstance(meta, dict):
                stamp_hop(meta, "admit", depth=len(self._q))
            if len(self._q) > self._depth_peak:
                self._depth_peak = len(self._q)
            self._cv.notify()
            return AdmissionDecision(
                admitted=True, queue_depth=len(self._q),
                retry_after_ms=self._retry_after_locked(),
                victims=victims, victim_cause=victim_cause)

    def _offer_tenant(self, item, meta, now: float) -> AdmissionDecision:
        """Tenant-mode admission (lock held; self._offered already
        counted). Same decision ladder as legacy mode, with the class
        resolved first so *every* outcome — including refusals — is
        attributed to exactly one class."""
        st, valid = self._class_for(meta)
        st.offered += 1
        if not valid:
            return self._refuse("bad_tenant", st)
        if self._closed:
            return self._refuse("shutdown", st)
        expiry = self._expiry_from(meta, now, st.deadline_ms)
        victims: List[Any] = []
        victim_cause = None
        if self.shed_policy == "deadline-drop":
            victims = self._purge_expired(now)
            if victims:
                victim_cause = "deadline"
        if self.max_inflight and \
                self._total_depth() + self._inflight >= self.max_inflight:
            d = self._refuse("inflight_full", st)
            d.victims, d.victim_cause = victims, victim_cause
            return d
        bound = self._class_bound(st)
        if len(st.q) >= bound:
            # the class is over its share: under reject-oldest it
            # displaces ITS OWN oldest entry (never another tenant's);
            # otherwise the arrival is refused. Either way the cause is
            # tenant_over_share — a flood only ever exhausts its share.
            if self.shed_policy == "reject-oldest" and st.q:
                victim, _, _ = st.q.popleft()
                victims.append(victim)
                victim_cause = "tenant_over_share"
                st.shed["tenant_over_share"] = \
                    st.shed.get("tenant_over_share", 0) + 1
                self._shed["tenant_over_share"] = \
                    self._shed.get("tenant_over_share", 0) + 1
            else:
                d = self._refuse("tenant_over_share", st)
                d.victims, d.victim_cause = victims, victim_cause
                return d
        elif self._total_depth() >= self.max_pending:
            # global bound (shared headroom exhausted even though this
            # class is within its share) — refuse, never displace
            # another class's entries
            d = self._refuse("queue_full", st)
            d.victims, d.victim_cause = victims, victim_cause
            return d
        self._admitted += 1
        st.admitted += 1
        if not st.q:                      # class goes backlogged: SFQ
            st.vt = max(st.vt, self._vnow)
        st.q.append((item, now, expiry))
        if isinstance(meta, dict):
            meta[CLASS_META] = st.name
            stamp_hop(meta, "admit", depth=self._total_depth(),
                      tenant=st.name)
        if len(st.q) > st.depth_peak:
            st.depth_peak = len(st.q)
        total = self._total_depth()
        if total > self._depth_peak:
            self._depth_peak = total
        self._cv.notify()
        return AdmissionDecision(
            admitted=True, queue_depth=total,
            retry_after_ms=self._retry_after_locked(),
            victims=victims, victim_cause=victim_cause)

    @staticmethod
    def _expiry_from(meta, now: float,
                     default_ms: Optional[float]) -> Optional[float]:
        budget = None
        if isinstance(meta, dict):
            b = meta.get(DEADLINE_META)
            if isinstance(b, (int, float)) and b > 0:
                budget = float(b)
        if budget is None and default_ms is not None:
            budget = float(default_ms)
        return None if budget is None else now + budget / 1e3

    def _refuse(self, cause: str,
                st: Optional[_ClassState] = None) -> AdmissionDecision:
        self._rejected[cause] = self._rejected.get(cause, 0) + 1
        if st is not None:
            st.rejected[cause] = st.rejected.get(cause, 0) + 1
        return AdmissionDecision(
            admitted=False, cause=cause, queue_depth=self._total_depth(),
            retry_after_ms=self._retry_after_locked())

    def _purge_expired(self, now: float) -> List[Any]:
        """deadline-drop: shed queued entries whose budget has passed.
        Expired work is wasted work — purge on every offer, not only
        when full."""
        victims = []
        kept = deque()
        for item, enq_t, expiry in self._q:
            if expiry is not None and expiry <= now:
                victims.append(item)
            else:
                kept.append((item, enq_t, expiry))
        if victims:
            self._q = kept
            self._shed["deadline"] = \
                self._shed.get("deadline", 0) + len(victims)
        for st in self._classes.values():
            if not st.q:
                continue
            mine = []
            ckept: deque = deque()
            for item, enq_t, expiry in st.q:
                if expiry is not None and expiry <= now:
                    mine.append(item)
                else:
                    ckept.append((item, enq_t, expiry))
            if mine:
                st.q = ckept
                st.shed["deadline"] = \
                    st.shed.get("deadline", 0) + len(mine)
                self._shed["deadline"] = \
                    self._shed.get("deadline", 0) + len(mine)
                victims.extend(mine)
        return victims

    def _shrink_to_bound_locked(self) -> List[Any]:
        """Mid-stream ``max_pending`` shrink (lock held; called from
        configure()). Only ``reject-oldest`` displaces queued work, so
        only that policy sheds here — the oldest excess entries go
        first, mirroring what the policy does on a full-queue offer.
        Teardown sentinels (`None` rides the legacy queue via
        put_nowait) are never evicted. Every victim is counted exactly
        once — globally and, in tenant mode, on the class that owned
        it — so ``admitted == replied + shed + depth + inflight``
        stays exact through the change."""
        if getattr(self, "shed_policy", None) != "reject-oldest":
            return []
        shed = getattr(self, "_shed", None)
        if shed is None:      # __init__-time configure: queue is empty
            return []
        victims: List[Any] = []
        # tenant mode: trim each class to its recomputed fair-share
        # bound (the global bound re-shares live through _class_bound)
        if self._table is not None:
            for st in self._classes.values():
                bound = self._class_bound(st)
                while len(st.q) > bound:
                    item, _, _ = st.q.popleft()
                    victims.append(item)
                    st.shed["bound_shrink"] = \
                        st.shed.get("bound_shrink", 0) + 1
                    shed["bound_shrink"] = \
                        shed.get("bound_shrink", 0) + 1
        # legacy queue: trim the global excess, oldest first,
        # skipping sentinels
        excess = self._total_depth() - self.max_pending
        if excess > 0 and self._q:
            kept: deque = deque()
            for entry in self._q:
                if excess > 0 and entry[0] is not None:
                    victims.append(entry[0])
                    shed["bound_shrink"] = \
                        shed.get("bound_shrink", 0) + 1
                    excess -= 1
                else:
                    kept.append(entry)
            self._q = kept
        return victims

    def _retry_after_locked(self) -> float:
        """Suggested client backoff: expected time for the current queue
        to drain at the EWMA service rate, clamped to [1ms, 10s].

        Cold start: before the first reply lands the EWMA has no
        samples — a freshly joined host must still advertise a finite,
        positive hint (a zero/degenerate backoff would turn every BUSY
        into an immediate-retry hot loop against the emptiest host in
        the mesh), so the default and a non-finite/non-positive EWMA
        both fall back to `_DEFAULT_RETRY_MS`."""
        ewma = self._ewma_reply_s
        if ewma is None or not math.isfinite(ewma) or ewma <= 0.0:
            return _DEFAULT_RETRY_MS
        est = (self._total_depth() + 1) * ewma * 1e3
        if not math.isfinite(est):
            return 10_000.0
        return min(max(est, 1.0), 10_000.0)

    # -- queue.Queue-compatible consumer side ------------------------------
    def get(self, timeout: Optional[float] = None):
        """Blocking dequeue; raises `queue.Empty` on timeout (drop-in
        for the previous `queue.Queue` drain loops). A dequeued request
        becomes *inflight* until `note_replied`/`note_failed`. In
        tenancy mode the backlogged class with the smallest virtual
        time is served (weighted fair)."""
        with self._cv:
            if not self._cv.wait_for(lambda: self._total_depth() > 0,
                                     timeout=timeout):
                raise _queue.Empty
            if self._q:               # legacy queue / teardown sentinels
                item, _, _ = self._q.popleft()
            else:
                item = self._dequeue_fair_locked()
            if item is not None:          # None = teardown sentinel
                self._inflight += 1
                meta = getattr(item, "meta", None)
                if self._table is not None and isinstance(meta, dict):
                    st = self._classes.get(meta.get(CLASS_META, ""))
                    if st is not None:
                        st.inflight += 1
                stamp_hop(meta, "dequeue")
            return item

    def _dequeue_fair_locked(self):
        """SFQ pick: min virtual time among backlogged classes; the
        served class is charged 1/weight so higher-weight classes are
        picked proportionally more often over any backlogged period."""
        st = min((s for s in self._classes.values() if s.q),
                 key=lambda s: (s.vt, s.name))
        item, _, _ = st.q.popleft()
        self._vnow = st.vt
        st.vt += 1.0 / max(st.weight, 1e-9)
        return item

    def put_nowait(self, item) -> None:
        """Sentinel bypass: enqueue without admission accounting. Used
        for `None` teardown wakeups, which must never be refused or lost
        to a full queue (the seed's `queue.Full` drop left `generate()`
        blocked forever)."""
        with self._cv:
            self._q.append((item, time.monotonic(), None))
            self._cv.notify()

    # -- completion accounting ---------------------------------------------
    def note_replied(self, cls: Optional[str] = None) -> None:
        """One admitted request answered (RESULT sent, or attempted —
        a vanished client still counts as served). `cls` is the value
        the offer stamped into ``meta["_tenant_class"]``; pass it
        whenever tenancy is enabled so the per-class invariant stays
        exact."""
        now = time.monotonic()
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            self._replied += 1
            st = self._class_done_locked(cls)
            if st is not None:
                st.replied += 1
            if self._last_reply_t is not None:
                dt = now - self._last_reply_t
                self._ewma_reply_s = dt if self._ewma_reply_s is None \
                    else 0.8 * self._ewma_reply_s + 0.2 * dt
            self._last_reply_t = now

    def note_failed(self, cause: str = "dispatch_error",
                    cls: Optional[str] = None) -> None:
        """One dequeued request failed before a RESULT could be sent —
        counts as shed so conservation still balances; the caller owes
        the client a BUSY with the same cause."""
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            self._shed[cause] = self._shed.get(cause, 0) + 1
            st = self._class_done_locked(cls)
            if st is not None:
                st.shed[cause] = st.shed.get(cause, 0) + 1

    def _class_done_locked(self, cls: Optional[str]):
        """Per-class inflight release for a completion (lock held).
        With tenancy on, a completion with no class (a request admitted
        before set_tenants, or a caller that lost the meta) lands on
        the default class — global counters stay exact either way."""
        if self._table is None:
            return None
        if cls is None or cls not in self._classes:
            cls = self._table.default
        st = self._class_state(cls)
        st.inflight = max(0, st.inflight - 1)
        return st

    def shed_remaining(self, cause: str = "shutdown") -> List[Any]:
        """Drain every queued request (at close): they are shed with
        `cause`, returned so the caller can send each a BUSY reply, and
        further offers are refused with the same cause."""
        with self._cv:
            self._closed = True
            victims = [item for item, _, _ in self._q if item is not None]
            self._q.clear()
            for st in self._classes.values():
                if st.q:
                    mine = [item for item, _, _ in st.q]
                    st.q.clear()
                    st.shed[cause] = st.shed.get(cause, 0) + len(mine)
                    victims.extend(mine)
            if victims:
                self._shed[cause] = \
                    self._shed.get(cause, 0) + len(victims)
            self._cv.notify_all()
            return victims

    def reopen(self) -> None:
        """Undo `shed_remaining`'s closed latch (tests / restart)."""
        with self._lock:
            self._closed = False

    # -- introspection ------------------------------------------------------
    @property
    def depth(self) -> int:
        with self._lock:
            return self._total_depth()

    def counters(self) -> Dict[str, Any]:
        """Consistent snapshot of the accounting state (one lock hold).
        With tenancy enabled, ``classes`` maps each class name to the
        same shape of counters scoped to that class (plus its weight
        and effective bound); per-class values sum to the globals."""
        with self._lock:
            out = {
                "offered": self._offered,
                "admitted": self._admitted,
                "replied": self._replied,
                "rejected": dict(self._rejected),
                "shed": dict(self._shed),
                "depth": self._total_depth(),
                "inflight": self._inflight,
                "depth_peak": self._depth_peak,
                "max_pending": self.max_pending,
                "max_inflight": self.max_inflight,
                "shed_policy": self.shed_policy,
                # measured per-reply interval (the autotuner's
                # Little's-law service-rate sensor); None until the
                # second reply lands
                "ewma_reply_s": self._ewma_reply_s,
            }
            if self._table is not None:
                out["classes"] = {
                    st.name: {
                        "offered": st.offered,
                        "admitted": st.admitted,
                        "replied": st.replied,
                        "rejected": dict(st.rejected),
                        "shed": dict(st.shed),
                        "depth": len(st.q),
                        "inflight": st.inflight,
                        "depth_peak": st.depth_peak,
                        "weight": st.weight,
                        "max_pending": (
                            st.max_pending
                            if st.name == INVALID_CLASS
                            else self._class_bound(st)),
                        "deadline_ms": st.deadline_ms,
                    }
                    for st in self._classes.values()
                }
            return out
