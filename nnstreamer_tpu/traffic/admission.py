"""Bounded admission queue — load shedding at the serving edge.

The reference's query server buffers unboundedly and collapses under
overload (every request eventually times out, goodput → 0). Real
serving edges shed instead: a bounded queue admits up to `max_pending`
requests, refuses the rest with a *typed* rejection the client can act
on (wire `BUSY`, edge/protocol.py), and keeps per-cause counters so the
operator can see exactly what was shed and why.

Policy knobs:

- ``max_pending``   — bound on queued-but-not-yet-dequeued requests.
- ``max_inflight``  — bound on total outstanding requests (queued +
  dequeued-but-not-yet-replied); 0 = unlimited. This caps end-to-end
  concurrency/memory, not just the queue.
- ``shed_policy``   — what happens when the queue is full:
    * ``reject-newest`` (default): refuse the arriving request. FIFO
      fairness; the cheapest policy (nothing admitted is ever wasted).
    * ``reject-oldest``: admit the arrival, shed the oldest *queued*
      request (which has waited longest and is most likely to miss its
      deadline anyway). The victim still gets a BUSY reply — nothing is
      ever silently dropped.
    * ``deadline-drop``: requests carrying a ``meta["deadline_ms"]``
      budget are purged once the budget expires (measured from arrival,
      so no cross-host clock agreement is needed); a full queue with no
      expired entries falls back to reject-newest.

Accounting contract (the conservation invariant tests assert):

    offered  == admitted + sum(rejected.values())
    admitted == replied + sum(shed.values()) + depth + inflight

``rejected`` counts at-the-door refusals (never entered the queue);
``shed`` counts post-admission victims (reject-oldest, deadline purge,
shutdown drain, dispatch errors). Both reach the client as BUSY.

The queue doubles as the serversrc's frame source: ``get()`` is
``queue.Queue``-compatible (blocking, raises ``queue.Empty`` on
timeout) so it drops into the existing drain loops, and ``None``
sentinels pushed via ``put_nowait`` bypass admission entirely (they are
teardown wakeups, not requests — and must never be lost to a full
queue).
"""

from __future__ import annotations

import math
import queue as _queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from nnstreamer_tpu.runtime.tracing import stamp_hop

SHED_POLICIES = ("reject-newest", "reject-oldest", "deadline-drop")

#: TensorBuffer.meta key: per-request latency budget in ms, measured
#: from server-side arrival (deadline-drop purges expired entries)
DEADLINE_META = "deadline_ms"

#: retry-after suggestion before any service-rate estimate exists
_DEFAULT_RETRY_MS = 50.0


@dataclass
class AdmissionDecision:
    """Outcome of one `offer()`: admitted or not, why not, and any
    previously-admitted victims the caller must send BUSY replies for
    (reject-oldest / deadline purge)."""

    admitted: bool
    cause: Optional[str] = None          # rejection cause when refused
    queue_depth: int = 0
    retry_after_ms: float = _DEFAULT_RETRY_MS
    victims: List[Any] = field(default_factory=list)
    victim_cause: Optional[str] = None   # cause for the victims' BUSY


class AdmissionQueue:
    """Bounded request queue with typed rejection (module docstring)."""

    def __init__(self, max_pending: int = 64, max_inflight: int = 0,
                 shed_policy: str = "reject-newest"):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._q: deque = deque()          # (item, enq_t, expiry_or_None)
        self.configure(max_pending=max_pending, max_inflight=max_inflight,
                       shed_policy=shed_policy)
        self._inflight = 0
        self._closed = False
        # counters (all mutated under _lock)
        self._offered = 0
        self._admitted = 0
        self._replied = 0
        self._rejected: Dict[str, int] = {}
        self._shed: Dict[str, int] = {}
        self._depth_peak = 0
        # EWMA of inter-reply interval → retry-after suggestion
        self._ewma_reply_s: Optional[float] = None
        self._last_reply_t: Optional[float] = None

    def configure(self, max_pending: Optional[int] = None,
                  max_inflight: Optional[int] = None,
                  shed_policy: Optional[str] = None) -> None:
        """Re-knob a live queue (serversrc applies its properties at
        start(); the process-wide QueryServer is created earlier with
        defaults)."""
        with self._lock:
            if max_pending is not None:
                if max_pending < 1:
                    raise ValueError(
                        f"max_pending must be >= 1, got {max_pending}")
                self.max_pending = max_pending
            if max_inflight is not None:
                if max_inflight < 0:
                    raise ValueError(
                        f"max_inflight must be >= 0 (0 = unlimited), "
                        f"got {max_inflight}")
                self.max_inflight = max_inflight
            if shed_policy is not None:
                if shed_policy not in SHED_POLICIES:
                    raise ValueError(
                        f"shed_policy must be one of "
                        f"{' | '.join(SHED_POLICIES)}, got {shed_policy!r}")
                self.shed_policy = shed_policy

    # -- admission ---------------------------------------------------------
    def offer(self, item, now: Optional[float] = None) -> AdmissionDecision:
        """Admit `item` or return a typed refusal. Never blocks."""
        if now is None:
            now = time.monotonic()
        expiry = None
        meta = getattr(item, "meta", None)
        if isinstance(meta, dict):
            budget = meta.get(DEADLINE_META)
            if isinstance(budget, (int, float)) and budget > 0:
                expiry = now + float(budget) / 1e3
        with self._cv:
            self._offered += 1
            if self._closed:
                return self._refuse("shutdown")
            victims: List[Any] = []
            victim_cause = None
            if self.shed_policy == "deadline-drop":
                victims = self._purge_expired(now)
                if victims:
                    victim_cause = "deadline"
            if self.max_inflight and \
                    len(self._q) + self._inflight >= self.max_inflight:
                d = self._refuse("inflight_full")
                d.victims, d.victim_cause = victims, victim_cause
                return d
            if len(self._q) >= self.max_pending:
                if self.shed_policy == "reject-oldest":
                    victim, _, _ = self._q.popleft()
                    victims.append(victim)
                    victim_cause = "reject_oldest"
                    self._shed["reject_oldest"] = \
                        self._shed.get("reject_oldest", 0) + 1
                else:      # reject-newest, or deadline-drop w/o expiries
                    d = self._refuse("queue_full")
                    d.victims, d.victim_cause = victims, victim_cause
                    return d
            self._admitted += 1
            self._q.append((item, now, expiry))
            if isinstance(meta, dict):
                stamp_hop(meta, "admit", depth=len(self._q))
            if len(self._q) > self._depth_peak:
                self._depth_peak = len(self._q)
            self._cv.notify()
            return AdmissionDecision(
                admitted=True, queue_depth=len(self._q),
                retry_after_ms=self._retry_after_locked(),
                victims=victims, victim_cause=victim_cause)

    def _refuse(self, cause: str) -> AdmissionDecision:
        self._rejected[cause] = self._rejected.get(cause, 0) + 1
        return AdmissionDecision(
            admitted=False, cause=cause, queue_depth=len(self._q),
            retry_after_ms=self._retry_after_locked())

    def _purge_expired(self, now: float) -> List[Any]:
        """deadline-drop: shed queued entries whose budget has passed.
        Expired work is wasted work — purge on every offer, not only
        when full."""
        victims = []
        kept = deque()
        for item, enq_t, expiry in self._q:
            if expiry is not None and expiry <= now:
                victims.append(item)
            else:
                kept.append((item, enq_t, expiry))
        if victims:
            self._q = kept
            self._shed["deadline"] = \
                self._shed.get("deadline", 0) + len(victims)
        return victims

    def _retry_after_locked(self) -> float:
        """Suggested client backoff: expected time for the current queue
        to drain at the EWMA service rate, clamped to [1ms, 10s].

        Cold start: before the first reply lands the EWMA has no
        samples — a freshly joined host must still advertise a finite,
        positive hint (a zero/degenerate backoff would turn every BUSY
        into an immediate-retry hot loop against the emptiest host in
        the mesh), so the default and a non-finite/non-positive EWMA
        both fall back to `_DEFAULT_RETRY_MS`."""
        ewma = self._ewma_reply_s
        if ewma is None or not math.isfinite(ewma) or ewma <= 0.0:
            return _DEFAULT_RETRY_MS
        est = (len(self._q) + 1) * ewma * 1e3
        if not math.isfinite(est):
            return 10_000.0
        return min(max(est, 1.0), 10_000.0)

    # -- queue.Queue-compatible consumer side ------------------------------
    def get(self, timeout: Optional[float] = None):
        """Blocking dequeue; raises `queue.Empty` on timeout (drop-in
        for the previous `queue.Queue` drain loops). A dequeued request
        becomes *inflight* until `note_replied`/`note_failed`."""
        with self._cv:
            if not self._cv.wait_for(lambda: len(self._q) > 0,
                                     timeout=timeout):
                raise _queue.Empty
            item, _, _ = self._q.popleft()
            if item is not None:          # None = teardown sentinel
                self._inflight += 1
                stamp_hop(getattr(item, "meta", None), "dequeue")
            return item

    def put_nowait(self, item) -> None:
        """Sentinel bypass: enqueue without admission accounting. Used
        for `None` teardown wakeups, which must never be refused or lost
        to a full queue (the seed's `queue.Full` drop left `generate()`
        blocked forever)."""
        with self._cv:
            self._q.append((item, time.monotonic(), None))
            self._cv.notify()

    # -- completion accounting ---------------------------------------------
    def note_replied(self) -> None:
        """One admitted request answered (RESULT sent, or attempted —
        a vanished client still counts as served)."""
        now = time.monotonic()
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            self._replied += 1
            if self._last_reply_t is not None:
                dt = now - self._last_reply_t
                self._ewma_reply_s = dt if self._ewma_reply_s is None \
                    else 0.8 * self._ewma_reply_s + 0.2 * dt
            self._last_reply_t = now

    def note_failed(self, cause: str = "dispatch_error") -> None:
        """One dequeued request failed before a RESULT could be sent —
        counts as shed so conservation still balances; the caller owes
        the client a BUSY with the same cause."""
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            self._shed[cause] = self._shed.get(cause, 0) + 1

    def shed_remaining(self, cause: str = "shutdown") -> List[Any]:
        """Drain every queued request (at close): they are shed with
        `cause`, returned so the caller can send each a BUSY reply, and
        further offers are refused with the same cause."""
        with self._cv:
            self._closed = True
            victims = [item for item, _, _ in self._q if item is not None]
            self._q.clear()
            if victims:
                self._shed[cause] = \
                    self._shed.get(cause, 0) + len(victims)
            self._cv.notify_all()
            return victims

    def reopen(self) -> None:
        """Undo `shed_remaining`'s closed latch (tests / restart)."""
        with self._lock:
            self._closed = False

    # -- introspection ------------------------------------------------------
    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    def counters(self) -> Dict[str, Any]:
        """Consistent snapshot of the accounting state (one lock hold)."""
        with self._lock:
            return {
                "offered": self._offered,
                "admitted": self._admitted,
                "replied": self._replied,
                "rejected": dict(self._rejected),
                "shed": dict(self._shed),
                "depth": len(self._q),
                "inflight": self._inflight,
                "depth_peak": self._depth_peak,
                "max_pending": self.max_pending,
                "max_inflight": self.max_inflight,
                "shed_policy": self.shed_policy,
            }
