"""Production serving edge: admission control + open-loop traffic.

The among-device layer (edge/) gives the wire; this package gives the
discipline real traffic forces onto it:

- admission.py — bounded admission queue with typed rejection (wire
  BUSY), shed policies (reject-newest | reject-oldest | deadline-drop),
  and exhaustive per-cause accounting (nothing is ever silently lost).
- loadgen.py   — open-loop Poisson / bursty (Markov-modulated on/off)
  load harness with a latency-SLO report: goodput at a p99 budget,
  shed rate, queue-depth timeline. `run_against_mesh` floods a
  multi-host MeshRouter while a host is partitioned mid-flood;
  `run_multitenant` / `noisy_neighbor_drill` flood a weighted-fair
  multi-tenant pool and report per-tenant isolation.
- netchaos.py  — deterministic seeded network fault injection
  (delay/drop/duplicate/blackhole/slow-close) at message granularity,
  between any two query-wire peers.

Surfaces: `tensor_query_serversrc` admission properties (max_pending /
max_inflight / shed_policy), `tensor_query_client` BUSY backpressure
through the element error-policy machinery, `python -m nnstreamer_tpu
traffic`, and `bench.py --family traffic`. See docs/traffic.md.
"""

from nnstreamer_tpu.traffic.admission import (
    DEADLINE_META, SHED_POLICIES, AdmissionDecision, AdmissionQueue)
from nnstreamer_tpu.traffic.loadgen import (
    EchoServer, MeshWorld, bursty_arrivals, conservation_ok,
    diurnal_arrivals, flash_crowd_arrivals, merge_tenant_arrivals,
    noisy_neighbor_drill, poisson_arrivals, replay_report,
    run_against_echo, run_against_mesh, run_against_pool,
    run_autotune_ramp, run_multitenant, run_open_loop,
    schedule_worker_kills, tenant_conservation_ok)
from nnstreamer_tpu.traffic.netchaos import ChaosProxy

__all__ = [
    "AdmissionDecision",
    "AdmissionQueue",
    "ChaosProxy",
    "DEADLINE_META",
    "SHED_POLICIES",
    "EchoServer",
    "MeshWorld",
    "bursty_arrivals",
    "conservation_ok",
    "diurnal_arrivals",
    "flash_crowd_arrivals",
    "merge_tenant_arrivals",
    "noisy_neighbor_drill",
    "poisson_arrivals",
    "replay_report",
    "run_against_echo",
    "run_against_mesh",
    "run_against_pool",
    "run_autotune_ramp",
    "run_multitenant",
    "run_open_loop",
    "schedule_worker_kills",
    "tenant_conservation_ok",
]
