"""Deterministic network fault injection for the query wire.

`ChaosProxy` sits between a dialing peer (a mesh `HostAgent`, a query
client) and an upstream listener (a `MeshRouter`, any `MsgServer`),
relaying whole protocol messages — it parses the ``u32 type | u32 len``
framing rather than raw bytes, so injected faults corrupt *delivery*,
never *framing* (a dropped frame is a lost message, not a desynced
stream the receiver misparses forever).

Fault model (docs/robustness.md failure matrix):

- ``delay_ms`` / ``jitter_ms`` — per-message latency, applied in-line
  per direction so ordering within a direction is preserved (a slow
  link, not a reordering one).
- ``drop_p`` / ``dup_p`` — per-message loss / duplication.
- ``blackhole()`` / ``heal()`` — a silent partition: both directions
  keep READING and discard (no TCP backpressure, no FIN — exactly the
  failure a lease, not a connection event, must detect). A peer's
  close during the blackhole is withheld from the other side, as a
  real partition would; ``heal()`` drops the poisoned connections so
  the dialing side's reconnect logic rejoins cleanly.
- ``slow_close(linger_s)`` — the anti-blackhole: stop *reading* while
  keeping the connection open, so the sender's kernel buffer fills and
  unbounded ``sendall`` calls wedge (what `Connection.send(timeout=)`
  exists to survive); after the linger everything closes.
- ``program(events)`` — the switches above applied on a schedule
  relative to one clock instant, so a scenario executor (rather than
  ad-hoc caller sleeps) owns WHEN faults land; applied events are
  logged for fence math and replay audits.

Determinism: every per-message decision comes from `random.Random`
streams seeded from (seed, connection index, direction) — same seed,
same traffic, same faults, byte for byte. Handshake types (HELLO,
REGISTER and their acks) are spared from drop/dup by default so a
lossy link still lets peers join; pass ``spare_types=()`` to drop
those too.
"""

from __future__ import annotations

import random
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.edge import protocol as P

log = get_logger("traffic.netchaos")

#: handshake types spared from drop/dup by default (joins survive a
#: lossy link; data-plane loss is what the mesh must absorb)
DEFAULT_SPARE_TYPES = (P.T_HELLO, P.T_HELLO_ACK, P.T_HELLO_NAK,
                       P.T_REGISTER, P.T_REGISTER_ACK)


class _Route:
    """One proxied connection: the accepted downstream socket and its
    upstream dial, plus the two pump threads."""

    def __init__(self, idx: int, down: socket.socket,
                 up: socket.socket):
        self.idx = idx
        self.down = down
        self.up = up
        self.threads: List[threading.Thread] = []
        self.closed = threading.Event()

    def close(self) -> None:
        if self.closed.is_set():
            return
        self.closed.set()
        for s in (self.down, self.up):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class ChaosProxy:
    """Message-level TCP proxy with seeded fault injection (module
    docstring). `stats()` exposes exact per-fault counters so tests can
    assert determinism, not just survival."""

    def __init__(self, upstream_host: str, upstream_port: int, *,
                 listen_host: str = "127.0.0.1", port: int = 0,
                 seed: int = 0,
                 delay_ms: float = 0.0, jitter_ms: float = 0.0,
                 drop_p: float = 0.0, dup_p: float = 0.0,
                 spare_types: Tuple[int, ...] = DEFAULT_SPARE_TYPES,
                 connect_timeout_s: Optional[float] = None):
        self.upstream = (upstream_host, upstream_port)
        self.seed = seed
        self.delay_ms = delay_ms
        self.jitter_ms = jitter_ms
        self.drop_p = drop_p
        self.dup_p = dup_p
        self.spare_types = tuple(spare_types)
        self.connect_timeout_s = connect_timeout_s \
            if connect_timeout_s is not None \
            else P.DEFAULT_CONNECT_TIMEOUT_S
        self._blackholed = threading.Event()
        self._frozen = threading.Event()   # slow_close: stop reading
        self._stopping = threading.Event()
        self._program = None               # (thread, cancel, done)
        self.program_log: List[dict] = []
        self._lock = threading.Lock()
        self._routes: List[_Route] = []
        self._next_idx = 0
        self.counters: Dict[str, int] = {
            "forwarded": 0, "dropped": 0, "duplicated": 0,
            "delayed": 0, "discarded": 0, "conns": 0}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((listen_host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self.host = listen_host
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"netchaos:{self.port}",
            daemon=True)
        self._accept_thread.start()

    # -- fault switches ----------------------------------------------------
    def blackhole(self) -> None:
        """Silent partition: traffic in both directions is read and
        discarded; no FIN crosses the proxy. Undo with `heal()`."""
        self._blackholed.set()
        log.info("netchaos:%d blackholed", self.port)

    def heal(self) -> None:
        """End the partition AND drop the poisoned connections — both
        peers see a clean close and the dialing side's reconnect logic
        takes it from there (resuming mid-stream after arbitrary loss
        would hand each peer a gap it cannot detect)."""
        self._blackholed.clear()
        with self._lock:
            routes = list(self._routes)
        for r in routes:
            r.close()
        log.info("netchaos:%d healed (%d connection(s) reset)",
                 self.port, len(routes))

    @property
    def blackholed(self) -> bool:
        return self._blackholed.is_set()

    def slow_close(self, linger_s: float = 0.5) -> None:
        """Stop draining both directions without closing, so senders
        hit TCP backpressure; close everything after `linger_s`."""
        self._frozen.set()
        log.info("netchaos:%d slow-close (linger %.2fs)", self.port,
                 linger_s)

        def finish():
            time.sleep(linger_s)
            with self._lock:
                routes = list(self._routes)
            for r in routes:
                r.close()
            self._frozen.clear()

        threading.Thread(target=finish, name="netchaos-slow-close",
                         daemon=True).start()

    # -- scheduled fault programs ------------------------------------------
    #: switch ops a program may apply; slow_close takes the linger arg
    PROGRAM_OPS = ("blackhole", "heal", "slow_close")

    def program(self, events, *, t0: Optional[float] = None) -> None:
        """Apply fault switches at scenario-clock offsets: ``events``
        is a list of ``(t_s, op)`` or ``(t_s, op, arg)`` with op in
        `PROGRAM_OPS` and t_s seconds relative to ``t0`` (a
        `time.monotonic` instant; default: now). One scheduler thread
        sleeps to each offset and flips the switch — callers stop
        hand-rolling Timer/sleep choreography and the executor
        (scenario/executor.py) owns the clock.

        Only the switches move; per-message fault decisions still come
        from the per-(seed, connection, direction) RNG streams, drawn
        for every message in fixed order — a scheduled program does not
        perturb where an existing seed places its drops.

        Applied events land in `program_log` as
        ``{"t_s", "op", "applied_monotonic"}`` rows, the ground truth
        for fence-detection math and replay audits."""
        evs = []
        for ev in events:
            if len(ev) == 2:
                t_s, op = ev
                arg = None
            elif len(ev) == 3:
                t_s, op, arg = ev
            else:
                raise ValueError(f"program event must be (t_s, op[, arg]),"
                                 f" got {ev!r}")
            if op not in self.PROGRAM_OPS:
                raise ValueError(
                    f"unknown program op {op!r}; expected one of "
                    f"{self.PROGRAM_OPS}")
            if float(t_s) < 0:
                raise ValueError(f"program offset must be >= 0, got {t_s}")
            evs.append((float(t_s), op, arg))
        evs.sort(key=lambda e: e[0])
        self.cancel_program()
        start = time.monotonic() if t0 is None else float(t0)
        cancel = threading.Event()
        done = threading.Event()

        def run():
            try:
                for t_s, op, arg in evs:
                    wait = start + t_s - time.monotonic()
                    if wait > 0 and cancel.wait(wait):
                        return
                    if cancel.is_set() or self._stopping.is_set():
                        return
                    if op == "blackhole":
                        self.blackhole()
                    elif op == "heal":
                        self.heal()
                    else:
                        self.slow_close(arg if arg is not None else 0.5)
                    with self._lock:
                        self.program_log.append({
                            "t_s": round(t_s, 3), "op": op,
                            "applied_monotonic": time.monotonic()})
            finally:
                done.set()

        t = threading.Thread(target=run, name=f"netchaos-prog:{self.port}",
                             daemon=True)
        self._program = (t, cancel, done)
        t.start()

    def cancel_program(self) -> None:
        """Stop a running program; already-applied switches stay."""
        prog = getattr(self, "_program", None)
        if prog is None:
            return
        t, cancel, done = prog
        cancel.set()
        done.set()
        t.join(timeout=2)
        self._program = None

    def wait_program(self, timeout_s: float = 10.0) -> bool:
        """Block until the current program applied its last event (or
        was cancelled). True if it finished within the timeout."""
        prog = getattr(self, "_program", None)
        if prog is None:
            return True
        return prog[2].wait(timeout_s)

    def applied(self, op: str) -> Optional[float]:
        """Monotonic instant the program FIRST applied `op` (None if
        not yet) — e.g. the blackhole instant fence math measures from."""
        with self._lock:
            for row in self.program_log:
                if row["op"] == op:
                    return row["applied_monotonic"]
        return None

    def set_faults(self, *, delay_ms: Optional[float] = None,
                   jitter_ms: Optional[float] = None,
                   drop_p: Optional[float] = None,
                   dup_p: Optional[float] = None) -> None:
        if delay_ms is not None:
            self.delay_ms = delay_ms
        if jitter_ms is not None:
            self.jitter_ms = jitter_ms
        if drop_p is not None:
            self.drop_p = drop_p
        if dup_p is not None:
            self.dup_p = dup_p

    # -- relay -------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                down, _addr = self._sock.accept()
            except OSError:
                return
            try:
                up = socket.create_connection(
                    self.upstream, timeout=self.connect_timeout_s)
                up.settimeout(None)
            except OSError as e:
                log.warning("netchaos:%d upstream dial failed: %s",
                            self.port, e)
                try:
                    down.close()
                except OSError:
                    pass
                continue
            for s in (down, up):
                try:
                    s.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
                except OSError:
                    pass
            with self._lock:
                idx = self._next_idx
                self._next_idx += 1
                route = _Route(idx, down, up)
                self._routes.append(route)
                self.counters["conns"] += 1
            for dirn, src, dst in (("c2u", down, up),
                                   ("u2c", up, down)):
                t = threading.Thread(
                    target=self._pump, args=(route, dirn, src, dst),
                    name=f"netchaos:{self.port}:{idx}:{dirn}",
                    daemon=True)
                route.threads.append(t)
                t.start()

    def _pump(self, route: _Route, dirn: str, src: socket.socket,
              dst: socket.socket) -> None:
        # one RNG stream per (seed, connection, direction): decisions
        # are drawn for EVERY message in a fixed order, so fault
        # placement is reproducible independent of which faults are on
        rng = random.Random(f"{self.seed}:{route.idx}:{dirn}")
        lock = threading.Lock()
        while not self._stopping.is_set() and not route.closed.is_set():
            if self._frozen.is_set():
                time.sleep(0.01)      # slow_close: stop draining src
                continue
            try:
                msg = P.read_msg(src)
            except Exception:
                msg = None
            if msg is None:
                # src closed. During a blackhole the FIN must NOT
                # propagate — the far side keeps its half open until
                # heal(), like a real partition
                if not self._blackholed.is_set():
                    route.close()
                return
            mtype, payload = msg
            r_drop = rng.random()
            r_dup = rng.random()
            r_jit = rng.random()
            if self._blackholed.is_set():
                with self._lock:
                    self.counters["discarded"] += 1
                continue
            sparable = mtype in self.spare_types
            if self.delay_ms > 0 or self.jitter_ms > 0:
                with self._lock:
                    self.counters["delayed"] += 1
                time.sleep((self.delay_ms + self.jitter_ms * r_jit)
                           / 1e3)
            if not sparable and r_drop < self.drop_p:
                with self._lock:
                    self.counters["dropped"] += 1
                continue
            try:
                P.write_msg(dst, mtype, payload, lock)
                with self._lock:
                    self.counters["forwarded"] += 1
                if not sparable and r_dup < self.dup_p:
                    P.write_msg(dst, mtype, payload, lock)
                    with self._lock:
                        self.counters["duplicated"] += 1
            except OSError:
                if not self._blackholed.is_set():
                    route.close()
                return

    # -- introspection / lifecycle -----------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.counters)
        out["blackholed"] = int(self._blackholed.is_set())
        return out

    def close(self) -> None:
        self._stopping.set()
        self.cancel_program()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            routes = list(self._routes)
        for r in routes:
            r.close()
        for r in routes:
            for t in r.threads:
                t.join(timeout=2)
