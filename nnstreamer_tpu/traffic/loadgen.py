"""Open-loop load harness — Poisson/bursty arrivals + latency-SLO report.

Closed-loop load (send, wait, send) lets a slow server throttle its own
offered load and flatter its tail — the coordinated-omission trap. This
harness is *open-loop*: arrival times are pre-drawn from the arrival
process and every request is sent at its scheduled time regardless of
completions, so overload actually happens and the report shows what the
server did about it.

Arrival processes:

- ``poisson_arrivals(rate, n)``  — memoryless, the standard serving
  baseline (exponential inter-arrivals).
- ``bursty_arrivals(n, ...)``    — Markov-modulated on/off: the source
  alternates between a high-rate and a low-rate state with
  exponentially-distributed dwell times. Same mean rate as a Poisson
  source can carry; the bursts are what break naive admission.
- ``diurnal_arrivals(n, ...)``   — inhomogeneous Poisson whose rate
  swings sinusoidally between a trough and a peak (a day/night load
  curve compressed to seconds).
- ``flash_crowd_arrivals(n, ...)`` — base-rate Poisson until
  ``ramp_at_s``, then a linear ramp to the peak rate over ``ramp_s``
  that stays there: the thundering-herd shape scenario drills
  (scenario/) compose with faults.

Per-request outcome accounting is exhaustive: every sent request ends
as *completed* (RESULT received), *rejected* (typed BUSY received), or
*lost* (neither — a crash or silent drop). A healthy bounded server
under overload reports nonzero ``rejected`` and ZERO ``lost``; the seed
behavior (silent queue drop) shows up as ``lost`` > 0.

SLO report fields (``run_open_loop`` return value): see docs/traffic.md.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.traffic.admission import DEADLINE_META
from nnstreamer_tpu.edge import protocol as P
from nnstreamer_tpu.edge.wire import decode_buffer, encode_buffer
from nnstreamer_tpu.runtime.tracing import (
    ensure_trace_ctx, get_trace_ctx, hop_spans, percentile)
from nnstreamer_tpu.tensor.buffer import TensorBuffer

log = get_logger("traffic.loadgen")


# -- arrival processes -------------------------------------------------------

def poisson_arrivals(rate_hz: float, n: int,
                     rng: Optional[np.random.Generator] = None
                     ) -> np.ndarray:
    """`n` cumulative arrival times (s) of a Poisson process at
    `rate_hz` requests/s."""
    if rate_hz <= 0:
        raise ValueError(f"rate_hz must be > 0, got {rate_hz}")
    rng = rng or np.random.default_rng(0)
    return np.cumsum(rng.exponential(1.0 / rate_hz, size=n))


def bursty_arrivals(n: int, *, rate_high_hz: float, rate_low_hz: float,
                    mean_dwell_s: float = 0.25,
                    rng: Optional[np.random.Generator] = None
                    ) -> np.ndarray:
    """`n` cumulative arrival times of a Markov-modulated on/off
    process: exponential dwell (`mean_dwell_s`) in each state, drawing
    exponential inter-arrivals at that state's rate. Starts in the
    high-rate state."""
    if rate_high_hz <= 0 or rate_low_hz <= 0:
        raise ValueError("both state rates must be > 0")
    rng = rng or np.random.default_rng(0)
    out: List[float] = []
    t = 0.0
    high = True
    state_end = float(rng.exponential(mean_dwell_s))
    while len(out) < n:
        rate = rate_high_hz if high else rate_low_hz
        t += float(rng.exponential(1.0 / rate))
        while t >= state_end:        # dwell expired: flip state
            high = not high
            state_end += float(rng.exponential(mean_dwell_s))
        out.append(t)
    return np.asarray(out)


def _inhomogeneous_arrivals(n: int, rate_of: Callable[[float], float],
                            rng: np.random.Generator) -> np.ndarray:
    """`n` cumulative arrival times of an inhomogeneous Poisson process
    whose instantaneous rate is `rate_of(t)`: each inter-arrival gap is
    drawn exponential at the rate in force when it starts. For rates
    that vary slowly relative to the gap (every program here) this is
    indistinguishable from thinning and stays strictly sequential in
    the rng — one draw per arrival, so seeds replay bit-exact."""
    out: List[float] = []
    t = 0.0
    for _ in range(n):
        t += float(rng.exponential(1.0 / max(rate_of(t), 1e-9)))
        out.append(t)
    return np.asarray(out)


def diurnal_arrivals(n: int, *, peak_hz: float, trough_hz: float,
                     period_s: float = 4.0,
                     rng: Optional[np.random.Generator] = None
                     ) -> np.ndarray:
    """`n` cumulative arrival times whose rate swings sinusoidally
    between `trough_hz` and `peak_hz` with period `period_s`, starting
    at the midpoint on the rising edge."""
    if trough_hz <= 0 or peak_hz < trough_hz:
        raise ValueError(
            f"need 0 < trough_hz <= peak_hz, got {trough_hz}/{peak_hz}")
    if period_s <= 0:
        raise ValueError(f"period_s must be > 0, got {period_s}")
    rng = rng if rng is not None else np.random.default_rng(0)
    mid = (peak_hz + trough_hz) / 2.0
    amp = (peak_hz - trough_hz) / 2.0
    return _inhomogeneous_arrivals(
        n, lambda t: mid + amp * float(np.sin(2 * np.pi * t / period_s)),
        rng)


def flash_crowd_arrivals(n: int, *, base_hz: float, peak_hz: float,
                         ramp_at_s: float, ramp_s: float = 0.5,
                         rng: Optional[np.random.Generator] = None
                         ) -> np.ndarray:
    """`n` cumulative arrival times of a flash crowd: Poisson at
    `base_hz` until `ramp_at_s`, then a linear rate ramp to `peak_hz`
    over `ramp_s` that never comes back down."""
    if base_hz <= 0 or peak_hz < base_hz:
        raise ValueError(
            f"need 0 < base_hz <= peak_hz, got {base_hz}/{peak_hz}")
    if ramp_at_s < 0 or ramp_s <= 0:
        raise ValueError(
            f"need ramp_at_s >= 0 and ramp_s > 0, got "
            f"{ramp_at_s}/{ramp_s}")
    rng = rng if rng is not None else np.random.default_rng(0)

    def rate_of(t: float) -> float:
        if t < ramp_at_s:
            return base_hz
        frac = min(1.0, (t - ramp_at_s) / ramp_s)
        return base_hz + (peak_hz - base_hz) * frac

    return _inhomogeneous_arrivals(n, rate_of, rng)


# -- open-loop runner --------------------------------------------------------

def run_open_loop(host: str, port: int, *, dims: str,
                  types: str = "float32",
                  arrivals: np.ndarray,
                  make_frame: Callable[[int], TensorBuffer],
                  p99_budget_ms: float = 250.0,
                  drain_timeout_s: float = 15.0,
                  hello_timeout_s: float = 10.0,
                  depth_probe: Optional[Callable[[], int]] = None,
                  depth_sample_ms: float = 25.0,
                  group_of: Optional[Callable[[int], str]] = None,
                  trace: bool = False,
                  collect_traces: bool = False) -> dict:
    """Drive one live query server open-loop; return the SLO report.

    make_frame(i) builds request i's TensorBuffer (its pts is forced to
    i — the pts echo is how outcomes are matched). `depth_probe`, when
    the server is in-process, samples its admission-queue depth on a
    timeline; remote servers still get depth points from every BUSY
    payload.

    ``trace=True`` gives every frame a trace context
    (runtime/tracing.py): the server stack stamps its hops into the
    meta and the reply carries them home, so the report gains a
    ``hop_breakdown`` — the per-stage latency decomposition (admission
    wait / route / worker queue / service / reply) of the worst-p99
    request. The client_send/client_recv hops are recorded LOCALLY
    from the send/complete clocks, not serialized, so the send path
    stays one pre-encoded sendall per frame.
    """
    n = len(arrivals)
    if n == 0:
        raise ValueError("arrivals is empty")
    done: Dict[int, float] = {}      # pts -> completion t
    busy: Dict[int, dict] = {}       # pts -> BUSY payload
    traces: Dict[int, dict] = {}     # pts -> reply trace ctx
    evt_lock = threading.Lock()
    all_answered = threading.Event()
    hello_q: List[tuple] = []
    hello_evt = threading.Event()
    timeline: List[List[float]] = []  # [t_rel_s, depth]
    t0 = [0.0]                        # set when the clock starts

    def on_message(mtype: int, payload: bytes) -> None:
        now = time.perf_counter()
        if mtype in (P.T_HELLO_ACK, P.T_HELLO_NAK):
            hello_q.append((mtype, payload))
            hello_evt.set()
            return
        with evt_lock:
            if mtype == P.T_RESULT:
                try:
                    buf, _ = decode_buffer(payload)
                except ValueError as e:
                    log.warning("loadgen: corrupt result dropped: %s", e)
                    return
                if buf.pts is not None:
                    done[int(buf.pts)] = now
                    if trace:
                        ctx = get_trace_ctx(buf.meta)
                        if ctx:
                            traces[int(buf.pts)] = ctx
            elif mtype == P.T_BUSY:
                try:
                    info = json.loads(payload.decode())
                except ValueError:
                    info = {}
                pts = info.get("pts")
                if pts is not None:
                    busy[int(pts)] = info
                if "queue_depth" in info:
                    timeline.append([now - t0[0],
                                     int(info["queue_depth"])])
            if len(done) + len(busy) >= n:
                all_answered.set()

    client = P.MsgClient(host, port, on_message=on_message)
    try:
        client.send(P.T_HELLO,
                    json.dumps({"dims": dims, "types": types}).encode())
        if not hello_evt.wait(hello_timeout_s):
            raise StreamError(
                f"loadgen: query server {host}:{port} did not answer the "
                f"caps handshake within {hello_timeout_s}s")
        kind, payload = hello_q[0]
        if kind == P.T_HELLO_NAK:
            raise StreamError(
                f"loadgen: server rejected caps: {payload.decode()}")

        # pre-encode every frame: send-time work is one sendall, so the
        # arrival schedule is honored to sub-ms even at high rates
        frames = []
        for i in range(n):
            buf = make_frame(i)
            if trace:
                ensure_trace_ctx(buf.meta)
            frames.append(encode_buffer(
                buf.with_tensors(buf.tensors, pts=i)))

        stop_sampler = threading.Event()
        sampler = None
        t0[0] = time.perf_counter()
        if depth_probe is not None:
            def sample():
                while not stop_sampler.is_set():
                    try:
                        d = int(depth_probe())
                    except Exception:
                        break
                    with evt_lock:
                        timeline.append(
                            [time.perf_counter() - t0[0], d])
                    stop_sampler.wait(depth_sample_ms / 1e3)
            sampler = threading.Thread(target=sample, daemon=True,
                                       name="loadgen-depth")
            sampler.start()

        sent_at: List[float] = []
        send_errors = 0
        for i, t_arr in enumerate(arrivals):
            now = time.perf_counter() - t0[0]
            if t_arr > now:
                time.sleep(t_arr - now)
            sent_at.append(time.perf_counter())
            try:
                client.send(P.T_DATA, frames[i])
            except StreamError:
                send_errors += 1
                break                 # connection died: everything after
                                      # this counts as lost
        # drain: wait for every sent request to resolve (or time out)
        all_answered.wait(drain_timeout_s)
        elapsed = time.perf_counter() - t0[0]
        stop_sampler.set()
        if sampler is not None:
            sampler.join(timeout=2)
    finally:
        client.close()

    with evt_lock:
        n_sent = len(sent_at)
        lat_ms = sorted((done[i] - sent_at[i]) * 1e3
                        for i in list(done) if i < n_sent)
        completed = len(lat_ms)
        rejected = sum(1 for i in busy if i < n_sent)
        causes: Dict[str, int] = {}
        retry_hints = []
        for i, info in busy.items():
            if i >= n_sent:
                continue
            causes[info.get("cause", "?")] = \
                causes.get(info.get("cause", "?"), 0) + 1
            if "retry_after_ms" in info:
                retry_hints.append(float(info["retry_after_ms"]))
        tl = sorted(timeline)
    lost = n_sent - completed - rejected
    within = sum(1 for v in lat_ms if v <= p99_budget_ms)
    # offered rate is a property of the SEND window; elapsed also spans
    # the drain wait, which would understate it for any run that queues
    send_window = (sent_at[-1] - t0[0]) if sent_at else 0.0
    report = {
        "offered": n_sent,
        "completed": completed,
        "rejected": rejected,
        "lost": lost,
        "send_errors": send_errors,
        "duration_s": round(elapsed, 3),
        "offered_rate_rps": round(n_sent / send_window, 2)
        if send_window else 0.0,
        "throughput_rps": round(completed / elapsed, 2) if elapsed else 0.0,
        "goodput_rps": round(within / elapsed, 2) if elapsed else 0.0,
        "within_budget": within,
        "p99_budget_ms": p99_budget_ms,
        "shed_rate": round(rejected / n_sent, 4) if n_sent else 0.0,
        "busy_causes": causes,
    }
    if group_of is not None:
        # per-group outcome partition: pts IS the request index, so
        # group_of(i) attributes every sent request to exactly one
        # group — the same exhaustive completed/rejected/lost
        # accounting as the summed report, just filtered
        groups: Dict[str, dict] = {}
        for i in range(n_sent):
            g = str(group_of(i))
            row = groups.setdefault(g, {
                "offered": 0, "completed": 0, "rejected": 0,
                "lost": 0, "busy_causes": {}, "_lat": []})
            row["offered"] += 1
            if i in done:
                row["completed"] += 1
                row["_lat"].append((done[i] - sent_at[i]) * 1e3)
            elif i in busy:
                row["rejected"] += 1
                cause = busy[i].get("cause", "?")
                row["busy_causes"][cause] = \
                    row["busy_causes"].get(cause, 0) + 1
            else:
                row["lost"] += 1
        for row in groups.values():
            lats = sorted(row.pop("_lat"))
            w = sum(1 for v in lats if v <= p99_budget_ms)
            row["within_budget"] = w
            row["goodput_rps"] = \
                round(w / elapsed, 2) if elapsed else 0.0
            row["shed_rate"] = (
                round(row["rejected"] / row["offered"], 4)
                if row["offered"] else 0.0)
            if lats:
                row["latency_ms"] = {
                    "p50": round(percentile(lats, 50), 2),
                    "p99": round(percentile(lats, 99), 2),
                    "max": round(lats[-1], 2)}
        report["groups"] = groups
    if lat_ms:
        report["latency_ms"] = {
            "p50": round(percentile(lat_ms, 50), 2),
            "p95": round(percentile(lat_ms, 95), 2),
            "p99": round(percentile(lat_ms, 99), 2),
            "max": round(lat_ms[-1], 2)}
    if retry_hints:
        retry_hints.sort()
        report["retry_after_ms_p50"] = round(
            percentile(retry_hints, 50), 1)
    if trace and lat_ms:
        # worst-p99 point: the completed request at the p99 latency
        # rank — decompose ITS end-to-end time by hop, from the trace
        # context its reply carried home
        per = {i: (done[i] - sent_at[i]) * 1e3
               for i in done if i < n_sent}
        p99v = percentile(lat_ms, 99)
        at_p99 = [i for i, v in per.items() if v >= p99v]
        pick = min(at_p99, key=lambda i: per[i]) if at_p99 else None
        if pick is not None:
            hops = [{"hop": "client_send", "t": sent_at[pick],
                     "pid": os.getpid()}]
            hops += list(traces.get(pick, {}).get("hops", []))
            hops.append({"hop": "client_recv", "t": done[pick],
                         "pid": os.getpid()})
            spans = hop_spans(hops)
            report["hop_breakdown"] = {
                "pts": pick,
                "latency_ms": round(per[pick], 2),
                "trace_id": traces.get(pick, {}).get("id"),
                "hops": [h["hop"] for h in
                         sorted(hops, key=lambda h: h.get("t", 0.0))],
                "spans": {k: (round(v, 3) if isinstance(v, float)
                              else v) for k, v in spans.items()},
            }
        report["traced_replies"] = len(traces)
        if collect_traces:
            # raw per-reply trace contexts, keyed by pts — what the
            # scenario property checker needs to prove every replied
            # frame carries the full hop chain
            report["traces"] = {int(i): ctx for i, ctx in traces.items()
                                if i < n_sent}
        # redelivery audit: replies whose trace context carries a
        # router/mesh "reoffer" hop survived a worker death or a host
        # fence — list which workers/hosts each one touched, proving
        # one trace_id spans the whole failover
        redelivered = []
        for i, ctx in traces.items():
            hops = [h for h in ctx.get("hops", [])
                    if isinstance(h, dict)]
            if not any(h.get("hop") == "reoffer" for h in hops):
                continue
            redelivered.append({
                "pts": i,
                "trace_id": ctx.get("id"),
                "hosts": sorted({str(h["host"]) for h in hops
                                 if h.get("hop") == "dispatch"
                                 and "host" in h}),
                "wids": sorted({int(h["wid"]) for h in hops
                                if h.get("hop") == "dispatch"
                                and "wid" in h}),
            })
        report["redelivered"] = len(redelivered)
        report["redelivered_examples"] = redelivered[:3]
    if tl:
        # downsample the timeline to <= 200 points, keep the peak honest
        step = max(1, len(tl) // 200)
        report["queue_depth_peak"] = max(d for _, d in tl)
        report["queue_depth_timeline"] = [
            [round(t, 3), int(d)] for t, d in tl[::step]]
    return report


# -- self-contained server (CLI / bench / tests share it) --------------------

class EchoServer:
    """A live bounded query server with a known service time: serversrc
    → custom filter (sleeps `service_ms`, returns its input) →
    serversink. Capacity is 1000/service_ms rps by construction, which
    is what lets the harness express load as a multiple of capacity."""

    _seq = 0
    _seq_lock = threading.Lock()

    def __init__(self, *, dims: str = "8:1", types: str = "float32",
                 service_ms: float = 5.0, max_pending: int = 16,
                 max_inflight: int = 0,
                 shed_policy: str = "reject-newest", port: int = 0):
        import nnstreamer_tpu as nns
        from nnstreamer_tpu.backends.custom import register_custom_easy

        with EchoServer._seq_lock:
            EchoServer._seq += 1
            self.sid = 9000 + EchoServer._seq
        self.dims, self.types = dims, types
        self.service_ms = service_ms
        model = f"traffic_echo_{self.sid}"
        delay = service_ms / 1e3

        def serve(ts):
            if delay > 0:
                time.sleep(delay)
            return ts

        register_custom_easy(model, serve)
        self.pipe = nns.parse_launch(
            f"tensor_query_serversrc name=src id={self.sid} port={port} "
            f"dims={dims} types={types} max_pending={max_pending} "
            f"max_inflight={max_inflight} shed_policy={shed_policy} ! "
            f"tensor_filter framework=custom model={model} ! "
            f"tensor_query_serversink id={self.sid}")
        self.runner = nns.PipelineRunner(self.pipe).start()
        self.src = self.pipe.get("src")
        self.port = self.src.port

    @property
    def capacity_rps(self) -> float:
        return 1e3 / self.service_ms if self.service_ms > 0 else 1e6

    def admission_counters(self) -> dict:
        return self.src.admission_counters()

    def depth_probe(self) -> int:
        from nnstreamer_tpu.edge.query import QueryServer

        return QueryServer.get(self.sid).frames.depth

    def crashed(self) -> bool:
        return self.runner._error is not None

    def stop(self) -> None:
        from nnstreamer_tpu.backends.custom import unregister_custom_easy

        try:
            self.runner.stop()
        finally:
            unregister_custom_easy(f"traffic_echo_{self.sid}")


def run_against_echo(*, pattern: str = "poisson", load_x: float = 2.0,
                     n: int = 200, service_ms: float = 5.0,
                     max_pending: int = 16, max_inflight: int = 0,
                     shed_policy: str = "reject-newest",
                     p99_budget_ms: Optional[float] = None,
                     seed: int = 0, trace: bool = False) -> dict:
    """One self-contained harness run: bounded echo server + open-loop
    load at `load_x` × its capacity. The shape bench/CLI/tests share."""
    rng = np.random.default_rng(seed)
    srv = EchoServer(service_ms=service_ms, max_pending=max_pending,
                     max_inflight=max_inflight, shed_policy=shed_policy)
    try:
        rate = load_x * srv.capacity_rps
        if pattern == "poisson":
            arrivals = poisson_arrivals(rate, n, rng)
        elif pattern == "bursty":
            arrivals = bursty_arrivals(
                n, rate_high_hz=2 * rate, rate_low_hz=max(rate / 4, 0.5),
                rng=rng)
        else:
            raise ValueError(
                f"pattern must be poisson|bursty, got {pattern!r}")
        if p99_budget_ms is None:
            # budget: full queue's worth of waiting + one service time
            p99_budget_ms = (max_pending + 2) * service_ms
        x = np.ones((8, 1), np.float32)

        def make_frame(i):
            buf = TensorBuffer.of(x, pts=i)
            if shed_policy == "deadline-drop":
                # deadline-drop only purges frames that carry a budget;
                # without this stamp the policy silently degrades to
                # reject-newest in the harness
                buf = buf.with_meta(**{DEADLINE_META: p99_budget_ms})
            return buf

        report = run_open_loop(
            "127.0.0.1", srv.port, dims=srv.dims, types=srv.types,
            arrivals=arrivals,
            make_frame=make_frame,
            p99_budget_ms=p99_budget_ms,
            depth_probe=srv.depth_probe, trace=trace)
        report["pattern"] = pattern
        report["load_x"] = load_x
        report["service_ms"] = service_ms
        report["capacity_rps"] = round(srv.capacity_rps, 1)
        report["server_crashed"] = srv.crashed()
        report["admission"] = srv.admission_counters()
        report["seed"] = int(seed)
        report["schedule"] = {
            "kind": "echo", "pattern": pattern, "load_x": load_x,
            "n": n, "service_ms": service_ms,
            "max_pending": max_pending, "max_inflight": max_inflight,
            "shed_policy": shed_policy,
            "p99_budget_ms": p99_budget_ms, "trace": bool(trace)}
        return report
    finally:
        srv.stop()


def _arrivals_for(pattern: str, rate: float, n: int,
                  rng: np.random.Generator) -> np.ndarray:
    if pattern == "poisson":
        return poisson_arrivals(rate, n, rng)
    if pattern == "bursty":
        return bursty_arrivals(n, rate_high_hz=2 * rate,
                               rate_low_hz=max(rate / 4, 0.5), rng=rng)
    raise ValueError(f"pattern must be poisson|bursty, got {pattern!r}")


def conservation_ok(c: dict) -> bool:
    """The PR-9 invariants, checked over an admission counters()
    snapshot — they must hold exactly even across a worker kill."""
    return (c["offered"] == c["admitted"] + sum(c["rejected"].values())
            and c["admitted"] == c["replied"] + sum(c["shed"].values())
            + c["depth"] + c["inflight"])


def tenant_conservation_ok(c: dict) -> bool:
    """Per-class form of the invariants: each class's counters must
    close exactly on their own, AND the classes must sum back to the
    global counters — shed load can move between classes only through
    the books."""
    if not conservation_ok(c):
        return False
    classes = c.get("classes")
    if not classes:
        return True
    sums = {k: 0 for k in ("offered", "admitted", "replied",
                           "rejected", "shed", "depth", "inflight")}
    for st in classes.values():
        rej = sum(st["rejected"].values())
        shed = sum(st["shed"].values())
        if st["offered"] != st["admitted"] + rej:
            return False
        if st["admitted"] != (st["replied"] + shed
                              + st["depth"] + st["inflight"]):
            return False
        sums["offered"] += st["offered"]
        sums["admitted"] += st["admitted"]
        sums["replied"] += st["replied"]
        sums["rejected"] += rej
        sums["shed"] += shed
        sums["depth"] += st["depth"]
        sums["inflight"] += st["inflight"]
    return (sums["offered"] == c["offered"]
            and sums["admitted"] == c["admitted"]
            and sums["replied"] == c["replied"]
            and sums["rejected"] == sum(c["rejected"].values())
            and sums["shed"] == sum(c["shed"].values())
            and sums["depth"] == c["depth"]
            and sums["inflight"] == c["inflight"])


# -- autotune ramp drill -----------------------------------------------------

def run_autotune_ramp(*, ramp=(0.5, 1.0, 1.5, 2.0, 2.5),
                      n_per_step: int = 120, service_ms: float = 5.0,
                      static_max_pending: int = 64,
                      p99_budget_ms: Optional[float] = None,
                      tuned: bool = True, dry_run: bool = False,
                      tick_interval_s: float = 0.25,
                      cooldown_s: float = 0.5,
                      seed: int = 0) -> dict:
    """Open-loop ramp (default 0.5→2.5× capacity, one Poisson segment
    per step) against a bounded echo server, with or without the SLO
    autotuner (serving/autotune.py) closing the loop live.

    Both arms start from the same deliberately mis-set hand config — a
    ``max_pending`` deep enough that queue wait alone blows the p99
    budget under overload. ``tuned=False`` is the static baseline;
    ``tuned=True`` binds an AutoTuner to the server's live admission
    queue, which derives the Little's-law bound from the measured
    reply rate and shrinks the queue until the budget holds. Same
    arrival trace (same seed) either way, so the reports compare
    directly: the tuned arm's win is goodput (completions *within
    budget* per second), not raw throughput.

    The report carries the full audit: ``autotune`` (AutoTuner.stats()
    — every decision), ``conservation_after_apply`` (the admission
    conservation invariants re-checked immediately after every applied
    knob change, mid-flood), and ``conservation_final``."""
    rng = np.random.default_rng(seed)
    # reject-oldest: overload displaces the stalest queued request
    # (which gets a BUSY), and a live max_pending shrink sheds excess
    # entries as victims — every sent request still resolves, so the
    # zero-lost accounting holds through every knob change
    srv = EchoServer(service_ms=service_ms,
                     max_pending=static_max_pending,
                     shed_policy="reject-oldest")
    tuner = None
    try:
        segs = []
        t_off = 0.0
        for x in ramp:
            a = poisson_arrivals(x * srv.capacity_rps, n_per_step, rng) \
                + t_off
            t_off = float(a[-1])
            segs.append(a)
        arrivals = np.concatenate(segs)
        if p99_budget_ms is None:
            # ~18 service times: far less than the static queue's
            # worth of waiting (so the hand config visibly fails it)
            # but wide enough to absorb the drill's own service
            # jitter — the sleep-based echo service overshoots on a
            # loaded host, putting the latency tail at ~2x the median
            # independent of queue depth, and a budget under that
            # floor is unmeetable at any bound
            p99_budget_ms = 18.0 * service_ms
        conservation_after_apply: List[bool] = []
        applied: List[dict] = []
        if tuned:
            from nnstreamer_tpu.edge.query import QueryServer
            from nnstreamer_tpu.serving.autotune import AutoTuner, SLOSpec

            qsrv = QueryServer.get(srv.sid)
            adm = qsrv.frames

            def on_apply(rec):
                conservation_after_apply.append(
                    conservation_ok(adm.counters()))
                applied.append({"knob": rec["knob"], "old": rec["old"],
                                "new": rec["new"]})

            def on_victims(victims):
                for v in victims:
                    try:
                        qsrv.send_busy(v.meta.get("client_id"), v.pts,
                                       "bound_shrink")
                    except Exception:
                        log.warning("autotune victim BUSY failed",
                                    exc_info=True)

            tuner = AutoTuner(
                SLOSpec(p99_budget_ms=p99_budget_ms),
                admission=adm, interval_s=tick_interval_s,
                cooldown_s=cooldown_s, dry_run=dry_run,
                on_apply=on_apply, on_victims=on_victims).start()
        x0 = np.ones((8, 1), np.float32)
        report = run_open_loop(
            "127.0.0.1", srv.port, dims=srv.dims, types=srv.types,
            arrivals=arrivals,
            make_frame=lambda i: TensorBuffer.of(x0, pts=i),
            p99_budget_ms=p99_budget_ms,
            depth_probe=srv.depth_probe)
        if tuner is not None:
            tuner.stop()
            report["autotune"] = tuner.stats()
            report["audit"] = tuner.audit()
            report["conservation_after_apply"] = conservation_after_apply
            report["applied"] = applied
        report["conservation_final"] = conservation_ok(
            srv.admission_counters())
        report["admission"] = srv.admission_counters()
        report["ramp"] = [float(x) for x in ramp]
        report["capacity_rps"] = round(srv.capacity_rps, 1)
        report["service_ms"] = service_ms
        report["static_max_pending"] = static_max_pending
        report["tuned"] = bool(tuned)
        report["dry_run"] = bool(dry_run)
        report["server_crashed"] = srv.crashed()
        report["seed"] = int(seed)
        report["schedule"] = {
            "kind": "autotune_ramp", "ramp": [float(x) for x in ramp],
            "n_per_step": n_per_step, "service_ms": service_ms,
            "static_max_pending": static_max_pending,
            "p99_budget_ms": p99_budget_ms, "tuned": bool(tuned),
            "dry_run": bool(dry_run),
            "tick_interval_s": tick_interval_s,
            "cooldown_s": cooldown_s}
        return report
    finally:
        if tuner is not None:
            tuner.stop()
        srv.stop()


# -- multi-tenant harness ----------------------------------------------------

def merge_tenant_arrivals(schedules: Dict[str, np.ndarray]
                          ) -> "tuple[np.ndarray, List[str]]":
    """Merge per-tenant arrival schedules into one global timeline.
    Returns (arrivals, owner) where owner[i] is the tenant whose
    schedule produced arrival i — the pts→tenant map that lets one
    open-loop run stamp and account per tenant."""
    pairs: List[tuple] = []
    for name, times in schedules.items():
        pairs.extend((float(t), name) for t in times)
    pairs.sort()
    arrivals = np.asarray([t for t, _ in pairs])
    owner = [name for _, name in pairs]
    return arrivals, owner


def run_multitenant(*, tenants: Dict[str, dict],
                    n_per_tenant: Dict[str, int],
                    rate_hz: Dict[str, float],
                    workers: int = 2, service_ms: float = 10.0,
                    max_pending: int = 32,
                    shed_policy: str = "reject-oldest",
                    p99_budget_ms: float = 250.0, seed: int = 0,
                    drain_timeout_s: float = 15.0,
                    **pool_kwargs) -> dict:
    """One multi-tenant harness run: a worker POOL fronted by the WFQ
    admission queue (a TenantTable built from `tenants`), flooded by
    the merged per-tenant Poisson schedules in `rate_hz`/`n_per_tenant`.
    Every frame is stamped with its tenant meta; the report's
    ``groups`` partition the outcome per tenant, and ``conserved``
    checks the invariants per class AND summed.

    `tenants` maps name -> TenantClass kwargs (weight, deadline_ms,
    max_pending, model) — the same dict shape TenantTable.from_dict
    accepts as its "tenants" entry.
    """
    from nnstreamer_tpu.serving.pool import PooledQueryServer
    from nnstreamer_tpu.serving.tenancy import TENANT_META, TenantTable

    rng = np.random.default_rng(seed)
    table = TenantTable.from_dict({"tenants": dict(tenants)})
    schedules = {
        name: poisson_arrivals(rate_hz[name], n_per_tenant[name], rng)
        for name in tenants if n_per_tenant.get(name, 0) > 0}
    if not schedules:
        raise ValueError("no tenant has a nonzero request count")
    arrivals, owner = merge_tenant_arrivals(schedules)

    pqs = PooledQueryServer.echo(
        workers=workers, service_ms=service_ms,
        max_pending=max_pending, shed_policy=shed_policy,
        tenants=table, **pool_kwargs)
    try:
        x = np.ones((8, 1), np.float32)

        def make_frame(i):
            return TensorBuffer.of(x, pts=i).with_meta(
                **{TENANT_META: owner[i]})

        report = run_open_loop(
            "127.0.0.1", pqs.port, dims=pqs.pool.spec.dims,
            types=pqs.pool.spec.types, arrivals=arrivals,
            make_frame=make_frame, p99_budget_ms=p99_budget_ms,
            drain_timeout_s=drain_timeout_s,
            depth_probe=pqs.depth_probe,
            group_of=lambda i: owner[i])
        c = pqs.admission_counters()
        report.update({
            "service_ms": service_ms, "workers": workers,
            "capacity_rps": round(pqs.capacity_rps, 1),
            "seed": int(seed),
            "tenants": {name: {"rate_hz": rate_hz.get(name),
                               "n": n_per_tenant.get(name, 0)}
                        for name in tenants},
            "conserved": tenant_conservation_ok(c),
            "admission": c,
            "schedule": {
                "kind": "multitenant",
                "tenants": {k: dict(v) for k, v in tenants.items()},
                "n_per_tenant": dict(n_per_tenant),
                "rate_hz": {k: float(v) for k, v in rate_hz.items()},
                "workers": workers, "service_ms": service_ms,
                "max_pending": max_pending,
                "shed_policy": shed_policy,
                "p99_budget_ms": p99_budget_ms},
        })
        return report
    finally:
        pqs.close()


def noisy_neighbor_drill(*, victim_weight: float = 1.0,
                         flood_weight: float = 1.0,
                         victim_x: float = 0.5, flood_x: float = 3.0,
                         n_victim: int = 120,
                         workers: int = 2, service_ms: float = 10.0,
                         max_pending: int = 32,
                         deadline_ms: Optional[float] = None,
                         seed: int = 0, **kw) -> dict:
    """The noisy-neighbor acceptance drill: tenant ``flood`` offers
    `flood_x` × its fair share while ``victim`` stays at `victim_x` ×
    its own. Two runs — the victim alone (baseline), then contested —
    and the verdict is the contested/solo goodput ratio: weighted-fair
    admission must keep the victim's goodput and p99 where they were,
    with the overage shed FROM THE FLOODER (cause tenant_over_share).

    Returns {solo, contested, victim_goodput_ratio, victim_p99_ms,
    victim_p99_budget_ms, conserved, zero_lost}.
    """
    capacity = workers * 1e3 / service_ms
    total_w = victim_weight + flood_weight
    victim_share = capacity * victim_weight / total_w
    flood_share = capacity * flood_weight / total_w
    victim_rate = victim_x * victim_share
    flood_rate = flood_x * flood_share
    # matched send windows: the flooder floods for as long as the
    # victim is offering, so contention covers the whole run
    n_flood = max(1, int(round(
        n_victim / victim_rate * flood_rate)))
    if deadline_ms is None:
        # a full fair-share queue's worth of waiting + one service time
        deadline_ms = (max_pending + 2) * service_ms
    tenants = {
        "victim": {"weight": victim_weight, "deadline_ms": deadline_ms},
        "flood": {"weight": flood_weight, "deadline_ms": deadline_ms},
    }

    solo = run_multitenant(
        tenants=tenants,
        n_per_tenant={"victim": n_victim, "flood": 0},
        rate_hz={"victim": victim_rate, "flood": flood_rate},
        workers=workers, service_ms=service_ms,
        max_pending=max_pending, p99_budget_ms=deadline_ms,
        seed=seed, **kw)
    contested = run_multitenant(
        tenants=tenants,
        n_per_tenant={"victim": n_victim, "flood": n_flood},
        rate_hz={"victim": victim_rate, "flood": flood_rate},
        workers=workers, service_ms=service_ms,
        max_pending=max_pending, p99_budget_ms=deadline_ms,
        seed=seed, **kw)

    v_solo = solo["groups"]["victim"]
    v_cont = contested["groups"]["victim"]
    ratio = (v_cont["goodput_rps"] / v_solo["goodput_rps"]
             if v_solo["goodput_rps"] else 0.0)
    return {
        "solo": solo,
        "contested": contested,
        "victim_goodput_ratio": round(ratio, 3),
        "victim_p99_ms": v_cont.get("latency_ms", {}).get("p99"),
        "victim_p99_budget_ms": deadline_ms,
        "conserved": bool(solo["conserved"] and contested["conserved"]),
        "zero_lost": solo["lost"] == 0 and contested["lost"] == 0,
    }


def schedule_worker_kills(pool, *, workers: int,
                          rng: np.random.Generator,
                          kill_at_s: float, kills: int,
                          stagger_s: float = 0.25
                          ) -> "tuple[List[dict], List[threading.Timer]]":
    """Fault-injector primitive: plan `kills` SIGKILLs of rng-chosen
    workers starting at `kill_at_s` (staggered by `stagger_s`). Returns
    (schedule, timers); the caller starts the timers when its clock
    starts and cancels them when the run ends. Each schedule entry's
    ``pid`` is filled in when its kill actually lands, so the executed
    schedule is the replay record. Shared by `run_against_pool` and the
    scenario executor (scenario/executor.py)."""
    schedule: List[dict] = []
    timers: List[threading.Timer] = []
    for k in range(max(0, kills)):
        t_k = kill_at_s + k * stagger_s
        wid = int(rng.integers(0, workers))
        entry = {"t_s": round(t_k, 3), "wid": wid, "pid": None}
        schedule.append(entry)

        def do_kill(entry=entry):
            # the chosen slot may be dead/restarting already: fall
            # back to any live worker so the kill still happens
            pid = pool.kill_worker(entry["wid"])
            if pid is None:
                pid = pool.kill_worker(None)
            entry["pid"] = pid

        t = threading.Timer(t_k, do_kill)
        # cancelled by the caller; daemon besides, so an exception
        # between here and start() can't hang exit
        t.daemon = True
        timers.append(t)
    return schedule, timers


def run_against_pool(*, pattern: str = "poisson", load_x: float = 1.5,
                     n: int = 300, service_ms: float = 20.0,
                     workers: int = 2, max_pending: int = 32,
                     max_inflight: int = 0,
                     shed_policy: str = "reject-newest",
                     p99_budget_ms: float = 90.0, seed: int = 0,
                     kill_at_s: Optional[float] = None, kills: int = 1,
                     recovery_timeout_s: Optional[float] = None,
                     trace: bool = False,
                     **pool_kwargs) -> dict:
    """Chaos-kill harness run: open-loop load at `load_x` × a worker
    POOL's aggregate capacity, with `kills` SIGKILLs of rng-chosen
    workers at `kill_at_s` (default: the median arrival — mid-flood,
    where a lost worker hurts most). The run is reproducible from
    (seed, kill schedule), both recorded in the report.

    The report adds to run_open_loop's fields: `kill_schedule` (planned
    t / wid / pid actually signalled), `recovered` (pool back to full
    non-disabled capacity within `recovery_timeout_s` — default sized
    to the restart budget), `conserved` (admission invariants exact),
    and `orphans` (live pids left after close() — must be empty).
    """
    from nnstreamer_tpu.serving.pool import PooledQueryServer, proc_alive

    rng = np.random.default_rng(seed)
    pqs = PooledQueryServer.echo(
        workers=workers, service_ms=service_ms, max_pending=max_pending,
        max_inflight=max_inflight, shed_policy=shed_policy,
        **pool_kwargs)
    pool = pqs.pool
    closed = False
    try:
        rate = load_x * pqs.capacity_rps
        arrivals = _arrivals_for(pattern, rate, n, rng)
        if kill_at_s is None:
            kill_at_s = float(arrivals[len(arrivals) // 2])
        schedule, timers = schedule_worker_kills(
            pool, workers=workers, rng=rng, kill_at_s=kill_at_s,
            kills=kills)

        x = np.ones((8, 1), np.float32)
        for t in timers:
            t.start()
        try:
            report = run_open_loop(
                "127.0.0.1", pqs.port, dims=pool.spec.dims,
                types=pool.spec.types, arrivals=arrivals,
                make_frame=lambda i: TensorBuffer.of(x, pts=i),
                p99_budget_ms=p99_budget_ms,
                depth_probe=pqs.depth_probe, trace=trace)
        finally:
            for t in timers:
                t.cancel()
        # recovery: back to full non-disabled capacity within the
        # restart budget's worth of backoff (+ margin for respawn)
        if recovery_timeout_s is None:
            recovery_timeout_s = max(
                5.0, 2 * pool.restart_backoff_max_s + 5.0)
        t_rec = time.perf_counter()
        recovered = pool.wait_ready(recovery_timeout_s)
        c = pqs.admission_counters()
        report.update({
            "pattern": pattern, "load_x": load_x,
            "service_ms": service_ms, "workers": workers,
            "capacity_rps": round(pqs.capacity_rps, 1),
            "seed": int(seed),
            "schedule": {
                "kind": "pool", "pattern": pattern, "load_x": load_x,
                "n": n, "service_ms": service_ms, "workers": workers,
                "max_pending": max_pending,
                "max_inflight": max_inflight,
                "shed_policy": shed_policy,
                "p99_budget_ms": p99_budget_ms,
                "kill_at_s": round(float(kill_at_s), 3),
                "kills": kills, "trace": bool(trace)},
            "kill_schedule": schedule,
            "recovered": recovered,
            "recovery_s": round(time.perf_counter() - t_rec, 3),
            "conserved": conservation_ok(c),
            "admission": c,
            "pool": pool.stats(),
        })
        # orphan audit must run AFTER close(): a drained pool may leave
        # no live child — a pid still alive here is a leaked orphan
        all_pids = pool.all_pids_ever()
        pqs.close()
        closed = True
        report["orphans"] = [p for p in all_pids if proc_alive(p)]
        return report
    finally:
        if not closed:
            pqs.close()


#: query-server ids the mesh harness burns through (the registry is
#: process-wide; a crashed run must not leave a stale id in the way)
_mesh_sids = None


def _next_mesh_sid() -> int:
    global _mesh_sids
    if _mesh_sids is None:
        import itertools

        _mesh_sids = itertools.count(9500)
    return next(_mesh_sids)


class MeshWorld:
    """A live multi-host mesh fixture: a `MeshRouter` fronting `hosts`
    subprocess worker pools joined by `HostAgent`s, with a seeded
    `ChaosProxy` inserted in front of every host index in
    `proxy_hosts`. The build/teardown half of `run_against_mesh`,
    extracted so the scenario executor (scenario/executor.py) can
    compose its own fault programs against the same world. Drive
    traffic at ``world.router.port``; call `all_pids()` BEFORE
    `close()` to feed the post-close orphan audit."""

    def __init__(self, *, hosts: int, workers_per_host: int = 1,
                 service_ms: float = 20.0, max_pending: int = 64,
                 lease_s: float = 1.0, max_redeliver: int = 2,
                 seed: int = 0, proxy_hosts=(),
                 dims: str = "8:1", types: str = "float32",
                 connect_timeout_s: float = 2.0,
                 wait_timeout_s: float = 10.0,
                 trace_hosts: bool = False, **mesh_kwargs):
        from nnstreamer_tpu.runtime.tracing import Tracer
        from nnstreamer_tpu.serving.mesh import MeshRouter, pool_join
        from nnstreamer_tpu.serving.pool import PooledQueryServer
        from nnstreamer_tpu.traffic.netchaos import ChaosProxy

        if hosts < 1:
            raise ValueError(f"hosts must be >= 1, got {hosts}")
        self.hosts = hosts
        self.workers_per_host = workers_per_host
        self.service_ms = service_ms
        self.router = MeshRouter(
            sid=_next_mesh_sid(), dims=dims, types=types,
            max_pending=max_pending, lease_s=lease_s,
            max_redeliver=max_redeliver, **mesh_kwargs)
        self.pools: List = []
        self.agents: List = []
        self.proxies: Dict[int, "ChaosProxy"] = {}
        try:
            for k in range(hosts):
                # a traced host pool runs traced workers, which is what
                # puts worker_recv/worker_done on the reply hop chain
                # (tracing.REQUIRED_REPLY_HOPS) the scenario checker
                # audits — plain drills skip the decode cost
                pqs = PooledQueryServer.echo(
                    workers=workers_per_host, service_ms=service_ms,
                    sid=_next_mesh_sid(), max_pending=max_pending,
                    tracer=Tracer() if trace_hosts else None)
                self.pools.append(pqs)
                r_host, r_port = "127.0.0.1", self.router.port
                if k in proxy_hosts:
                    proxy = ChaosProxy("127.0.0.1", self.router.port,
                                       seed=seed)
                    self.proxies[k] = proxy
                    r_host, r_port = proxy.host, proxy.port
                self.agents.append(pool_join(
                    pqs, r_host, r_port, name=f"host{k}",
                    connect_timeout_s=connect_timeout_s))
            if not self.router.wait_hosts(hosts,
                                          timeout_s=wait_timeout_s):
                raise StreamError(
                    f"mesh harness: only {self.router.ready_hosts()}"
                    f"/{hosts} hosts registered")
        except Exception:
            self.close()
            raise

    @property
    def capacity_rps(self) -> float:
        return self.hosts * self.workers_per_host * 1e3 / self.service_ms

    def all_pids(self) -> List[int]:
        return [p for pqs in self.pools
                for p in pqs.pool.all_pids_ever()]

    def close(self) -> None:
        _mesh_teardown(self.agents, list(self.proxies.values()),
                       self.pools, self.router)
        self.agents, self.pools = [], []
        self.proxies = {}
        self.router = None


def run_against_mesh(*, hosts: int = 2, workers_per_host: int = 1,
                     pattern: str = "poisson", load_x: float = 1.5,
                     n: int = 300, service_ms: float = 20.0,
                     max_pending: int = 64,
                     p99_budget_ms: float = 250.0, seed: int = 0,
                     lease_s: float = 1.0, max_redeliver: int = 2,
                     blackhole_at_s: Optional[float] = None,
                     blackhole_host: Optional[int] = 0,
                     heal_after_s: Optional[float] = None,
                     drain_timeout_s: float = 15.0,
                     trace: bool = True,
                     **mesh_kwargs) -> dict:
    """Chaos-partition harness run: a `MeshRouter` fronting `hosts`
    worker-pool hosts (each a separate PR-10 subprocess pool joined by
    a `HostAgent`), flooded open-loop at `load_x` × the mesh's
    aggregate capacity while host `blackhole_host` is silently
    partitioned mid-flood through a netchaos proxy (set it to None for
    a fault-free run).

    The acceptance contract this encodes (ISSUE 12): zero lost —
    every request resolves as RESULT or typed BUSY; `conserved` — the
    router's two admission invariants hold exactly; the fence lands
    within the lease budget (`fence_detect_s`); and with ``trace=True``
    a cross-host redelivered frame keeps ONE trace_id whose dispatch
    hops show both hosts (`redelivered_examples`).

    With `heal_after_s`, the partition heals that many seconds after it
    starts and the report waits for the agent's rejoin
    (`rejoined`) — the full fence → re-offer → rejoin cycle.
    """
    from nnstreamer_tpu.serving.pool import proc_alive

    rng = np.random.default_rng(seed)
    world = MeshWorld(
        hosts=hosts, workers_per_host=workers_per_host,
        service_ms=service_ms, max_pending=max_pending,
        lease_s=lease_s, max_redeliver=max_redeliver, seed=seed,
        proxy_hosts=(() if blackhole_host is None
                     else (blackhole_host,)), **mesh_kwargs)
    router = world.router
    closed = False
    try:
        capacity = world.capacity_rps
        arrivals = _arrivals_for(pattern, load_x * capacity, n, rng)
        if blackhole_at_s is None:
            blackhole_at_s = float(arrivals[len(arrivals) // 2])
        proxy = world.proxies.get(blackhole_host) \
            if blackhole_host is not None else None
        t_prog = time.monotonic()
        if proxy is not None:
            # the partition is a scheduled ChaosProxy program, not
            # hand-rolled timers: the harness owns the clock instant
            # and the proxy's applied-event log is the ground truth
            events = [(blackhole_at_s, "blackhole")]
            if heal_after_s is not None:
                events.append((blackhole_at_s + heal_after_s, "heal"))
            proxy.program(events, t0=t_prog)

        x = np.ones((8, 1), np.float32)
        report = run_open_loop(
            "127.0.0.1", router.port, dims="8:1", types="float32",
            arrivals=arrivals,
            make_frame=lambda i: TensorBuffer.of(x, pts=i),
            p99_budget_ms=p99_budget_ms,
            drain_timeout_s=drain_timeout_s,
            depth_probe=router.depth_probe, trace=trace)
        c = router.admission_counters()
        stats = router.stats()
        report.update({
            "pattern": pattern, "load_x": load_x,
            "service_ms": service_ms, "hosts": hosts,
            "workers_per_host": workers_per_host,
            "capacity_rps": round(capacity, 1),
            "seed": int(seed),
            "schedule": {
                "kind": "mesh", "hosts": hosts,
                "workers_per_host": workers_per_host,
                "pattern": pattern, "load_x": load_x, "n": n,
                "service_ms": service_ms, "max_pending": max_pending,
                "p99_budget_ms": p99_budget_ms, "lease_s": lease_s,
                "max_redeliver": max_redeliver,
                "blackhole_at_s": (round(float(blackhole_at_s), 3)
                                   if blackhole_host is not None
                                   else None),
                "blackhole_host": blackhole_host,
                "heal_after_s": heal_after_s,
                "drain_timeout_s": drain_timeout_s,
                "trace": bool(trace)},
            "lease_s": lease_s,
            "conserved": conservation_ok(c),
            "admission": c,
            "mesh": stats,
            # every router reply maps to exactly one host reply: the
            # cross-host form of the conservation contract
            "perhost_replied_sum": sum(h["replied"]
                                       for h in stats["hosts"]),
        })
        t_bh = proxy.applied("blackhole") if proxy is not None else None
        if proxy is not None and t_bh is None:
            # flood drained before the partition was due: drop the
            # pending program so no surprise fault lands mid-teardown
            proxy.cancel_program()
        if t_bh is not None:
            fences = [e for e in router.events
                      if e[2] == "fence" and e[0] >= t_bh]
            detect_s = (fences[0][0] - t_bh) if fences else None
            report["blackhole_at_s"] = round(blackhole_at_s, 3)
            report["fence_detect_s"] = \
                round(detect_s, 3) if detect_s is not None else None
            # fenced within the lease budget (+ the supervisor's poll
            # cadence): the lease, not luck, found the silent host
            report["recovered"] = bool(
                fences and detect_s <= 2.0 * lease_s
                and report["lost"] == 0 and report["conserved"])
            if heal_after_s is not None:
                # the flood may drain early, but the program still
                # heals at the promised scenario-clock offset — wait
                # for its last event to land, then for the rejoin
                remaining = (t_prog + blackhole_at_s + heal_after_s) \
                    - time.monotonic()
                proxy.wait_program(max(0.0, remaining) + 10.0)
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline and \
                        router.ready_hosts() < hosts:
                    time.sleep(0.05)
                report["rejoined"] = router.ready_hosts() >= hosts
        # orphan audit must run AFTER close(): a pid still alive once
        # every pool drained is a leaked child
        all_pids = world.all_pids()
        world.close()
        closed = True
        report["orphans"] = [p for p in all_pids if proc_alive(p)]
        return report
    finally:
        if not closed:
            world.close()


def _mesh_teardown(agents, proxies, pools, router) -> None:
    for a in agents:
        a.stop()
    for p in proxies:
        p.close()
    for pqs in pools:
        pqs.close()
    if router is not None:
        router.close()


# -- replay ------------------------------------------------------------------

def replay_report(report: dict) -> dict:
    """Re-run the exact drill a ``run_*`` report records. Every runner
    stamps a top-level ``{"seed", "schedule"}`` block sufficient to
    reconstruct its run; this dispatches back into the runner with the
    recorded arguments. Same seed → same arrival trace and same
    planned fault schedule; a quiescent run (zero lost, fully drained)
    replays to the same offered/admitted/replied totals."""
    sched = report.get("schedule")
    seed = report.get("seed")
    if not isinstance(sched, dict) or "kind" not in sched \
            or seed is None:
        raise ValueError(
            "report carries no replayable {'seed', 'schedule'} block")
    kw = dict(sched)
    kind = kw.pop("kind")
    fn = _REPLAY_RUNNERS.get(kind)
    if fn is None:
        raise ValueError(
            f"unknown schedule kind {kind!r}; expected one of "
            f"{sorted(_REPLAY_RUNNERS)}")
    return fn(seed=int(seed), **kw)


_REPLAY_RUNNERS: Dict[str, Callable[..., dict]] = {
    "echo": run_against_echo,
    "autotune_ramp": run_autotune_ramp,
    "multitenant": run_multitenant,
    "pool": run_against_pool,
    "mesh": run_against_mesh,
}

#: pre-PR-19 private names, kept for the callers that grew up with them
_conservation_ok = conservation_ok
_tenant_conservation_ok = tenant_conservation_ok
