"""SingleShot — pipeline-less model invocation.

Reference parity: `GTensorFilterSingle` (gst/nnstreamer/tensor_filter/
tensor_filter_single.c, class hdr :67-82) — the object the ML C-API uses
to run one model without a pipeline: same backend open/info/invoke
protocol, no pads. This is the "model runner" surface for applications
that just want `invoke()`.

    runner = SingleShot(model="zoo://mobilenet_v2", framework="xla")
    out, = runner.invoke(frame)          # frame: np/jax array
    runner.set_fusion(pre=..., post=...) # optional fused chains
    runner.close()
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from nnstreamer_tpu.backends.base import get_backend
from nnstreamer_tpu.core.errors import BackendError, PipelineError
from nnstreamer_tpu.tensor.info import TensorsSpec


class SingleShot:
    def __init__(self, model: Any, framework: str = "xla",
                 accelerator: str = "", custom: str = "",
                 input_spec: Optional[TensorsSpec] = None):
        self.backend = get_backend(framework)
        self.backend.open({
            "model": model,
            "accelerator": accelerator,
            "custom": custom,
        })
        self._in_spec, self._out_spec = self.backend.get_model_info()
        if input_spec is not None:
            self.set_input_info(input_spec)
        elif self._in_spec is not None and self._out_spec is None:
            self._out_spec = self.backend.set_input_info(self._in_spec)

    # -- info (getTensorsInfo analogs) -------------------------------------
    @property
    def input_info(self) -> Optional[TensorsSpec]:
        return self._in_spec

    @property
    def output_info(self) -> Optional[TensorsSpec]:
        return self._out_spec

    def set_input_info(self, spec: TensorsSpec) -> TensorsSpec:
        """Reconfigure for a new input shape (setInputDimension analog)."""
        self._in_spec = spec
        self._out_spec = self.backend.set_input_info(spec)
        return self._out_spec

    def set_fusion(self, pre=None, post=None) -> None:
        """Fuse elementwise pre/post fns into the model computation."""
        absorbed = self.backend.fuse(pre, post)
        if not absorbed:
            raise BackendError(
                f"backend {type(self.backend).BACKEND_NAME!r} cannot fuse; "
                f"apply the chains manually around invoke()")

    # -- hot path ----------------------------------------------------------
    def invoke(self, *tensors) -> Tuple[Any, ...]:
        if self._in_spec is None and not tensors:
            raise PipelineError("invoke() needs at least one input tensor")
        return self.backend.invoke(tuple(tensors))

    def reload(self, model: Any) -> None:
        self.backend.reload(model)

    def close(self) -> None:
        self.backend.close()

    def __enter__(self) -> "SingleShot":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
